"""Unit tests for the ops layer against the NumPy oracle (SURVEY.md §4a)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from glom_tpu.ops import (
    build_local_mask,
    consensus_attention,
    grouped_ffw,
    init_grouped_ffw,
    patchify,
    unpatchify,
)
from glom_tpu.ops.ffw import GroupedFFWParams
from oracle_np import (
    np_consensus,
    np_grouped_ffw,
    np_local_mask,
    np_patchify,
    np_unpatchify,
)


def rand_ffw_params(rng, groups, dim, mult=4):
    hidden = dim * mult
    return {
        "w1": rng.normal(size=(groups, dim, hidden)) * 0.1,
        "b1": rng.normal(size=(groups, hidden)) * 0.1,
        "w2": rng.normal(size=(groups, hidden, dim)) * 0.1,
        "b2": rng.normal(size=(groups, dim)) * 0.1,
    }


def to_jax_ffw(p):
    return GroupedFFWParams(
        *(jnp.asarray(p[k], jnp.float32) for k in ("w1", "b1", "w2", "b2"))
    )


class TestGroupedFFW:
    def test_matches_oracle(self, rng):
        G, d = 5, 32
        p = rand_ffw_params(rng, G, d)
        x = rng.normal(size=(2, 7, G, d))
        got = grouped_ffw(to_jax_ffw(p), jnp.asarray(x, jnp.float32))
        want = np_grouped_ffw(p, x)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)

    def test_no_cross_group_mixing(self, rng):
        """Perturbing group g's input must not change any other group's output
        (the defining property of the reference's Conv1d-groups trick)."""
        G, d = 4, 16
        p = to_jax_ffw(rand_ffw_params(rng, G, d))
        x = jnp.asarray(rng.normal(size=(1, 3, G, d)), jnp.float32)
        base = grouped_ffw(p, x)
        x2 = x.at[:, :, 1, :].add(1.0)
        out2 = grouped_ffw(p, x2)
        others = [g for g in range(G) if g != 1]
        np.testing.assert_allclose(
            np.asarray(out2[:, :, others]), np.asarray(base[:, :, others]), atol=1e-6
        )
        assert not np.allclose(np.asarray(out2[:, :, 1]), np.asarray(base[:, :, 1]))

    def test_init_shapes(self):
        p = init_grouped_ffw(jax.random.PRNGKey(0), groups=6, dim=64, mult=4)
        assert p.w1.shape == (6, 64, 256)
        assert p.b1.shape == (6, 256)
        assert p.w2.shape == (6, 256, 64)
        assert p.b2.shape == (6, 64)


class TestConsensusAttention:
    @pytest.mark.parametrize("attend_self", [False, True])
    def test_matches_oracle(self, rng, attend_self):
        b, n, L, d = 2, 9, 3, 16
        x = rng.normal(size=(b, n, L, d))
        got = consensus_attention(
            jnp.asarray(x, jnp.float32), attend_self=attend_self
        )
        want = np_consensus(x, attend_self=attend_self)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)

    def test_local_mask_matches_oracle(self, rng):
        side, L, d = 4, 2, 8
        n = side * side
        mask = build_local_mask(side, radius=1.5)
        want_mask = np_local_mask(side, 1.5)
        np.testing.assert_array_equal(mask, want_mask)
        x = rng.normal(size=(1, n, L, d))
        got = consensus_attention(jnp.asarray(x, jnp.float32), local_mask=mask)
        want = np_consensus(x, local_mask=want_mask)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)

    def test_local_mask_zeroes_nonlocal_attention(self, rng):
        """Hard-masked (non-local) pairs must receive exactly zero attention
        weight, while the soft self mask must NOT zero the diagonal."""
        side, L, d = 3, 1, 4
        n = side * side
        mask = build_local_mask(side, radius=1.0)
        x = jnp.asarray(rng.normal(size=(1, n, L, d)), jnp.float32)
        # recompute attention weights the oracle way to probe them
        from glom_tpu.utils.helpers import TOKEN_ATTEND_SELF_VALUE, l2norm

        sim = jnp.einsum("bild,bjld->blij", x, l2norm(x)) * (d ** -0.5)
        sim = jnp.where(jnp.eye(n, dtype=bool)[None, None], TOKEN_ATTEND_SELF_VALUE, sim)
        sim = jnp.where(jnp.asarray(mask)[None, None], -jnp.finfo(jnp.float32).max, sim)
        attn = jax.nn.softmax(sim, axis=-1)
        attn = np.asarray(attn)[0, 0]
        assert np.all(attn[np.asarray(mask)] == 0.0)
        assert np.all(attn.diagonal() > 0.0)  # soft self penalty, not -inf

    def test_self_mask_is_soft_not_hard(self, rng):
        """-5e-4 vs -inf distinction: diagonal attention stays near-uniform
        magnitude, far from zero."""
        n, L, d = 6, 1, 8
        x = jnp.asarray(rng.normal(size=(1, n, L, d)) * 0.01, jnp.float32)
        out_masked = consensus_attention(x, attend_self=False)
        out_self = consensus_attention(x, attend_self=True)
        # With tiny inputs sims ~0, so -5e-4 barely changes the result.
        np.testing.assert_allclose(
            np.asarray(out_masked), np.asarray(out_self), atol=1e-3
        )

    def test_per_level_independence(self, rng):
        """Attention at level l must only read level l across columns."""
        b, n, L, d = 1, 5, 3, 8
        x = rng.normal(size=(b, n, L, d))
        base = np.asarray(consensus_attention(jnp.asarray(x, jnp.float32)))
        x2 = x.copy()
        x2[:, :, 2, :] += 1.0
        out2 = np.asarray(consensus_attention(jnp.asarray(x2, jnp.float32)))
        np.testing.assert_allclose(out2[:, :, :2], base[:, :, :2], atol=1e-6)


class TestPatchify:
    def test_roundtrip(self, rng):
        img = rng.normal(size=(2, 3, 16, 16))
        p = patchify(jnp.asarray(img, jnp.float32), 4)
        back = unpatchify(p, 4, 16)
        np.testing.assert_allclose(np.asarray(back), img, rtol=1e-6)

    def test_matches_oracle_ordering(self, rng):
        img = rng.normal(size=(1, 3, 8, 8))
        got = patchify(jnp.asarray(img, jnp.float32), 2)
        want = np_patchify(img, 2)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)
        back = np_unpatchify(want, 2, 8)
        np.testing.assert_allclose(back, img, rtol=1e-12)


def test_virtual_device_count():
    assert jax.device_count() == 8, "conftest must provide 8 virtual CPU devices"
