"""Tracing subsystem tests: spans, step-windowed XLA capture, HBM
accounting, the crash flight recorder (including the induced-crash
acceptance path: dump -> schema lint -> event ordering), and the
utils/profiling.py compat shim.

Deliberately host-side: every test here uses fake step functions / fake
devices / a monkeypatched jax.profiler, so the module adds no jit compiles
to the tier-1 budget and runs without a profiler backend — which is the
spans' and flight recorder's own contract.
"""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from glom_tpu.telemetry import schema
from glom_tpu.tracing.capture import TraceCapture, parse_trace_steps
from glom_tpu.tracing.flight import (
    FlightRecorder,
    dump_flight_recorder,
    observe_event,
    set_global_flight_recorder,
)
from glom_tpu.tracing.memory import (
    hbm_watermarks,
    memory_record,
    model_live_bytes_total,
)
from glom_tpu.tracing.spans import SpanAggregator, current_span, span


class ListWriter:
    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(rec)


@pytest.fixture(autouse=True)
def _clean_global_recorder():
    """No test may leak a global flight recorder into the rest of the
    suite (every sink in the process feeds it)."""
    yield
    set_global_flight_recorder(None)


class TestSpans:
    def test_span_emits_stamped_event(self):
        w = ListWriter()
        with span("host_data_next", writer=w, step=3):
            pass
        (rec,) = w.records
        assert rec["kind"] == "span"
        assert rec["name"] == "host_data_next"
        assert rec["dur_s"] >= 0
        assert rec["depth"] == 0
        assert rec["step"] == 3
        assert schema.validate_record(rec) == [], rec

    def test_span_nesting_tracks_parent_and_depth(self):
        w = ListWriter()
        with span("outer", writer=w):
            assert current_span() == "outer"
            with span("inner", writer=w):
                assert current_span() == "inner"
        assert current_span() is None
        inner, outer = w.records  # inner closes first
        assert inner["name"] == "inner"
        assert inner["parent"] == "outer"
        assert inner["depth"] == 1
        assert outer["depth"] == 0
        assert "parent" not in outer

    def test_span_reraises_and_still_records(self):
        agg = SpanAggregator()
        with pytest.raises(RuntimeError):
            with span("x", aggregator=agg):
                raise RuntimeError("boom")
        assert current_span() is None
        (rec,) = agg.records()
        assert rec["count"] == 1

    def test_aggregator_rollup_and_reset(self):
        agg = SpanAggregator()
        for dur in (0.01, 0.02, 0.03):
            agg.observe("host_step_dispatch", dur)
        agg.observe("host_data_next", 0.5)
        recs = agg.records(extra={"step": 7.0})
        by_name = {r["name"]: r for r in recs}
        d = by_name["host_step_dispatch"]
        assert d["count"] == 3
        assert d["dur_s"] == pytest.approx(0.06, abs=1e-6)
        assert d["max_ms"] == pytest.approx(30.0, abs=0.01)
        assert d["mean_ms"] == pytest.approx(20.0, abs=0.01)
        assert d["step"] == 7.0
        for r in recs:
            assert schema.validate_record(r) == [], r
        # drained: the next logging boundary starts fresh
        assert agg.records() == []


class FakeProfiler:
    """Stand-in for jax.profiler: records start/stop calls, no backend."""

    def __init__(self):
        self.calls = []

    def start_trace(self, log_dir):
        self.calls.append(("start", log_dir))

    def stop_trace(self):
        self.calls.append(("stop", None))

    class StepTraceAnnotation:
        def __init__(self, name, **kw):
            pass

        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False


@pytest.fixture
def fake_profiler(monkeypatch):
    import jax

    prof = FakeProfiler()
    monkeypatch.setattr(jax, "profiler", prof)
    return prof


class TestTraceCapture:
    def test_parse_specs(self):
        assert parse_trace_steps("3:5") == (3, 5)
        assert parse_trace_steps("7") == (7, 7)
        for bad in ("5:3", "-1:2", "a:b", "1:2:3", ""):
            with pytest.raises(ValueError):
                parse_trace_steps(bad)

    def test_window_opens_and_closes_at_bounds(self, fake_profiler):
        w = ListWriter()
        cap = TraceCapture.parse("2:4", "/tmp/tr", writer=w)
        seen = []
        for _ in range(7):
            with cap.unit() as i:
                seen.append((i, cap._active))
        assert seen == [
            (0, False), (1, False), (2, True), (3, True), (4, True),
            (5, False), (6, False),
        ]
        assert fake_profiler.calls == [("start", "/tmp/tr"), ("stop", None)]
        start, stop = w.records
        assert start["note"] == "xla-trace-start"
        assert start["first_step"] == 2
        assert stop["note"] == "xla-trace-stop"
        assert stop["steps_captured"] == 3
        assert stop["last_step"] == 4
        for r in w.records:
            assert schema.validate_record(r) == [], r

    def test_counter_spans_multiple_fit_calls(self, fake_profiler):
        # The CLI's checkpoint-span pattern: one capture across fit calls.
        cap = TraceCapture.parse("3:4", "/tmp/tr", writer=ListWriter())
        for _ in range(2):  # span 1: units 0,1
            with cap.unit():
                pass
        assert not fake_profiler.calls
        for _ in range(3):  # span 2: units 2,3,4 — window 3:4 inside it
            with cap.unit():
                pass
        assert fake_profiler.calls == [("start", "/tmp/tr"), ("stop", None)]

    def test_close_truncates_open_window(self, fake_profiler):
        w = ListWriter()
        cap = TraceCapture.parse("1:100", "/tmp/tr", writer=w)
        for _ in range(3):
            with cap.unit():
                pass
        assert cap._active
        cap.close()
        cap.close()  # idempotent
        assert fake_profiler.calls == [("start", "/tmp/tr"), ("stop", None)]
        assert w.records[-1]["reason"] == "truncated-by-close"
        # a closed capture never reopens
        with cap.unit():
            pass
        assert len(fake_profiler.calls) == 2


class FakeDevice:
    def __init__(self, stats):
        self._stats = stats

    def memory_stats(self):
        return self._stats


class TestMemory:
    STATS = {
        "bytes_in_use": 1100,
        "peak_bytes_in_use": 2000,
        "bytes_limit": 4000,
    }

    def test_watermarks_from_device_stats(self):
        wm = hbm_watermarks(FakeDevice(self.STATS))
        assert wm == {
            "hbm_bytes_in_use": 1100,
            "hbm_peak_bytes": 2000,
            "hbm_bytes_limit": 4000,
        }

    def test_no_stats_degrades_to_empty(self):
        assert hbm_watermarks(FakeDevice(None)) == {}
        assert memory_record(1000, device=FakeDevice(None)) == {}
        # CPU backend (the test platform) has no allocator stats either:
        # the probe the trainers install must stay a silent no-op there.
        assert memory_record(1000) == {}

    def test_drift_reconciles_against_model(self):
        rec = memory_record(1000, device=FakeDevice(self.STATS))
        assert rec["hbm_model_live_bytes"] == 1000
        assert rec["hbm_model_drift"] == pytest.approx(0.1)
        # no model -> watermarks only
        rec = memory_record(None, device=FakeDevice(self.STATS))
        assert "hbm_model_drift" not in rec
        assert rec["hbm_bytes_in_use"] == 1100

    def test_model_total_from_static_record(self):
        static = {
            "params_bytes_per_replica": 10,
            "grads_bytes_per_replica": 20,
            "opt_bytes_per_replica": 30,
            "comm_bytes_per_step": 999,  # not a tenant
        }
        assert model_live_bytes_total(static) == 60

    def test_raising_device_never_raises(self):
        class Broken:
            def memory_stats(self):
                raise RuntimeError("plugin wedged")

        assert memory_record(100, device=Broken()) == {}


def _step_rec(i):
    return schema.stamp({"step": float(i), "loss": 1.0 / (i + 1)},
                        kind="train_step")


class TestFlightRecorder:
    def test_ring_keeps_last_n_in_order(self, tmp_path):
        fr = FlightRecorder(tmp_path, capacity=5)
        for i in range(12):
            fr.observe(_step_rec(i))
        path = fr.dump("manual")
        lines = [json.loads(l) for l in open(path)]
        header, events = lines[0], lines[1:]
        assert header["kind"] == "note"
        assert header["trigger"] == "manual"
        assert header["n_events"] == 5
        assert [e["step"] for e in events] == [7.0, 8.0, 9.0, 10.0, 11.0]
        seqs = [e["flight_seq"] for e in events]
        assert seqs == sorted(seqs)
        assert schema.lint_stream(open(path)) == []

    def test_dump_skips_when_nothing_new(self, tmp_path):
        fr = FlightRecorder(tmp_path, capacity=4)
        fr.observe(_step_rec(0))
        assert fr.dump("one") is not None
        assert fr.dump("atexit") is None  # no new events since
        fr.observe(_step_rec(1))
        assert fr.dump("two") is not None
        assert len(fr.dumps) == 2

    def test_watchdog_down_triggers_dump(self, tmp_path):
        """The acceptance path: steps flow, the backend watchdog forces a
        'down' transition through the shared writer, and the dump holds
        the last N step + watchdog events in arrival order and passes the
        schema linter."""
        from glom_tpu.telemetry.watchdog import BackendWatchdog
        from glom_tpu.utils.metrics import MetricsWriter

        fr = FlightRecorder(tmp_path / "flight", capacity=8)
        set_global_flight_recorder(fr)
        writer = MetricsWriter(str(tmp_path / "m.jsonl"), echo=False)
        for i in range(4):
            writer.write({"step": float(i), "loss": 0.5})
        probes = iter([8, None])
        wd = BackendWatchdog(probe=lambda t: next(probes), writer=writer)
        assert wd.probe_once() == "up"
        assert wd.probe_once() == "down"
        assert len(fr.dumps) == 1, "down transition must dump exactly once"
        lines = [json.loads(l) for l in open(fr.dumps[0])]
        assert lines[0]["trigger"] == "backend-down"
        kinds = [l["kind"] for l in lines[1:]]
        assert kinds == ["train_step"] * 4 + ["watchdog"] * 2
        assert [l["backend_state"] for l in lines[-2:]] == ["up", "down"]
        seqs = [l["flight_seq"] for l in lines[1:]]
        assert seqs == sorted(seqs)
        assert schema.lint_stream(open(fr.dumps[0])) == []

    def test_writerless_watchdog_feeds_global_recorder(self, tmp_path):
        from glom_tpu.telemetry.watchdog import BackendWatchdog

        fr = FlightRecorder(tmp_path, capacity=8)
        set_global_flight_recorder(fr)
        wd = BackendWatchdog(probe=lambda t: None)  # no writer
        wd.probe_once()
        assert len(fr.dumps) == 1  # unknown -> down dumps immediately

    def test_anomaly_storm_triggers_dump(self, tmp_path):
        t = [0.0]
        fr = FlightRecorder(
            tmp_path, capacity=16, storm_threshold=3, storm_window_s=60.0,
            clock=lambda: t[0],
        )
        anomaly = schema.stamp({"step": 1.0, "reason": "nonfinite"},
                               kind="anomaly")
        fr.observe(anomaly)
        t[0] += 100.0  # outside the window: the counter must have aged out
        fr.observe(anomaly)
        assert fr.dumps == []
        fr.observe(anomaly)
        fr.observe(anomaly)  # 3 inside one window -> storm
        assert len(fr.dumps) == 1
        header = json.loads(open(fr.dumps[0]).readline())
        assert header["trigger"] == "anomaly-storm"

    def test_observe_never_raises(self, tmp_path, monkeypatch):
        fr = FlightRecorder(tmp_path, capacity=2)
        monkeypatch.setattr(
            FlightRecorder, "dump",
            lambda self, *a, **k: (_ for _ in ()).throw(OSError("disk full")),
        )
        # trigger event with a broken dump: swallowed, run survives
        fr.observe(schema.stamp(
            {"backend_state": "down", "t": 1.0}, kind="watchdog"
        ))

    def test_global_helpers_are_noops_without_recorder(self):
        observe_event({"kind": "note", "note": "x"})
        assert dump_flight_recorder("whatever") is None

    def test_metrics_writer_and_emit_feed_global_recorder(self, tmp_path, capsys):
        from glom_tpu.telemetry.sinks import emit
        from glom_tpu.utils.metrics import MetricsWriter

        fr = FlightRecorder(tmp_path, capacity=8)
        set_global_flight_recorder(fr)
        w = MetricsWriter(str(tmp_path / "m.jsonl"), echo=False)
        w.write({"step": 0, "loss": 1.0})
        emit({"metric": "m", "value": 1.0, "unit": "u"})
        capsys.readouterr()
        path = fr.dump("check")
        kinds = [json.loads(l)["kind"] for l in open(path)][1:]
        assert kinds == ["train_step", "bench"]

    def test_fit_loop_exception_dumps_postmortem(self, tmp_path):
        """Induced crash inside fit_loop (acceptance criterion): the dump
        exists, names the exception, holds the preceding step records in
        order, and passes the schema linter; the exception re-raises."""
        from glom_tpu.train.trainer import fit_loop
        from glom_tpu.utils.metrics import MetricsWriter

        fr = FlightRecorder(tmp_path / "flight", capacity=16)
        set_global_flight_recorder(fr)
        writer = MetricsWriter(str(tmp_path / "m.jsonl"), echo=False)
        calls = [0]

        def fake_step(batch):
            calls[0] += 1
            if calls[0] == 4:
                raise RuntimeError("induced crash")
            return {"loss": 0.5, "step": float(calls[0] - 1)}

        def data():
            while True:
                yield None

        with pytest.raises(RuntimeError, match="induced crash"):
            fit_loop(fake_step, data(), 10, log_every=1,
                     metrics_writer=writer)
        assert len(fr.dumps) == 1
        lines = [json.loads(l) for l in open(fr.dumps[0])]
        header = lines[0]
        assert header["trigger"] == "fit-loop-exception"
        assert "RuntimeError: induced crash" in header["exception"]
        assert header["at_iteration"] == 3
        steps = [l["step"] for l in lines[1:] if l["kind"] == "train_step"]
        assert steps == [0.0, 1.0, 2.0]
        assert schema.lint_stream(open(fr.dumps[0])) == []

    def test_fit_loop_writerless_still_feeds_recorder(self, tmp_path):
        from glom_tpu.train.trainer import fit_loop

        fr = FlightRecorder(tmp_path, capacity=16)
        set_global_flight_recorder(fr)

        def fake_step(batch):
            return {"loss": 0.5, "step": 0.0}

        fit_loop(fake_step, iter(lambda: None, 1), 2, log_every=1)
        path = fr.dump("check")
        kinds = [json.loads(l)["kind"] for l in open(path)][1:]
        assert "train_step" in kinds and "span" in kinds

    def test_sigterm_hook_dumps(self, tmp_path):
        import os
        import signal

        fr = FlightRecorder(tmp_path, capacity=4)
        fr.observe(_step_rec(0))
        prev = signal.getsignal(signal.SIGTERM)
        try:
            fr.install_process_hooks(on_exit=False)
            with pytest.raises(SystemExit):
                os.kill(os.getpid(), signal.SIGTERM)
            assert len(fr.dumps) == 1
            assert json.loads(open(fr.dumps[0]).readline())["trigger"] == "sigterm"
        finally:
            signal.signal(signal.SIGTERM, prev)

    def test_sigterm_hook_preserves_ignored_disposition(self, tmp_path):
        # A host that set SIG_IGN must stay alive through SIGTERM — the
        # hook dumps and returns instead of converting ignore into exit.
        import os
        import signal

        fr = FlightRecorder(tmp_path, capacity=4)
        fr.observe(_step_rec(0))
        prev = signal.getsignal(signal.SIGTERM)
        try:
            signal.signal(signal.SIGTERM, signal.SIG_IGN)
            fr.install_process_hooks(on_exit=False)
            os.kill(os.getpid(), signal.SIGTERM)  # must NOT raise
            assert len(fr.dumps) == 1
        finally:
            signal.signal(signal.SIGTERM, prev)

    def test_capacity_validation(self, tmp_path):
        with pytest.raises(ValueError):
            FlightRecorder(tmp_path, capacity=0)
        with pytest.raises(ValueError):
            FlightRecorder(tmp_path, storm_threshold=0)


class TestFitLoopTracingHooks:
    """fit_loop's span/memory/trace plumbing on a fake step — no compiles."""

    def _data(self):
        while True:
            yield None

    def test_logging_records_carry_spans_and_memory(self, tmp_path):
        from glom_tpu.train.trainer import fit_loop
        from glom_tpu.utils.metrics import MetricsWriter

        path = tmp_path / "m.jsonl"
        writer = MetricsWriter(str(path), echo=False)
        n = [0]

        def fake_step(batch):
            n[0] += 1
            return {"loss": 1.0, "step": float(n[0] - 1)}

        probe = lambda: {"hbm_bytes_in_use": 123, "hbm_model_drift": 0.01}
        history = fit_loop(
            fake_step, self._data(), 4, log_every=2,
            metrics_writer=writer, memory_probe=probe,
        )
        assert all(r["hbm_bytes_in_use"] == 123 for r in history)
        recs = [json.loads(l) for l in path.read_text().splitlines()]
        span_recs = [r for r in recs if r["kind"] == "span"]
        names = {r["name"] for r in span_recs}
        assert {"host_data_next", "host_step_dispatch", "host_log_fetch"} <= names
        # two logging boundaries -> each phase drained twice
        assert sum(r["name"] == "host_data_next" for r in span_recs) == 2
        # the rollup covers every step since the previous boundary
        first = next(r for r in span_recs if r["name"] == "host_data_next")
        assert first["count"] == 2
        for r in recs:
            assert schema.validate_record(r) == [], r
        # history itself stays homogeneous train_step records
        assert all(r["kind"] == "train_step" for r in history)

    def test_trace_capture_advances_per_step(self, fake_profiler, tmp_path):
        from glom_tpu.train.trainer import fit_loop

        cap = TraceCapture.parse("1:2", "/tmp/tr", writer=ListWriter())
        fit_loop(lambda b: {"loss": 1.0, "step": 0.0}, self._data(), 4,
                 log_every=4, trace_capture=cap)
        assert fake_profiler.calls == [("start", "/tmp/tr"), ("stop", None)]
        assert cap._count == 4


class TestProfilingShim:
    def test_reexports_are_the_tracing_objects(self):
        from glom_tpu import tracing
        from glom_tpu.utils import profiling

        assert profiling.trace is tracing.capture.trace
        assert profiling.start_server is tracing.capture.start_server
        assert profiling.annotate is tracing.capture.annotate
        assert profiling.perf_report is tracing.report.perf_report
        assert profiling.StepTimer is tracing.report.StepTimer

    def test_trace_context_manager_drives_profiler(self, fake_profiler):
        from glom_tpu.utils.profiling import trace

        with trace("/tmp/shimtrace") as d:
            assert d == "/tmp/shimtrace"
        assert fake_profiler.calls == [("start", "/tmp/shimtrace"),
                                       ("stop", None)]
        # stop must run on exception too (no leaked profiler session)
        with pytest.raises(RuntimeError):
            with trace("/tmp/shimtrace2"):
                raise RuntimeError("boom")
        assert fake_profiler.calls[-1] == ("stop", None)

    def test_perf_report_math(self):
        from glom_tpu.utils.config import GlomConfig
        from glom_tpu.utils.metrics import flops_per_column_iter, mfu
        from glom_tpu.utils.profiling import perf_report

        cfg = GlomConfig(dim=16, levels=3, image_size=8, patch_size=2)
        rep = perf_report(
            cfg, column_iters_per_sec=1000.0, chip="cpu", num_chips=2,
            backward=True,
        )
        assert rep["column_iters_per_sec_per_chip"] == 500.0
        assert rep["flops_per_column_iter"] == flops_per_column_iter(cfg)
        assert rep["mfu"] == mfu(cfg, 500.0, chip="cpu", backward=True)
        assert rep["num_chips"] == 2

    def test_step_timer_best(self):
        from glom_tpu.utils.profiling import StepTimer

        t = StepTimer()
        for _ in range(3):
            t.start()
            t.stop(sync_scalar=jnp.float32(1.0))
        assert len(t.history) == 3
        assert t.best == min(t.history)
        assert t.best >= 0


class TestHostSpanCoverage:
    """The last unattributed host-time sinks the ROADMAP named: checkpoint
    save/wait and the prefetch worker are span-covered via spans.spanned."""

    class Sink:
        def __init__(self):
            self.records = []

        def write(self, rec):
            self.records.append(rec)

    def test_checkpoint_save_and_wait_emit_spans(self, tmp_path):
        from glom_tpu.telemetry import schema
        from glom_tpu.utils.checkpoint import CheckpointManager, abstract_like

        sink = self.Sink()
        mgr = CheckpointManager(
            str(tmp_path / "ckpt"), async_save=False, metrics_writer=sink
        )
        state = {"w": jnp.arange(4.0)}
        mgr.save(0, state)
        mgr.wait()
        names = [r.get("name") for r in sink.records]
        assert "host_checkpoint_save" in names
        assert "host_checkpoint_wait" in names
        for r in sink.records:
            assert r["kind"] == "span"
            assert schema.validate_record(r) == [], r
        # The spanned wrapper must not break the return contract.
        step, restored = mgr.restore(abstract_state=abstract_like(state))
        assert step == 0
        np.testing.assert_allclose(restored["w"], np.arange(4.0))

    def test_checkpoint_spans_feed_flight_ring_without_writer(self, tmp_path):
        from glom_tpu.tracing.flight import (
            FlightRecorder,
            set_global_flight_recorder,
        )
        from glom_tpu.utils.checkpoint import CheckpointManager

        fr = FlightRecorder(str(tmp_path / "fl"), capacity=16)
        set_global_flight_recorder(fr)
        try:
            mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
            mgr.save(0, {"w": jnp.zeros(2)})
            mgr.wait()
        finally:
            set_global_flight_recorder(None)
        names = [r.get("name") for r in fr._buf]
        assert "host_checkpoint_save" in names

    def test_prefetch_worker_emits_span_rollups(self):
        from glom_tpu.data.prefetch import prefetch_to_device
        from glom_tpu.telemetry import schema

        sink = self.Sink()
        data = iter(np.ones((2, 3), np.float32) for _ in range(4))
        out = list(prefetch_to_device(data, size=2, metrics_writer=sink))
        assert len(out) == 4
        spans = [r for r in sink.records if r.get("kind") == "span"]
        names = {r["name"] for r in spans}
        assert "host_prefetch_stage" in names
        assert "host_prefetch_next" in names
        for r in spans:
            assert r.get("source") == "prefetch_to_device"
            assert schema.validate_record(r) == [], r
        stage = next(r for r in spans if r["name"] == "host_prefetch_stage")
        assert stage["count"] == 4

    def test_prefetch_spans_drain_on_early_drop(self):
        from glom_tpu.data.prefetch import prefetch_to_device

        sink = self.Sink()
        data = iter(np.zeros(2) for _ in range(100))
        it = prefetch_to_device(data, size=2, metrics_writer=sink)
        next(it)
        it.close()  # consumer walks away mid-stream
        assert any(
            r.get("name") == "host_prefetch_stage" for r in sink.records
        )
