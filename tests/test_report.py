"""tracing/report.py — the MFU rollup and the rolling step timer (the
last tracing module with zero direct coverage). Host-only: fake clocks,
no device, no compiles; stays in tier-1."""

import itertools

import pytest

from glom_tpu.tracing.report import StepTimer, perf_report
from glom_tpu.utils.config import GlomConfig
from glom_tpu.utils.metrics import PEAK_FLOPS, flops_per_column_iter, mfu

CFG = GlomConfig(dim=16, levels=3, image_size=8, patch_size=4)


class TestPerfReport:
    def test_fields_and_values(self):
        r = perf_report(CFG, column_iters_per_sec=100.0, chip="cpu")
        assert r["chip"] == "cpu"
        assert r["num_chips"] == 1
        assert r["column_iters_per_sec_per_chip"] == 100.0
        assert r["flops_per_column_iter"] == flops_per_column_iter(CFG)
        assert r["mfu"] == pytest.approx(
            100.0 * flops_per_column_iter(CFG) / PEAK_FLOPS["cpu"]
        )
        assert r["mfu"] > 0

    def test_multi_chip_divides_the_rate(self):
        r1 = perf_report(CFG, column_iters_per_sec=800.0, chip="v5e")
        r8 = perf_report(
            CFG, column_iters_per_sec=800.0, chip="v5e", num_chips=8
        )
        assert r8["num_chips"] == 8
        assert r8["column_iters_per_sec_per_chip"] == pytest.approx(
            r1["column_iters_per_sec_per_chip"] / 8
        )
        # per-chip MFU scales the same way: 8 chips at the same aggregate
        # rate each do 1/8 of the work
        assert r8["mfu"] == pytest.approx(r1["mfu"] / 8)

    def test_backward_costs_three_x(self):
        fwd = perf_report(CFG, column_iters_per_sec=100.0, chip="v5e")
        bwd = perf_report(
            CFG, column_iters_per_sec=100.0, chip="v5e", backward=True
        )
        assert bwd["mfu"] == pytest.approx(3.0 * fwd["mfu"])
        # consistency with the metrics-layer definition it wraps
        assert bwd["mfu"] == pytest.approx(
            mfu(CFG, 100.0, chip="v5e", backward=True)
        )


class TestStepTimer:
    def test_measures_between_start_and_stop(self, monkeypatch):
        ticks = itertools.count(start=10.0, step=0.25)
        monkeypatch.setattr("time.perf_counter", lambda: next(ticks))
        t = StepTimer()
        t.start()  # 10.0
        dt = t.stop()  # 10.25
        assert dt == pytest.approx(0.25)
        assert t.history == [dt]

    def test_best_is_the_minimum(self, monkeypatch):
        clock = iter([0.0, 1.0, 1.0, 1.5, 1.5, 5.5])
        monkeypatch.setattr("time.perf_counter", lambda: next(clock))
        t = StepTimer()
        for _ in range(3):
            t.start()
            t.stop()
        assert t.history == pytest.approx([1.0, 0.5, 4.0])
        assert t.best == pytest.approx(0.5)

    def test_sync_scalar_is_fetched_before_the_clock_reads(self):
        """The timer's whole point: float(sync_scalar) forces the host
        fetch INSIDE the timed window, so the wall time includes the real
        device sync rather than timing an async dispatch."""
        order = []

        class Scalar:
            def __float__(self):
                order.append("sync")
                return 1.0

        t = StepTimer()
        t.start()
        dt = t.stop(sync_scalar=Scalar())
        order.append("stopped")
        assert order == ["sync", "stopped"]
        assert dt >= 0.0

    def test_stop_without_start_raises(self):
        t = StepTimer()
        with pytest.raises(TypeError):
            t.stop()
