"""Scratch: what matmul TF/s can this chip actually reach, and which grouped
formulation is fastest?"""

import sys
import time

import jax
import jax.numpy as jnp
from functools import partial

sys.path.insert(0, "/root/repo")


def timed(fn, *args, repeats=3):
    f = jax.jit(fn)
    warm = float(f(*args))
    assert warm == warm
    ts = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        float(f(*args))
        ts.append(time.perf_counter() - t0)
    return min(ts)


K_ITERS = 32


def report(name, dt, flops_per_app):
    per = dt / K_ITERS
    print(f"{name:28s}: {per*1e6:9.1f} us/app  {flops_per_app/per/1e12:6.1f} TF/s")


# 1) big square 2D matmul — achievable peak
for S in (4096, 8192):
    a = jax.random.normal(jax.random.PRNGKey(0), (S, S), jnp.bfloat16)
    b = jax.random.normal(jax.random.PRNGKey(1), (S, S), jnp.bfloat16)

    def sq(a0, b0):
        def body(_, c):
            c = jnp.dot(c, b0, preferred_element_type=jnp.float32).astype(jnp.bfloat16)
            return c * 1e-2
        out = jax.lax.fori_loop(0, K_ITERS, body, a0)
        return jnp.sum(out).astype(jnp.float32)

    dt = timed(sq, a, b)
    report(f"square {S}", dt, 2 * S**3)

# 2) grouped-FFW shaped: G=6, M=4096, K=512, N=2048, output fed back via :512
G, M, D, F = 6, 4096, 512, 2048
flops = 2 * G * M * D * F
x = jax.random.normal(jax.random.PRNGKey(2), (G, M, D), jnp.bfloat16)
w = jax.random.normal(jax.random.PRNGKey(3), (G, D, F), jnp.bfloat16)
w2 = jax.random.normal(jax.random.PRNGKey(4), (G, F, D), jnp.bfloat16)


def chain(step):
    def f(x0, w0, w20):
        def body(_, c):
            return step(c, w0, w20)
        out = jax.lax.fori_loop(0, K_ITERS, body, x0)
        return jnp.sum(out).astype(jnp.float32)
    return f


def einsum_pair(c, w0, w20):
    # round trip d->f->d so carry keeps shape and ALL flops count
    h = jnp.einsum("gmd,gdf->gmf", c, w0, preferred_element_type=jnp.float32)
    h = h.astype(jnp.bfloat16)
    o = jnp.einsum("gmf,gfd->gmd", h, w20, preferred_element_type=jnp.float32)
    return (o * 1e-3).astype(jnp.bfloat16)


def vmap_pair(c, w0, w20):
    def one(cg, wg, w2g):
        h = jnp.dot(cg, wg, preferred_element_type=jnp.float32).astype(jnp.bfloat16)
        return jnp.dot(h, w2g, preferred_element_type=jnp.float32)
    o = jax.vmap(one)(c, w0, w20)
    return (o * 1e-3).astype(jnp.bfloat16)


def unrolled_pair(c, w0, w20):
    outs = []
    for g in range(G):
        h = jnp.dot(c[g], w0[g], preferred_element_type=jnp.float32).astype(jnp.bfloat16)
        outs.append(jnp.dot(h, w20[g], preferred_element_type=jnp.float32))
    o = jnp.stack(outs)
    return (o * 1e-3).astype(jnp.bfloat16)


report("einsum grouped pair", timed(chain(einsum_pair), x, w, w2), 2 * flops)
report("vmap grouped pair", timed(chain(vmap_pair), x, w, w2), 2 * flops)
report("unrolled grouped pair", timed(chain(unrolled_pair), x, w, w2), 2 * flops)

# 3) pallas fused pair (existing kernel)
from glom_tpu.kernels.grouped_mlp import _fused_forward
from glom_tpu.ops.ffw import GroupedFFWParams

params = GroupedFFWParams(
    w1=w, b1=jnp.zeros((G, F), jnp.bfloat16),
    w2=w2, b2=jnp.zeros((G, D), jnp.bfloat16),
)


def pallas_pair(c, w0, w20):
    o = _fused_forward(params, c, tile_m=512, interpret=False)
    return (o * 1e-3).astype(jnp.bfloat16)


report("pallas fused pair", timed(chain(pallas_pair), x, w, w2), 2 * flops)

# 4) single big 2D matmul same total flops as grouped pair: [M, D] @ [D, G*F*2]?
# closer comparison: M=4096, K=512, N=2048 single (1/6 of grouped flops)
a = jax.random.normal(jax.random.PRNGKey(5), (M, D), jnp.bfloat16)
b = jax.random.normal(jax.random.PRNGKey(6), (D, F), jnp.bfloat16)
b2 = jax.random.normal(jax.random.PRNGKey(7), (F, D), jnp.bfloat16)


def single_pair(c, w0, w20):
    h = jnp.dot(c, w0, preferred_element_type=jnp.float32).astype(jnp.bfloat16)
    o = jnp.dot(h, w20, preferred_element_type=jnp.float32)
    return (o * 1e-3).astype(jnp.bfloat16)


report("single M4096 pair", timed(chain(single_pair), a, b, b2), 2 * 2 * M * D * F)

# 5) wide single: M=24576 (=G*M rows) x [512, 2048] shared weights
a = jax.random.normal(jax.random.PRNGKey(8), (G * M, D), jnp.bfloat16)


def wide_pair(c, w0, w20):
    h = jnp.dot(c, w0, preferred_element_type=jnp.float32).astype(jnp.bfloat16)
    o = jnp.dot(h, w20, preferred_element_type=jnp.float32)
    return (o * 1e-3).astype(jnp.bfloat16)


report("wide M24576 pair", timed(chain(wide_pair), a, b, b2), 2 * 2 * G * M * D * F)
