"""Regenerate the train-step profile trace (flagship config, scan_unroll)."""
import sys
sys.path.insert(0, "/root/repo")
import jax
import jax.numpy as jnp

from glom_tpu.train.trainer import create_train_state, make_train_step
from glom_tpu.utils.config import GlomConfig, TrainConfig

BATCH = 64  # matches the official bench_train.py config
cfg = GlomConfig(dim=512, levels=6, image_size=224, patch_size=14)
tcfg = TrainConfig(batch_size=BATCH, learning_rate=3e-4, compute_dtype="bfloat16",
                   use_pallas=True, scan_unroll=True)
state, optimizer = create_train_state(jax.random.PRNGKey(0), cfg, tcfg)
step_fn = jax.jit(
    make_train_step(cfg, tcfg, optimizer, with_grad_norm=False),
    donate_argnums=(0,),
)
img = jax.random.normal(jax.random.PRNGKey(1), (BATCH, 3, 224, 224), jnp.float32)
rng = jax.random.PRNGKey(2)

# warm/compile outside the trace
state, m = step_fn(state, img, rng)
print("warm loss:", float(m["loss"]))

out = sys.argv[1] if len(sys.argv) > 1 else "results/profiles/train_step"
with jax.profiler.trace(out):
    for i in range(3):
        state, m = step_fn(state, img, jax.random.fold_in(rng, i))
    print("traced loss:", float(m["loss"]))  # fetch = sync inside trace
print("trace written to", out)
