"""Slope-based forward timing: per-forward time = (t_long - t_short)/(k_long - k_short),
eliminating the ~106ms fixed dispatch overhead of the tunnel."""
import sys, time
import jax
import jax.numpy as jnp

sys.path.insert(0, "/root/repo")
from glom_tpu.models.core import glom_forward, init_glom
from glom_tpu.utils.config import GlomConfig
from glom_tpu.utils.metrics import mfu

cfg = GlomConfig(dim=512, levels=6, image_size=224, patch_size=14)
batch, iters = 16, 12
params = init_glom(jax.random.PRNGKey(0), cfg)
img = jax.random.normal(jax.random.PRNGKey(1), (batch, 3, 224, 224), jnp.float32)


def make_chain(k, use_pallas):
    def multi(p, x):
        def body(_, acc):
            out = glom_forward(p, x + acc * 0.0, cfg, iters=iters,
                               compute_dtype=jnp.bfloat16, use_pallas=use_pallas)
            return jnp.sum(out).astype(jnp.float32) * 1e-9
        return jax.lax.fori_loop(0, k, body, jnp.float32(0.0))
    return jax.jit(multi)


def t(f, repeats=4):
    warm = float(f(params, img)); assert warm == warm
    ts = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        float(f(params, img))
        ts.append(time.perf_counter() - t0)
    return min(ts)


for name, up in [("xla", False), ("pallas", True)]:
    k1, k2 = 8, 40
    t1 = t(make_chain(k1, up))
    t2 = t(make_chain(k2, up))
    per_fwd = (t2 - t1) / (k2 - k1)
    cis = batch * iters / per_fwd
    print(f"{name:8s}: t{k1}={t1*1e3:7.1f}ms t{k2}={t2*1e3:7.1f}ms "
          f"per_fwd={per_fwd*1e3:7.2f}ms col-iters/s={cis:9.1f} mfu={mfu(cfg, cis):.3f}")
