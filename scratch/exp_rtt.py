"""Measure the tunnel RTT floor: repeated fetch of an already-computed scalar,
and a trivial jitted scalar op."""
import sys, time
import jax
import jax.numpy as jnp

x = jnp.float32(1.5) + 1  # on device
times = []
for _ in range(10):
    t0 = time.perf_counter()
    float(x)
    times.append(time.perf_counter() - t0)
print("fetch existing scalar:", [f"{t*1e3:.1f}ms" for t in times])

f = jax.jit(lambda a: a * 2.0)
y = f(x); float(y)
times = []
for _ in range(10):
    t0 = time.perf_counter()
    float(f(x))
    times.append(time.perf_counter() - t0)
print("trivial jit + fetch  :", [f"{t*1e3:.1f}ms" for t in times])

# medium matmul, growing chain lengths -> slope = true per-iter time
a = jax.random.normal(jax.random.PRNGKey(0), (4096, 4096), jnp.bfloat16)
b = jax.random.normal(jax.random.PRNGKey(1), (4096, 4096), jnp.bfloat16)

def chain(k):
    def f(a0, b0):
        def body(_, c):
            return (jnp.dot(c, b0, preferred_element_type=jnp.float32) * 1e-2).astype(jnp.bfloat16)
        out = jax.lax.fori_loop(0, k, body, a0)
        return jnp.sum(out).astype(jnp.float32)
    return jax.jit(f)

for k in (8, 32, 128):
    fk = chain(k)
    float(fk(a, b))
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        float(fk(a, b))
        ts.append(time.perf_counter() - t0)
    dt = min(ts)
    print(f"chain {k:4d}: total {dt*1e3:8.1f} ms   per-iter {dt/k*1e6:8.1f} us")
