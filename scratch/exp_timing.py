"""Scratch experiment: time the XLA vs Pallas forward paths and the
component ops on the real chip. Not part of the package."""

import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, "/root/repo")

from glom_tpu.models.core import glom_forward, init_glom
from glom_tpu.ops.consensus import consensus_attention
from glom_tpu.ops.ffw import grouped_ffw
from glom_tpu.kernels import fused_grouped_ffw
from glom_tpu.utils.config import GlomConfig
from glom_tpu.utils.metrics import mfu

cfg = GlomConfig(dim=512, levels=6, image_size=224, patch_size=14)
batch, iters, chain = 16, 12, 8
params = init_glom(jax.random.PRNGKey(0), cfg)
img = jax.random.normal(jax.random.PRNGKey(1), (batch, 3, 224, 224), jnp.float32)


def timed(fn, *args, repeats=3):
    f = jax.jit(fn)
    warm = float(f(*args))  # compile+warm, sync via scalar fetch
    assert warm == warm, "nan"
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        float(f(*args))
        times.append(time.perf_counter() - t0)
    return min(times)


def fwd_chain(use_pallas):
    def multi(p, x):
        def body(_, acc):
            out = glom_forward(p, x + acc * 0.0, cfg, iters=iters,
                               compute_dtype=jnp.bfloat16, use_pallas=use_pallas)
            return jnp.sum(out).astype(jnp.float32) * 1e-9
        return jax.lax.fori_loop(0, chain, body, jnp.float32(0.0))
    return multi


for name, up in [("xla", False), ("pallas_ffw", True)]:
    dt = timed(fwd_chain(up), params, img)
    cis = batch * chain * iters / dt
    print(f"{name:12s}: {dt*1e3:8.2f} ms  {cis:8.1f} col-iters/s  mfu={mfu(cfg, cis):.3f}")

# ---- component timing: FFW alone (both impls), consensus alone ----
n, L, d = cfg.num_patches, cfg.levels, cfg.dim
x = jax.random.normal(jax.random.PRNGKey(2), (batch, n, L, d), jnp.bfloat16)
bu = jax.tree_util.tree_map(lambda t: t.astype(jnp.bfloat16), params.bottom_up)

K = iters * chain  # same number of applications as the full forward


def ffw_chain(impl):
    def f(p, x0):
        def body(_, carry):
            out = impl(p, carry)
            return out.astype(carry.dtype) * 0.5  # keep magnitudes bounded
        out = jax.lax.fori_loop(0, K, body, x0)
        return jnp.sum(out).astype(jnp.float32)
    return f


def cons_chain(x0):
    def body(_, carry):
        out = consensus_attention(carry)
        return out.astype(carry.dtype)
    out = jax.lax.fori_loop(0, K, body, x0)
    return jnp.sum(out).astype(jnp.float32)


dt_x = timed(ffw_chain(grouped_ffw), bu, x)
dt_p = timed(ffw_chain(fused_grouped_ffw), bu, x)
dt_c = timed(cons_chain, x)
print(f"ffw xla     : {dt_x*1e3:8.2f} ms total, {dt_x/K*1e6:8.1f} us/app")
print(f"ffw pallas  : {dt_p*1e3:8.2f} ms total, {dt_p/K*1e6:8.1f} us/app")
print(f"consensus   : {dt_c*1e3:8.2f} ms total, {dt_c/K*1e6:8.1f} us/app")

# matmul roofline check: same M,K,N as one grouped-FFW level pair
M = batch * n
a = jax.random.normal(jax.random.PRNGKey(3), (L, M, d), jnp.bfloat16)
w = jax.random.normal(jax.random.PRNGKey(4), (L, d, 4 * d), jnp.bfloat16)


def mm_chain(a0, w0):
    def body(_, carry):
        h = jnp.einsum("gmd,gdf->gmf", carry, w0, preferred_element_type=jnp.float32)
        return (h[..., :d] * 1e-3).astype(carry.dtype)
    out = jax.lax.fori_loop(0, K, body, a0)
    return jnp.sum(out).astype(jnp.float32)


dt_m = timed(mm_chain, a, w)
fl = 2 * L * M * d * 4 * d
print(f"bare matmul : {dt_m/K*1e6:8.1f} us/app  -> {fl/(dt_m/K)/1e12:6.1f} TF/s")
