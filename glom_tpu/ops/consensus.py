"""Consensus attention: same-level attention across all columns (patches).

Reference parity: ConsensusAttention (glom_pytorch/glom_pytorch.py:36-71).
Behavioral contract (every item is a reference subtlety — see tests):

  * No learned projections: attention is over the level embeddings themselves.
    q = levels (raw), k = L2-normalized levels, v = levels (raw). The k-only
    normalization makes the similarity cosine-like but asymmetric; the scale
    is still d^-1/2.                              (reference :56-58)
  * Per-level independence: sim[b, l, i, j] — each of the L levels runs its
    own attention over the n patch positions.     (reference :58)
  * Self mask (attend_self=False): the DIAGONAL similarity is REPLACED with
    the soft value -5e-4 (not -inf) — columns attend weakly to themselves.
                                                  (reference :9, :61-63)
  * Local mask (local_consensus_radius > 0): positions farther than `radius`
    in Euclidean patch-grid distance are hard-masked with -finfo.max.
    Two different fill semantics live in one op.   (reference :42-52, :65-67)

The dense form below materializes the [b, L, n, n] similarity — the simple,
always-correct baseline. The O(n)-memory blockwise/Pallas and ring-sharded
forms (glom_tpu.kernels / glom_tpu.parallel) are verified against this one.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from glom_tpu.utils.helpers import TOKEN_ATTEND_SELF_VALUE, l2norm, max_neg_value


def build_local_mask(num_patches_side: int, radius: float) -> Optional[np.ndarray]:
    """Static [n, n] boolean mask; True = NON-local pair (to be hard-masked).

    Mirrors the reference's init-time meshgrid -> cdist -> (dist > radius)
    buffer (reference :42-52). Built in numpy at trace time: it is a
    compile-time constant, never a traced value.
    """
    if radius <= 0:
        return None
    side = num_patches_side
    hs, ws = np.meshgrid(np.arange(side), np.arange(side), indexing="ij")
    coords = np.stack([hs, ws], axis=-1).reshape(-1, 2).astype(np.float64)
    dist = np.linalg.norm(coords[:, None, :] - coords[None, :, :], axis=-1)
    return dist > radius


def iota_local_mask(
    n: int, side: int, radius: float
) -> Optional[jnp.ndarray]:
    """In-graph [n, n] radius mask (True = non-local) from broadcasted
    iota — the device computes it inside the masking fusion, so no O(n^2)
    host numpy buffer is built at trace time or embedded as an executable
    constant (the reference's init-time meshgrid/cdist cost, reference
    :42-52, which build_local_mask reproduces host-side). Same contract as
    build_local_mask; used by the sharded paths where the mask would
    otherwise be re-materialized per shard."""
    if radius <= 0:
        return None
    idx = jnp.arange(n, dtype=jnp.int32)
    hi, wi = idx // side, idx % side
    dh = (hi[:, None] - hi[None, :]).astype(jnp.float32)
    dw = (wi[:, None] - wi[None, :]).astype(jnp.float32)
    return dh * dh + dw * dw > radius * radius


def consensus_attention(
    levels: jnp.ndarray,
    *,
    attend_self: bool = False,
    local_mask: Optional[np.ndarray] = None,
    side: Optional[int] = None,
    radius: float = 0.0,
    compute_dtype=None,
) -> jnp.ndarray:
    """Dense consensus attention.

    levels: [b, n, L, d]  ->  [b, n, L, d]
    local_mask: optional [n, n] bool, True = masked out (non-local).
    Alternatively pass (side, radius) to build the same mask in-graph from
    iota (no host [n, n] buffer — see iota_local_mask).
    """
    if compute_dtype is not None:
        levels = levels.astype(compute_dtype)
    b, n, L, d = levels.shape
    if local_mask is None and side is not None and radius > 0:
        local_mask = iota_local_mask(n, side, radius)
    q = levels
    k = l2norm(levels, axis=-1)
    v = levels

    scale = d ** -0.5
    sim = jnp.einsum("bild,bjld->blij", q, k, preferred_element_type=jnp.float32)
    sim = sim * scale

    if not attend_self:
        eye = jnp.eye(n, dtype=bool)
        sim = jnp.where(eye[None, None, :, :], TOKEN_ATTEND_SELF_VALUE, sim)

    if local_mask is not None:
        mask = jnp.asarray(local_mask)
        sim = jnp.where(mask[None, None, :, :], max_neg_value(sim.dtype), sim)

    attn = jax.nn.softmax(sim, axis=-1).astype(levels.dtype)

    out = jnp.einsum("blij,bjld->bild", attn, v, preferred_element_type=jnp.float32)
    return out.astype(levels.dtype)
