"""Patchify / unpatchify and the token embedding.

Reference parity: the `image_to_tokens` Sequential in Glom.__init__
(glom_pytorch/glom_pytorch.py:88-91):

    Rearrange('b c (h p1) (w p2) -> b (h w) (p1 p2 c)') ; Linear(p*p*c -> dim)

and the README's reconstruction head (`patches_to_images`): Linear(dim ->
p*p*c) + the inverse Rearrange (README :30-75, the denoise recipe).

Images are channel-first [b, c, H, W] to preserve the reference API surface.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from einops import rearrange


class LinearParams(NamedTuple):
    w: jnp.ndarray  # [in, out]
    b: jnp.ndarray  # [out]


def init_linear(key: jax.Array, d_in: int, d_out: int, dtype=jnp.float32) -> LinearParams:
    k1, k2 = jax.random.split(key)
    s = 1.0 / jnp.sqrt(d_in)
    return LinearParams(
        w=jax.random.uniform(k1, (d_in, d_out), dtype, -s, s),
        b=jax.random.uniform(k2, (d_out,), dtype, -s, s),
    )


def patchify(img: jnp.ndarray, patch_size: int) -> jnp.ndarray:
    """[b, c, H, W] -> [b, n, p*p*c] with n = (H/p)*(W/p).

    Patch-flattening order matches the reference's einops pattern
    'b c (h p1) (w p2) -> b (h w) (p1 p2 c)': within a patch, the channel
    axis is innermost.
    """
    p = patch_size
    return rearrange(img, "b c (h p1) (w p2) -> b (h w) (p1 p2 c)", p1=p, p2=p)


def unpatchify(patches: jnp.ndarray, patch_size: int, image_size: int) -> jnp.ndarray:
    """[b, n, p*p*c] -> [b, c, H, W]; exact inverse of `patchify`."""
    p = patch_size
    h = image_size // p
    return rearrange(
        patches, "b (h w) (p1 p2 c) -> b c (h p1) (w p2)", h=h, w=h, p1=p, p2=p
    )


def image_to_tokens(params: LinearParams, img: jnp.ndarray, patch_size: int) -> jnp.ndarray:
    """[b, c, H, W] -> [b, n, dim] token embedding."""
    x = patchify(img, patch_size)
    return x @ params.w + params.b


def tokens_to_image(
    params: LinearParams, tokens: jnp.ndarray, patch_size: int, image_size: int
) -> jnp.ndarray:
    """[b, n, dim] -> [b, c, H, W] reconstruction head (README denoise recipe)."""
    x = tokens @ params.w + params.b
    return unpatchify(x, patch_size, image_size)
