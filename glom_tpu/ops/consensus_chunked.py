"""Chunked (online-softmax) consensus attention — single-device long-context.

The dense op (ops/consensus.py) materializes [b, L, n, n]; at n = 4096
(e.g. 448px images with 7px patches) that is 1.6 GB per image-level in f32.
This variant scans over key/value chunks with a running (max, sumexp, out)
accumulator — flash-attention's recurrence — so memory is O(n * chunk)
while staying bitwise-faithful to the §3.2 contract:

  * k-only L2 normalization, d^-1/2 scale;
  * soft -5e-4 self mask (diagonal REPLACED, computed per chunk from global
    column indices);
  * hard -finfo.max local-radius mask (integer-exact squared distances).

Pure lax.scan: differentiable out of the box (autodiff of the scan
recomputes per-chunk under remat), portable to CPU/GPU, and XLA fuses each
chunk body. The ring form (parallel/ring.py) is the multi-chip analog of
the same recurrence; this one is the single-chip memory-scaling path.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import lax

from glom_tpu.utils.helpers import TOKEN_ATTEND_SELF_VALUE, l2norm

NEG_MAX = -jnp.finfo(jnp.float32).max


def chunked_consensus_attention(
    levels: jnp.ndarray,
    *,
    attend_self: bool = False,
    num_patches_side: Optional[int] = None,
    local_radius: float = 0.0,
    chunk_size: int = 512,
) -> jnp.ndarray:
    """[b, n, L, d] -> [b, n, L, d] without materializing the n x n matrix.

    `num_patches_side` is required when local_radius > 0 (grid geometry).
    n must be divisible by chunk_size (callers pick a divisor; n is a square
    of the patch grid side so powers of two are typically available).
    """
    b, n, L, d = levels.shape
    chunk = min(chunk_size, n)
    if n % chunk != 0:
        # Fall back to the dense op via its caller; keeping this function
        # total avoids silent wrong-shape behavior.
        raise ValueError(f"n={n} not divisible by chunk_size={chunk}")
    if local_radius > 0 and num_patches_side is None:
        raise ValueError("num_patches_side required when local_radius > 0")

    x32 = levels.astype(jnp.float32)
    q = x32  # [b, n, L, d]
    k = l2norm(x32, axis=-1)
    v = x32
    scale = d ** -0.5

    kc = k.reshape(b, n // chunk, chunk, L, d)
    vc = v.reshape(b, n // chunk, chunk, L, d)
    # scan over chunks: carry (m, s, o)
    idx_i = lax.iota(jnp.int32, n)[:, None]  # [n, 1] global query index

    def chunk_body(carry, inputs):
        m, s, o = carry
        c_idx, k_blk, v_blk = inputs  # k_blk: [b, chunk, L, d]
        sim = (
            jnp.einsum("bild,bjld->blij", q, k_blk, preferred_element_type=jnp.float32)
            * scale
        )  # [b, L, n, chunk]
        idx_j = c_idx * chunk + lax.iota(jnp.int32, chunk)[None, :]  # [1, chunk]
        if not attend_self:
            sim = jnp.where((idx_i == idx_j)[None, None], TOKEN_ATTEND_SELF_VALUE, sim)
        if local_radius > 0:
            side = num_patches_side
            ri, ci = idx_i // side, idx_i % side
            rj, cj = idx_j // side, idx_j % side
            dist2 = ((ri - rj) ** 2 + (ci - cj) ** 2).astype(jnp.float32)
            sim = jnp.where(
                (dist2 > local_radius * local_radius)[None, None], NEG_MAX, sim
            )
        blk_max = jnp.max(sim, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, blk_max)
        corr = jnp.exp(m - m_new)
        p = jnp.exp(sim - m_new)
        s_new = s * corr + jnp.sum(p, axis=-1, keepdims=True)
        o_new = o * corr + jnp.einsum(
            "blij,bjld->blid", p, v_blk, preferred_element_type=jnp.float32
        )
        return (m_new, s_new, o_new), None

    m0 = jnp.full((b, L, n, 1), NEG_MAX, jnp.float32)
    s0 = jnp.zeros((b, L, n, 1), jnp.float32)
    o0 = jnp.zeros((b, L, n, d), jnp.float32)
    chunk_ids = jnp.arange(n // chunk, dtype=jnp.int32)
    (m, s, o), _ = lax.scan(
        chunk_body,
        (m0, s0, o0),
        (chunk_ids, jnp.moveaxis(kc, 1, 0), jnp.moveaxis(vc, 1, 0)),
    )
    out = o / s  # [b, L, n, d]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(levels.dtype)
