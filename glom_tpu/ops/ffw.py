"""Grouped per-level feed-forward network.

Reference parity: GroupedFeedForward (glom_pytorch/glom_pytorch.py:21-34).
The reference implements "one independent d -> d*mult -> d MLP per level" via a
reshape + Conv1d(groups=L) trick. On TPU that trick is an anti-pattern (1x1
grouped convs map poorly onto the MXU); the idiomatic equivalent is a single
batched einsum over stacked per-level weight tensors:

    h   = gelu(einsum('...gd,gdf->...gf', x, w1) + b1)
    out =      einsum('...gf,gfd->...gd', h, w2) + b2

with weights [G, d, d*mult] / [G, d*mult, d]. This is bit-for-bit the same math
(each group g sees only its own slice — no cross-level mixing) but lets XLA
tile one large batched matmul onto the systolic array instead of L small ones.

Used twice by the model: bottom_up (groups = L) and top_down (groups = L-1).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class GroupedFFWParams(NamedTuple):
    """Per-group MLP weights. Leading axis = group (level)."""

    w1: jnp.ndarray  # [G, d, d*mult]
    b1: jnp.ndarray  # [G, d*mult]
    w2: jnp.ndarray  # [G, d*mult, d]
    b2: jnp.ndarray  # [G, d]


def init_grouped_ffw(
    key: jax.Array, groups: int, dim: int, mult: int = 4, dtype=jnp.float32
) -> GroupedFFWParams:
    """Fan-in-scaled uniform init (the same family as torch Conv1d's default:
    U(-1/sqrt(fan_in), 1/sqrt(fan_in)), where grouped-conv fan_in is the
    per-group channel count)."""
    k1, k2, k3, k4 = jax.random.split(key, 4)
    hidden = dim * mult
    s1 = 1.0 / jnp.sqrt(dim)
    s2 = 1.0 / jnp.sqrt(hidden)
    return GroupedFFWParams(
        w1=jax.random.uniform(k1, (groups, dim, hidden), dtype, -s1, s1),
        b1=jax.random.uniform(k2, (groups, hidden), dtype, -s1, s1),
        w2=jax.random.uniform(k3, (groups, hidden, dim), dtype, -s2, s2),
        b2=jax.random.uniform(k4, (groups, dim), dtype, -s2, s2),
    )


def grouped_ffw(
    params: GroupedFFWParams,
    x: jnp.ndarray,
    *,
    compute_dtype=None,
) -> jnp.ndarray:
    """Apply the per-group MLP.

    x: [..., G, d]  ->  [..., G, d], no mixing across the G axis.

    GELU is the exact (erf) variant, matching the reference's nn.GELU default.
    Matmuls accumulate in float32 via preferred_element_type so bfloat16
    compute stays numerically safe on the MXU.
    """
    w1, b1, w2, b2 = params
    if compute_dtype is not None:
        x = x.astype(compute_dtype)
        w1, b1, w2, b2 = (t.astype(compute_dtype) for t in (w1, b1, w2, b2))
    # Always accumulate in float32 (2048-term contractions in bf16 lose
    # digits, and off-TPU backends honor the accumulation dtype literally).
    # The bf16-traffic win comes from the astype below, which XLA fuses into
    # the matmul epilogue — the [..., G, 4d] hidden tensor hits HBM in bf16.
    acc = jnp.float32
    h = jnp.einsum("...gd,gdf->...gf", x, w1, preferred_element_type=acc)
    h = h + b1
    h = jax.nn.gelu(h, approximate=False)
    h = h.astype(x.dtype)
    out = jnp.einsum("...gf,gfd->...gd", h, w2, preferred_element_type=acc)
    out = out + b2
    return out.astype(x.dtype)


def grouped_ffw_lm(params: GroupedFFWParams, x: jnp.ndarray) -> jnp.ndarray:
    """Level-major form: x [G, M, d] -> [G, M, d]. Same math as grouped_ffw
    (group axis leading instead of next-to-last) — the layout the fused
    kernel and the level-major scan carry use natively."""
    w1, b1, w2, b2 = params
    acc = jnp.float32
    h = jnp.einsum("gmd,gdf->gmf", x, w1, preferred_element_type=acc)
    h = jax.nn.gelu(h + b1[:, None, :], approximate=False).astype(x.dtype)
    out = jnp.einsum("gmf,gfd->gmd", h, w2, preferred_element_type=acc)
    return (out + b2[:, None, :]).astype(x.dtype)
