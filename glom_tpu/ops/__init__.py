from glom_tpu.ops.consensus import build_local_mask, consensus_attention
from glom_tpu.ops.ffw import GroupedFFWParams, grouped_ffw, init_grouped_ffw
from glom_tpu.ops.patch import (
    LinearParams,
    image_to_tokens,
    init_linear,
    patchify,
    tokens_to_image,
    unpatchify,
)

__all__ = [
    "build_local_mask",
    "consensus_attention",
    "GroupedFFWParams",
    "grouped_ffw",
    "init_grouped_ffw",
    "LinearParams",
    "image_to_tokens",
    "init_linear",
    "patchify",
    "tokens_to_image",
    "unpatchify",
]
