from glom_tpu.train.objectives import (
    DenoiseParams,
    default_recon_index,
    denoise_loss,
    init_denoise,
    reconstruct,
)
from glom_tpu.train.supervise import TrainSupervisor, fit_supervised
from glom_tpu.train.temporal import temporal_rollout
from glom_tpu.train.trainer import (
    Trainer,
    TrainState,
    create_train_state,
    default_optimizer,
    make_train_step,
    resolve_training_route,
)

__all__ = [
    "DenoiseParams",
    "default_recon_index",
    "denoise_loss",
    "init_denoise",
    "reconstruct",
    "TrainSupervisor",
    "fit_supervised",
    "temporal_rollout",
    "Trainer",
    "TrainState",
    "create_train_state",
    "default_optimizer",
    "make_train_step",
    "resolve_training_route",
]
