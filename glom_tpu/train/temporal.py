"""Temporal / video mode.

Reference parity: the README video recipe (README :80-100, SURVEY.md §3.4):

    levels = None
    for frame in frames:
        if levels is not None: levels = levels.detach()
        levels = model(frame, iters=12, levels=levels)

i.e. columns persist across frames, with backprop-through-time truncated at
frame boundaries. TPU-native form: the frame loop is itself a `lax.scan`
(compiled once for any number of frames), and `.detach()` becomes
`lax.stop_gradient` on the carry.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from glom_tpu.models.core import ConsensusFn, GlomParams, glom_forward
from glom_tpu.utils.config import GlomConfig


def temporal_rollout(
    params: GlomParams,
    frames: jnp.ndarray,
    cfg: GlomConfig,
    *,
    iters: Optional[int] = None,
    detach_between_frames: bool = True,
    init_levels: Optional[jnp.ndarray] = None,
    remat: bool = False,
    compute_dtype=None,
    consensus_fn: Optional[ConsensusFn] = None,
) -> jnp.ndarray:
    """Run GLOM over a frame sequence, carrying column state.

    frames: [t, b, c, H, W]  ->  per-frame final levels [t, b, n, L, d].
    """
    t, b = frames.shape[:2]

    def run_frame(levels, frame):
        return glom_forward(
            params,
            frame,
            cfg,
            iters=iters,
            levels=levels,
            remat=remat,
            compute_dtype=compute_dtype,
            consensus_fn=consensus_fn,
        )

    # Frame 0 outside the scan: the reference calls it with levels=None, so
    # init_levels DOES get gradients through the first frame — only the
    # frame-to-frame carry is detached.
    first = run_frame(init_levels, frames[0])
    if t == 1:
        return first[None]

    def frame_step(levels, frame):
        if detach_between_frames:
            levels = jax.lax.stop_gradient(levels)
        new = run_frame(levels, frame)
        return new, new

    _, rest = jax.lax.scan(frame_step, first, frames[1:])
    return jnp.concatenate([first[None], rest], axis=0)
