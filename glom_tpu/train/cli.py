"""Command-line trainer: `python -m glom_tpu.train.cli --preset cifar10 ...`

The reference has no CLI (configuration is six constructor kwargs and a
README snippet); this is the framework's operational entry point —
presets, distributed meshes, checkpointing/resume, metrics, profiling.
"""

from __future__ import annotations

import argparse
import dataclasses
import sys

import jax


def _nonneg_int(s: str) -> int:
    v = int(s)
    if v < 0:
        raise argparse.ArgumentTypeError(f"must be >= 0, got {v}")
    return v


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="glom-tpu-train", description="Train GLOM (self-supervised denoising)"
    )
    p.add_argument("--preset", default="cifar10", help="see glom_tpu.utils.presets")
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--learning-rate", type=float, default=None)
    p.add_argument(
        "--lr-schedule", choices=["constant", "cosine", "warmup_cosine"],
        default=None,
    )
    p.add_argument("--warmup-steps", type=int, default=None)
    p.add_argument(
        "--schedule-steps", type=int, default=None,
        help="cosine decay horizon (defaults to --steps when a schedule is set)",
    )
    p.add_argument(
        "--grad-accum", type=int, default=None, metavar="A",
        help="split each batch into A microbatches, accumulate grads, one "
        "optimizer update (peak activation memory of one microbatch)",
    )
    p.add_argument(
        "--zero-stage", type=int, choices=[0, 1, 2], default=None,
        help="ZeRO sharded weight update over the 'data' mesh axis: 1 "
        "shards optimizer state (reduce-scatter grads, all-gather params), "
        "2 also shards the grad accumulator; dp=1 resolves to 0 "
        "(docs/PARALLELISM.md, ZeRO section)",
    )
    p.add_argument(
        "--quantized-reduce", action="store_true",
        help="EXPERIMENTAL int8 block-scaled quantized-reduce emulation "
        "(EQuARX-style; changes gradient numerics ~1e-2 rel)",
    )
    p.add_argument(
        "--telemetry-level", choices=["off", "scalars", "full"], default=None,
        help="in-graph diagnostics depth (docs/OBSERVABILITY.md): scalars "
        "= grad/update/param norms + NaN/Inf guard inside the jitted step; "
        "full adds per-level consensus agreement (GSPMD/single-device)",
    )
    p.add_argument(
        "--nonfinite-policy", choices=["skip", "warn"], default=None,
        help="what the NaN/Inf guard does (telemetry on): skip drops the "
        "poisoned update in-graph, warn applies it and flags the record",
    )
    p.add_argument(
        "--watchdog-interval", type=float, default=0.0, metavar="SECONDS",
        help="backend-liveness heartbeat: probe backend init in a throwaway "
        "subprocess every N seconds, stamping up/down/flapping transitions "
        "into the metrics stream (0 = off)",
    )
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--data", choices=["shapes", "gaussian"], default="shapes")
    p.add_argument(
        "--data-dir", default=None, metavar="PATH",
        help="train on REAL data: a directory of images (resized to the "
        "config's image_size), a .npy file, or a directory of .npy shards "
        "([N,H,W,C] or [N,C,H,W], uint8 or float). Overrides --data. "
        "Multi-host runs shard the file list by process automatically.",
    )
    p.add_argument(
        "--prefetch", type=_nonneg_int, default=2, metavar="N",
        help="stage N batches on device from a background thread (0 = off)",
    )
    p.add_argument("--metrics-file", default=None, help="JSONL metrics path")
    p.add_argument(
        "--tensorboard", default=None, metavar="DIR",
        help="also mirror scalar metrics to TensorBoard summaries in DIR",
    )
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--checkpoint-every", type=int, default=100)
    p.add_argument(
        "--checkpoint-keep", type=int, default=3, metavar="N",
        help="checkpoint retention (orbax max_to_keep). Pod runs keep "
        "more: the preemption barrier commits the gang MIN step, and a "
        "host past it must still RETAIN it (docs/RESILIENCE.md)",
    )
    p.add_argument("--resume", action="store_true", help="resume from latest ckpt")
    p.add_argument(
        "--pod-index", type=int, default=None, metavar="I",
        help="this process's index in a multi-process pod (0-based); "
        "enables the coordinated preemption barrier + cross-host restore "
        "reconciliation (docs/RESILIENCE.md). Requires --pod-count, "
        "--pod-dir, and a --checkpoint-dir named host_<I> under a shared "
        "pod root",
    )
    p.add_argument(
        "--pod-count", type=int, default=None, metavar="N",
        help="total processes in the pod (>= 2 for coordination)",
    )
    p.add_argument(
        "--pod-dir", default=None, metavar="DIR",
        help="shared coordination directory for the pod rendezvous "
        "(barrier messages + the pod commit marker)",
    )
    p.add_argument(
        "--supervise", type=_nonneg_int, default=None, metavar="RESTARTS",
        help="run under the fit_supervised restart loop (docs/RESILIENCE.md): "
        "on an unhandled training exception, restore the latest VALID "
        "checkpoint and retry with bounded exponential backoff, up to "
        "RESTARTS restarts; every decision is a stamped 'recovery' event. "
        "Requires --checkpoint-dir; implies --resume semantics.",
    )
    p.add_argument(
        "--preempt-deadline", type=float, default=30.0, metavar="SECONDS",
        help="SIGTERM (preemption) grace budget: with --flight-recorder and "
        "--checkpoint-dir, the SIGTERM hook saves a checkpoint bounded by "
        "this deadline before dumping the flight ring (docs/RESILIENCE.md)",
    )
    p.add_argument(
        "--profile-dir", default=None,
        help="capture an XProf trace of the WHOLE run (for step-windowed "
        "capture use --trace-steps)",
    )
    p.add_argument(
        "--trace-steps", default=None, metavar="A:B",
        help="programmatic XLA capture: open jax.profiler.start_trace "
        "right before global step A and close it after step B (inclusive; "
        "a bare 'A' captures one step). Window metadata is stamped into "
        "the metrics stream; view with tensorboard --logdir <trace dir>",
    )
    p.add_argument(
        "--trace-dir", default="/tmp/glom_tpu_trace", metavar="DIR",
        help="where --trace-steps writes the XProf trace",
    )
    p.add_argument(
        "--flight-recorder", default=None, metavar="DIR",
        help="crash flight recorder: keep a ring of the last "
        "--flight-events telemetry events and dump flight_<ts>.jsonl into "
        "DIR on backend-down, anomaly storm, SIGTERM/exit, or an "
        "unhandled training-loop exception (docs/OBSERVABILITY.md)",
    )
    p.add_argument(
        "--flight-events", type=int, default=256, metavar="N",
        help="flight-recorder ring capacity (default 256)",
    )
    p.add_argument(
        "--distributed",
        action="store_true",
        help="use the preset's mesh (scaled to available devices) + SP strategy",
    )
    p.add_argument(
        "--check-parity",
        action="store_true",
        help="run the sharded and single-device trainers side by side and "
        "compare losses (the sanity mode for new meshes)",
    )
    p.add_argument("--debug-nans", action="store_true")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.debug_nans:
        jax.config.update("jax_debug_nans", True)

    from glom_tpu.utils.metrics import MetricsWriter
    from glom_tpu.utils.presets import get_preset

    preset = get_preset(args.preset)
    tcfg = preset.train
    overrides = {}
    if args.batch_size is not None:
        overrides["batch_size"] = args.batch_size
    if args.learning_rate is not None:
        overrides["learning_rate"] = args.learning_rate
    if args.lr_schedule is not None:
        overrides["lr_schedule"] = args.lr_schedule
        overrides["schedule_steps"] = (
            args.schedule_steps if args.schedule_steps is not None else args.steps
        )
    elif args.schedule_steps is not None or args.warmup_steps is not None:
        # Fail loudly instead of silently training at a constant LR.
        raise SystemExit(
            "--schedule-steps/--warmup-steps require --lr-schedule "
            "(the preset's default schedule is 'constant')"
        )
    if args.warmup_steps is not None:
        overrides["warmup_steps"] = args.warmup_steps
    if args.grad_accum is not None:
        overrides["grad_accum"] = args.grad_accum
    if args.zero_stage is not None:
        overrides["zero_stage"] = args.zero_stage
    if args.quantized_reduce:
        overrides["quantized_reduce"] = True
    if args.telemetry_level is not None:
        overrides["telemetry_level"] = args.telemetry_level
    if args.nonfinite_policy is not None:
        overrides["nonfinite_policy"] = args.nonfinite_policy
    if args.seed is not None:
        overrides["seed"] = args.seed
    if overrides:
        tcfg = dataclasses.replace(tcfg, **overrides)
    cfg = preset.model

    writer = MetricsWriter(
        args.metrics_file, echo=True, tensorboard_dir=args.tensorboard
    )

    # Backend-liveness heartbeat: transitions (up/down/flapping — round
    # 5's 60-second flap went unrecorded) land in the SAME stream as the
    # training records, and every record stamps the current state via the
    # global registration.
    # Crash flight recorder FIRST: even a setup failure (bad --data-dir,
    # preset error) then leaves a postmortem trail of whatever telemetry
    # preceded it. The atexit/SIGTERM hooks stay installed for the process
    # lifetime (dump() is a no-op when nothing new arrived); the GLOBAL
    # registration is cleared on the way out so in-process callers (tests,
    # CI) don't keep feeding a dead run's buffer.
    fr = None
    if args.flight_recorder:
        from glom_tpu.tracing.flight import (
            FlightRecorder,
            set_global_flight_recorder,
        )

        fr = FlightRecorder(args.flight_recorder, capacity=args.flight_events)
        fr.install_process_hooks()
        set_global_flight_recorder(fr)

    wd = None
    if args.watchdog_interval > 0:
        from glom_tpu.telemetry.watchdog import (
            BackendWatchdog,
            set_global_watchdog,
        )

        wd = BackendWatchdog(
            interval_s=args.watchdog_interval, writer=writer
        )
        set_global_watchdog(wd)
        wd.start()
    # EVERYTHING past the heartbeat start runs under its try/finally: a
    # setup failure (bad --data-dir, preset error, trainer build) must not
    # leak a probing daemon thread into in-process callers (tests, CI).
    try:
        return _train_body(args, preset, cfg, tcfg, writer)
    finally:
        if wd is not None:
            wd.stop()
            # Unregister too: a stopped watchdog's last probed state would
            # otherwise stay frozen on every later record an in-process
            # caller (tests, CI) writes in this process.
            set_global_watchdog(None)
        if fr is not None:
            # Final dump before unregistering: the in-process caller path
            # never reaches the atexit hook with the buffer still global.
            fr.dump("run-end")
            from glom_tpu.tracing.flight import set_global_flight_recorder

            set_global_flight_recorder(None)


def _pod_setup(args, writer):
    """(PodCoordinator, peer host dirs) for a pod run; (None, None) for
    the single-host path. Partial pod flags fail loudly — a pod member
    that silently fell back to single-host preemption is exactly the
    inconsistent-resume hazard the coordinator exists to close."""
    pod_args = (args.pod_index, args.pod_count, args.pod_dir)
    if all(a is None for a in pod_args):
        return None, None
    if any(a is None for a in pod_args):
        raise SystemExit(
            "--pod-index/--pod-count/--pod-dir come together (pod "
            "coordination, docs/RESILIENCE.md)"
        )
    if args.pod_count < 2:
        raise SystemExit("--pod-count must be >= 2 (one host is the "
                         "single-host path; drop the pod flags)")
    if not args.checkpoint_dir:
        raise SystemExit("pod coordination requires --checkpoint-dir "
                         "(the pod root's host_<i> dir)")
    from glom_tpu.resilience.coordinator import (
        DirectoryTransport,
        PodCoordinator,
        peer_host_dirs,
    )

    try:
        peers = peer_host_dirs(
            args.checkpoint_dir, args.pod_index, args.pod_count
        )
    except ValueError as e:
        raise SystemExit(str(e)) from None
    transport = DirectoryTransport(
        args.pod_dir, args.pod_index, args.pod_count
    )
    return PodCoordinator(transport, writer=writer), peers


def _train_body(args, preset, cfg, tcfg, writer) -> int:
    from glom_tpu.data import gaussian_dataset, shapes_dataset
    from glom_tpu.train import Trainer

    if args.data_dir is not None:
        from glom_tpu.data import file_dataset

        def make_data(batch_size, image_size, seed=0):
            return file_dataset(
                args.data_dir, batch_size, image_size, seed=seed,
                shard_index=jax.process_index(), num_shards=jax.process_count(),
            )
    else:
        make_data = shapes_dataset if args.data == "shapes" else gaussian_dataset

    pod_coord, pod_peers = _pod_setup(args, writer)

    if args.supervise is not None:
        # The restart loop owns trainer/data/checkpoint lifecycle per
        # attempt (factories: a crashed attempt's state never leaks).
        from glom_tpu.train.supervise import TrainSupervisor, fit_supervised

        if not args.checkpoint_dir:
            raise SystemExit("--supervise requires --checkpoint-dir (the "
                             "restart loop resumes from checkpoints)")
        if args.check_parity or args.profile_dir or args.trace_steps:
            raise SystemExit(
                "--supervise does not compose with --check-parity/"
                "--profile-dir/--trace-steps (one concern per run)"
            )
        if args.prefetch > 0:
            print(
                "note: --prefetch is ignored under --supervise (the data "
                "stream is rebuilt per attempt)", file=sys.stderr,
            )

        def make_trainer():
            if args.distributed:
                from glom_tpu.parallel import DistributedTrainer

                scaled = preset.scaled_to(len(jax.devices()))
                return DistributedTrainer(
                    cfg, tcfg, scaled.mesh,
                    sp_strategy=scaled.sp_strategy, metrics_writer=writer,
                )
            return Trainer(cfg, tcfg, metrics_writer=writer)

        fit_supervised(
            make_trainer,
            lambda: make_data(tcfg.batch_size, cfg.image_size, seed=tcfg.seed),
            args.steps,
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_every=args.checkpoint_every,
            log_every=args.log_every,
            supervisor=TrainSupervisor(max_restarts=args.supervise, writer=writer),
            metrics_writer=writer,
            max_to_keep=args.checkpoint_keep,
            preemption_deadline_s=args.preempt_deadline,
            gang=pod_coord,
            pod_peers=pod_peers,
        )
        return 0

    data = make_data(tcfg.batch_size, cfg.image_size, seed=tcfg.seed)

    if args.check_parity:
        from glom_tpu.parallel import DistributedTrainer

        scaled = preset.scaled_to(len(jax.devices()))
        single = Trainer(cfg, tcfg)
        dist = DistributedTrainer(
            cfg, tcfg, scaled.mesh, sp_strategy=scaled.sp_strategy
        )
        d1 = make_data(tcfg.batch_size, cfg.image_size, seed=tcfg.seed)
        d2 = make_data(tcfg.batch_size, cfg.image_size, seed=tcfg.seed)
        h1 = single.fit(d1, num_steps=args.steps, log_every=args.log_every)
        h2 = dist.fit(d2, num_steps=args.steps, log_every=args.log_every)
        worst = max(
            abs(a["loss"] - b["loss"]) / max(abs(a["loss"]), 1e-9)
            for a, b in zip(h1, h2)
        )
        print(f"parity: worst relative loss deviation = {worst:.2e}")
        return 0 if worst < 1e-2 else 1

    if args.distributed:
        from glom_tpu.parallel import DistributedTrainer

        scaled = preset.scaled_to(len(jax.devices()))
        print(
            f"mesh {scaled.mesh.shape} (axes data/seq/model), "
            f"sp={scaled.sp_strategy}",
            file=sys.stderr,
        )
        trainer = DistributedTrainer(
            cfg,
            tcfg,
            scaled.mesh,
            sp_strategy=scaled.sp_strategy,
            metrics_writer=writer,
        )
    else:
        trainer = Trainer(cfg, tcfg, metrics_writer=writer)

    ckpt = None
    start_step = 0
    if args.checkpoint_dir:
        from glom_tpu.utils.checkpoint import CheckpointManager, abstract_like

        ckpt = CheckpointManager(
            args.checkpoint_dir,
            metrics_writer=writer,
            max_to_keep=args.checkpoint_keep,
            pod_peers=pod_peers,
        )
        if args.resume and ckpt.latest_step() is not None:
            start_step, trainer.state = ckpt.restore(
                abstract_state=abstract_like(trainer.state)
            )
            # The resume IS a recovery action — stamped into the same
            # stream as everything else, so a kill-and-resume run's
            # evidence trail reconciles without parsing stderr.
            from glom_tpu.telemetry import schema

            writer.write(
                schema.stamp(
                    {"action": "resume-from-checkpoint", "step": int(start_step)},
                    kind="recovery",
                )
            )
            print(f"resumed from step {start_step}", file=sys.stderr)
        from glom_tpu.tracing.flight import get_global_flight_recorder

        fr_live = get_global_flight_recorder()
        if fr_live is not None:
            # Preemption grace path: SIGTERM saves the live state bounded
            # by --preempt-deadline, then dumps the flight ring. In pod
            # mode the save rides the two-phase barrier instead — every
            # host commits ONE common step or the round aborts loudly.
            if pod_coord is not None:

                def _preempt_save(trainer=trainer, start=start_step):
                    from glom_tpu.resilience.coordinator import (
                        pod_preemption_save,
                    )

                    return pod_preemption_save(
                        pod_coord, args.checkpoint_dir, trainer.state,
                        int(trainer.state.step),
                        # The barrier budget sits INSIDE the hook's join
                        # deadline so an abort stamps before the dump
                        # gives up on the hook thread.
                        deadline_s=args.preempt_deadline * 0.8,
                        round_id=f"preempt-g{int(start)}",
                        metrics_writer=writer,
                    )

            else:

                def _preempt_save(trainer=trainer):
                    from glom_tpu.utils.checkpoint import preemption_save

                    return preemption_save(
                        args.checkpoint_dir, trainer.state,
                        int(trainer.state.step), metrics_writer=writer,
                    )

            fr_live.set_checkpoint_hook(
                _preempt_save, deadline_s=args.preempt_deadline
            )

    if args.prefetch > 0:
        # Wrap ONCE, outside the checkpoint-span loop: a per-span wrap over
        # the shared iterator would discard its staged batches at every
        # span boundary (skewing the data stream vs a --prefetch 0 run)
        # and race the dying worker against the next span's on the same
        # generator. Negative values fail here, at the call site.
        from glom_tpu.data import prefetch_to_device

        data = prefetch_to_device(
            data,
            size=args.prefetch,
            sharding=getattr(trainer, "batch_sharding", None),
            metrics_writer=writer,
        )

    # Step-windowed XLA capture: ONE TraceCapture across every checkpoint
    # span (its step counter is global to the run), closed in the finally
    # so a crash or a window past --steps can't leak a profiler session.
    cap = None
    if args.trace_steps and args.profile_dir:
        # jax allows one active trace: the step window opening inside the
        # whole-run trace would RuntimeError mid-training — reject up
        # front instead.
        raise SystemExit(
            "--profile-dir (whole-run trace) and --trace-steps (step "
            "window) are mutually exclusive — jax runs one profile at a "
            "time; pick one"
        )
    if args.trace_steps:
        from glom_tpu.tracing.capture import TraceCapture

        cap = TraceCapture.parse(
            args.trace_steps, args.trace_dir, writer=writer
        )

    def run(steps):
        remaining = steps - start_step
        if remaining <= 0:
            print("nothing to do (already past --steps)", file=sys.stderr)
            return
        done = 0
        while done < remaining:
            span = min(args.checkpoint_every, remaining - done) if ckpt else remaining
            trainer.fit(
                data, num_steps=span, log_every=args.log_every,
                trace_capture=cap,
            )
            done += span
            if ckpt:
                ckpt.save(start_step + done, trainer.state)
        if ckpt:
            ckpt.wait()

    try:
        if args.profile_dir:
            from glom_tpu.utils.profiling import trace

            with trace(args.profile_dir):
                run(args.steps)
        else:
            run(args.steps)
    finally:
        if cap is not None:
            cap.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
