"""fit_supervised: the restart loop that stands between a fault and a
dead training run.

TPU recovery is checkpoint-based restart (tests/test_resilience.py): the
slice is fixed-shape, so "recovery" means restore the latest VALID step
and continue. Until now a human was the restart loop. fit_supervised
closes it in-process:

    attempt:
        fresh trainer  (a crashed attempt's state never leaks forward)
        restore latest VALID checkpoint   -> stamped "recovery" event
        realign the data stream to the restored step
        fit in checkpoint spans, saving each span
    on failure:
        bounded exponential backoff       -> stamped "recovery" event
        next attempt (budget: max_restarts)
    budget exhausted:
        stamped "give-up" + the original exception re-raised

Cross-PROCESS faults (SIGKILL — nothing in-process survives those)
compose with this same loop: the replacement process calls
fit_supervised over the same checkpoint dir and attempt 1 resumes where
the dead process committed (glom_tpu/resilience/chaos.py drives exactly
that end-to-end). The in-process loop covers the faults a process DOES
survive: NaN storms that escalate to a raise, transient backend/dispatch
exceptions, poisoned batches, checkpoint-write failures.

The trainer protocol is deliberately thin — `.state` (settable), `.fit
(data, num_steps, log_every=...)`, optional `.state_shardings` for
sharded restore — so both Trainer and DistributedTrainer (and the test
harness's host-only fakes) supervise identically.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterator, List, Optional

import jax
import numpy as np


def _emit_recovery(writer, rec: dict) -> dict:
    from glom_tpu.resilience.faults import emit_recovery

    return emit_recovery(writer, rec)


class TrainSupervisor:
    """Restart budget + backoff state, stamped.

    Separated from fit_supervised so chaos tests can drive the policy
    directly and monitoring threads can read status() while the loop
    runs — the counters ride one lock (the lockset contract,
    docs/ANALYSIS.md)."""

    def __init__(
        self,
        *,
        max_restarts: int = 3,
        backoff_s: float = 0.5,
        backoff_factor: float = 2.0,
        backoff_max_s: float = 30.0,
        writer=None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        from glom_tpu.resilience.retry import validate_backoff

        if max_restarts < 0:
            raise ValueError(f"max_restarts {max_restarts} must be >= 0")
        validate_backoff(backoff_s, backoff_factor, backoff_max_s)
        self.max_restarts = max_restarts
        self.backoff_s = backoff_s
        self.backoff_factor = backoff_factor
        self.backoff_max_s = backoff_max_s
        self.writer = writer
        self._sleep = sleep
        self._lock = threading.Lock()
        self._attempts = 0
        self._restarts = 0
        self._gave_up = False
        self._last_error: Optional[str] = None

    def begin_attempt(self) -> int:
        with self._lock:
            self._attempts += 1
            return self._attempts

    def on_failure(self, exc: BaseException) -> Optional[float]:
        """One failed attempt: returns the backoff slept before the next
        attempt, or None when the budget is exhausted (the caller
        re-raises). Stamps the "recovery" event either way."""
        err = f"{type(exc).__name__}: {exc}"[:300]
        with self._lock:
            self._last_error = err
            attempt = self._attempts
            if self._restarts >= self.max_restarts:
                self._gave_up = True
                budget_left = False
            else:
                from glom_tpu.resilience.retry import next_backoff

                self._restarts += 1
                budget_left = True
                backoff = next_backoff(
                    self.backoff_s, self.backoff_factor,
                    self.backoff_max_s, self._restarts - 1,
                )
        if not budget_left:
            _emit_recovery(
                self.writer,
                {
                    "action": "give-up",
                    "attempt": attempt,
                    "max_restarts": self.max_restarts,
                    "exception": err,
                },
            )
            return None
        _emit_recovery(
            self.writer,
            {
                "action": "restart",
                "attempt": attempt,
                "restarts": self._restarts_snapshot(),
                "max_restarts": self.max_restarts,
                "backoff_s": round(backoff, 4),
                "exception": err,
            },
        )
        if backoff > 0:
            self._sleep(backoff)
        return backoff

    def _restarts_snapshot(self) -> int:
        with self._lock:
            return self._restarts

    def record(self) -> dict:
        """Status snapshot (stampable; readable from monitor threads)."""
        with self._lock:
            return {
                "attempts": self._attempts,
                "restarts": self._restarts,
                "max_restarts": self.max_restarts,
                "gave_up": self._gave_up,
                "last_error": self._last_error,
            }


def _abstract_state(trainer):
    """Restore target for the trainer's state: ShapeDtypeStructs carrying
    the trainer's NamedShardings when it exposes them (DistributedTrainer
    does — restored arrays land sharded, no host bounce)."""
    shardings = getattr(trainer, "state_shardings", None)
    if shardings is None:
        return jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype),
            trainer.state,
        )
    return jax.tree_util.tree_map(
        lambda x, s: jax.ShapeDtypeStruct(np.shape(x), x.dtype, sharding=s),
        trainer.state,
        shardings,
    )


def fit_supervised(
    make_trainer: Callable[[], object],
    make_data: Callable[[], Iterator],
    num_steps: int,
    *,
    checkpoint_dir: str,
    checkpoint_every: int = 100,
    log_every: int = 10,
    supervisor: Optional[TrainSupervisor] = None,
    metrics_writer=None,
    checkpoint_async: bool = False,
    max_to_keep: int = 3,
    preemption_deadline_s: float = 30.0,
    gang=None,
    pod_peers=None,
    gang_barrier_deadline_s: float = 30.0,
) -> List[dict]:
    """Run `num_steps` updates under the restart supervisor; returns the
    concatenated fit history across attempts.

    make_trainer/make_data are FACTORIES, called fresh per attempt: a
    crashed trainer's params/optimizer state must never leak into the
    next attempt (the checkpoint is the one source of resumed state), and
    the data stream must be deterministic from the start so the resumed
    attempt can realign by skipping `resumed_step` batches — the same
    contract tests/test_resilience.py's kill-a-worker harness pins.

    Checkpoints land every `checkpoint_every` steps through the
    manifest-verified CheckpointManager (utils/checkpoint.py): a torn
    final step restores from the previous valid one, stamped. While an
    attempt runs, the global flight recorder's SIGTERM hook (when one is
    installed) carries a bounded preemption checkpoint of the live
    trainer state (tracing/flight.py set_checkpoint_hook).

    checkpoint_async=False by default: the supervised loop's reason to
    exist is surviving kills, and a synchronous save is committed the
    moment the span ends — the async overlap win belongs to unsupervised
    throughput runs. max_to_keep is the retention knob (--checkpoint-keep
    on the CLI); pod gangs should raise it — retention bounds the step
    drift the preemption barrier can bridge.

    GANG MODE (`gang=` a resilience.coordinator.PodCoordinator,
    `pod_peers=` the sibling hosts' checkpoint dirs): the gang restarts
    as ONE unit. Each attempt rendezvous at the restart barrier before
    restoring, the restore reconciles to the newest step valid on EVERY
    host (pod-mode CheckpointManager), and any member's failure posts a
    gang-wide stop — the others raise GangRestart at their next
    checkpoint-span boundary, so the whole gang falls back together and
    resumes from the reconciled common step. Epochs are the attempt
    numbers, which the stop-flag propagation keeps in lockstep."""
    from glom_tpu.tracing.flight import get_global_flight_recorder
    from glom_tpu.utils.checkpoint import CheckpointManager

    if num_steps < 1:
        raise ValueError(f"num_steps {num_steps} must be >= 1")
    if checkpoint_every < 1:
        raise ValueError(f"checkpoint_every {checkpoint_every} must be >= 1")
    if gang is None and pod_peers:
        raise ValueError("pod_peers without gang= (pod restore needs the "
                         "coordinator's restart rendezvous)")
    sup = (
        supervisor
        if supervisor is not None
        else TrainSupervisor(writer=metrics_writer)
    )
    history: List[dict] = []
    while True:
        attempt = sup.begin_attempt()
        ckpt = CheckpointManager(
            checkpoint_dir,
            async_save=checkpoint_async,
            max_to_keep=max_to_keep,
            metrics_writer=metrics_writer,
            pod_peers=pod_peers,
        )
        fr = get_global_flight_recorder()
        try:
            if gang is not None:
                # Rendezvous BEFORE reconciling: every member must have
                # stopped writing its previous attempt's checkpoints, or
                # the common-step walk races live saves. Arrival messages
                # persist per epoch, so a member deep in backoff sails
                # through a barrier its peers already filled.
                gang.gang_barrier(
                    "restart", attempt, deadline_s=gang_barrier_deadline_s
                )
            trainer = make_trainer()
            start = 0
            latest = ckpt.latest_step()
            if latest is not None:
                start, trainer.state = ckpt.restore(
                    abstract_state=_abstract_state(trainer)
                )
                _emit_recovery(
                    metrics_writer,
                    {
                        "action": "resume-from-checkpoint",
                        "step": int(start),
                        "attempt": attempt,
                    },
                )
            if start >= num_steps:
                if gang is not None:
                    gang.signal_gang_done(num_steps)
                return history
            data = make_data()
            for _ in range(start):
                next(data)  # realign the deterministic stream
            if fr is not None:
                if gang is not None:

                    def preempt_save(start=start):
                        from glom_tpu.resilience.coordinator import (
                            pod_preemption_save,
                        )

                        return pod_preemption_save(
                            gang, checkpoint_dir, trainer.state,
                            int(np.asarray(trainer.state.step)),
                            deadline_s=preemption_deadline_s * 0.8,
                            round_id=f"preempt-g{int(start)}",
                            metrics_writer=metrics_writer,
                        )

                else:

                    def preempt_save():
                        from glom_tpu.utils.checkpoint import preemption_save

                        return preemption_save(
                            checkpoint_dir, trainer.state,
                            int(np.asarray(trainer.state.step)),
                            metrics_writer=metrics_writer,
                        )

                fr.set_checkpoint_hook(
                    preempt_save, deadline_s=preemption_deadline_s
                )
            done = start
            while done < num_steps:
                if gang is not None and gang.gang_stop_requested(attempt):
                    from glom_tpu.resilience.coordinator import GangRestart

                    raise GangRestart(
                        f"gang stop requested in epoch {attempt}"
                    )
                span = min(checkpoint_every, num_steps - done)
                history.extend(
                    trainer.fit(data, num_steps=span, log_every=log_every)
                )
                done += span
                ckpt.save(done, trainer.state)
            ckpt.wait()
            if gang is not None:
                # A finished member leaves the gang: the persistent done
                # flag excuses it from future restart barriers, so a
                # peer that crashes AFTER we return can still recover
                # (waiting for us would deadlock its every attempt).
                gang.signal_gang_done(num_steps)
            return history
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:  # noqa: BLE001 — the supervisor classifies
            if gang is not None:
                from glom_tpu.resilience.coordinator import GangRestart

                if not isinstance(e, GangRestart):
                    # OUR failure becomes the gang's: peers raise
                    # GangRestart at their next span boundary and the
                    # whole gang meets at the next restart barrier.
                    gang.signal_gang_stop(
                        attempt, f"{type(e).__name__}: {e}"[:300]
                    )
            if sup.on_failure(e) is None:
                raise
        finally:
            if fr is not None:
                fr.set_checkpoint_hook(None)
            try:
                ckpt.close()
            except Exception:  # noqa: BLE001 — best-effort on teardown
                pass
