"""The training loop the reference never had (SURVEY.md §5: trainer = absent
in reference; README recipe only). TPU-native design:

  * `train_step` is a pure function (state, batch, rng) -> (state, metrics),
    jitted once; under a mesh it is pjit-sharded by glom_tpu.parallel.
  * optimizer = any optax GradientTransformation (Adam by default).
  * donate_argnums on the state so XLA updates parameters in place —
    essential at pod scale where two copies of the optimizer state would
    blow HBM.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Iterator, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from glom_tpu.models.core import ConsensusFn, resolve_vjp_path
from glom_tpu.telemetry import diagnostics as diag
from glom_tpu.train.objectives import (
    DenoiseParams,
    default_recon_index,
    denoise_loss,
    init_denoise,
)
from glom_tpu.utils.config import GlomConfig, TrainConfig


class TrainState(NamedTuple):
    params: DenoiseParams
    opt_state: Any
    step: jnp.ndarray  # scalar int32


def create_train_state(
    key: jax.Array,
    cfg: GlomConfig,
    tcfg: TrainConfig,
    optimizer: Optional[optax.GradientTransformation] = None,
) -> Tuple[TrainState, optax.GradientTransformation]:
    optimizer = optimizer if optimizer is not None else default_optimizer(tcfg)
    params = init_denoise(key, cfg)
    return (
        TrainState(
            params=params,
            opt_state=optimizer.init(params),
            step=jnp.zeros((), jnp.int32),
        ),
        optimizer,
    )


def make_lr_schedule(tcfg: TrainConfig):
    """Learning-rate schedule from the config: a float (constant) or an
    optax schedule fn. Cosine decays to lr_final_fraction * lr; for
    warmup_cosine, schedule_steps is the TOTAL length INCLUDING the
    linear warmup (optax semantics: cosine decay runs over
    schedule_steps - warmup_steps)."""
    if tcfg.lr_schedule == "constant":
        return tcfg.learning_rate
    if tcfg.lr_schedule == "cosine":
        return optax.cosine_decay_schedule(
            tcfg.learning_rate, tcfg.schedule_steps, alpha=tcfg.lr_final_fraction
        )
    if tcfg.lr_schedule == "warmup_cosine":
        if not 0 <= tcfg.warmup_steps < tcfg.schedule_steps:
            raise ValueError(
                f"warmup_steps={tcfg.warmup_steps} must be < schedule_steps="
                f"{tcfg.schedule_steps} (schedule_steps is the TOTAL length "
                "including warmup)"
            )
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=tcfg.learning_rate,
            warmup_steps=tcfg.warmup_steps,
            decay_steps=tcfg.schedule_steps,
            end_value=tcfg.learning_rate * tcfg.lr_final_fraction,
        )
    raise ValueError(
        f"lr_schedule={tcfg.lr_schedule!r}: one of 'constant', 'cosine', "
        "'warmup_cosine'"
    )


def pinned_grad_accum(tcfg: TrainConfig) -> int:
    """The microbatch count an EXPLICIT TrainConfig.grad_accum pins, or the
    single-pass base (1) when None — None is the auto-routing sentinel and
    only resolve_training_route may raise it. THE single None-resolution
    source: every numeric use of tcfg.grad_accum (validation, manual-path
    scans, comm pricing) goes through here so an explicit user value is
    never silently overridden (ADVICE round 5)."""
    accum = 1 if tcfg.grad_accum is None else tcfg.grad_accum
    if accum < 1:
        raise ValueError(f"grad_accum={tcfg.grad_accum} must be >= 1 or None")
    return accum


def accumulate_grads(loss_fn, params, img, noise, accum: int,
                     grad_transform=None, grad_init=None, has_aux=False):
    """Exact microbatch gradient accumulation shared by the single-device,
    GSPMD, and manual-shard_map train steps: STRIDED split (microbatch i
    takes rows i, i+accum, ...) so a batch sharded over a 'data' mesh axis
    keeps every microbatch row-local to its shard (a contiguous split would
    reshuffle half the batch across devices on every scan step); the
    accumulated sum over all examples is invariant to the grouping, so
    loss/grads equal the full-batch values exactly (mean of microbatch
    means). Returns (loss, grads).

    grad_transform/grad_init are the ZeRO stage-2 hook — the scatter must
    happen per microbatch so the accumulation buffer only ever holds the
    1/dp owned shard (the sum over microbatches commutes with the linear
    scatter, so the math is still exact):
      * GSPMD step: transform = with_sharding_constraint to the
        data-sharded layout (XLA lowers to a per-microbatch
        reduce-scatter); init = zeros under the same constraint.
      * manual ZeRO step: transform = the explicit psum_scatter tree;
        init = zeros at the 1/dp shard shapes (the carry must match the
        transformed gradients, which is why init is a separate hook).

    has_aux=True mirrors jax.value_and_grad(has_aux=True): loss_fn returns
    (loss, aux) and the call returns ((loss, aux_mean), grads) — the
    telemetry "full" diagnostics ride the microbatch scan as a mean over
    microbatches (every aux stat here is itself a mean, so the grouping
    invariance argument above applies to it too)."""
    imgs = img.reshape(-1, accum, *img.shape[1:]).swapaxes(0, 1)
    noises = noise.reshape(-1, accum, *noise.shape[1:]).swapaxes(0, 1)

    def micro(carry, xs):
        acc_l, acc_aux, acc_g = carry
        mi, mn = xs
        if has_aux:
            (l, aux), g = jax.value_and_grad(loss_fn, has_aux=True)(
                params, mi, mn
            )
            acc_aux = jax.tree_util.tree_map(jnp.add, acc_aux, aux)
        else:
            l, g = jax.value_and_grad(loss_fn)(params, mi, mn)
        if grad_transform is not None:
            g = grad_transform(g)
        return (acc_l + l, acc_aux, jax.tree_util.tree_map(jnp.add, acc_g, g)), None

    zeros = (
        grad_init()
        if grad_init is not None
        else jax.tree_util.tree_map(jnp.zeros_like, params)
    )
    if has_aux:
        # Abstract-eval one microbatch for the aux accumulator's shapes
        # (the carry must be built before the scan body ever runs).
        _, aux_shape = jax.eval_shape(loss_fn, params, imgs[0], noises[0])
        aux_zeros = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), aux_shape
        )
    else:
        aux_zeros = ()
    (loss_sum, aux_sum, grads_sum), _ = jax.lax.scan(
        micro, (jnp.zeros((), jnp.float32), aux_zeros, zeros), (imgs, noises)
    )
    loss = loss_sum / accum
    grads = jax.tree_util.tree_map(lambda t: t / accum, grads_sum)
    if has_aux:
        aux = jax.tree_util.tree_map(lambda t: t / accum, aux_sum)
        return (loss, aux), grads
    return loss, grads


def resolve_route_keys(cfg: GlomConfig, tcfg: TrainConfig) -> Tuple[int, int]:
    """(effective loss iters k, compute itemsize) for vjp-path resolution —
    the ONE copy of the T/k defaulting + dtype prologue (both
    resolve_training_route and DistributedTrainer's manual-branch labeling
    use it; two copies would let a rule change silently resolve different
    backward labels at different call sites)."""
    T = tcfg.iters if tcfg.iters is not None else cfg.default_iters
    k = (
        tcfg.recon_iter_index
        if tcfg.recon_iter_index is not None
        else default_recon_index(T)
    )
    return k, 2 if tcfg.compute_dtype == "bfloat16" else 4


def resolve_training_route(
    cfg: GlomConfig,
    tcfg: TrainConfig,
    *,
    custom_consensus: bool = False,
    scan_only: bool = False,
) -> Tuple[int, str]:
    """Effective (grad_accum, vjp_path) for this training config.

    The framework must never hand out a below-baseline regime it knows how
    to beat (round-4 batch-128 measured 0.96x vs baseline on the scan path
    while grad_accum=2 over batch-64 microbatches rides the fused-loop VJP
    at 1.17x): when grad_accum is None (auto) and the full batch misses
    the fused loop, try power-of-two microbatch splits and take the first
    that lands on it — the accumulation is exact (accumulate_grads), so
    this changes the schedule, never the math. An EXPLICIT grad_accum —
    INCLUDING 1 — is always honored as given (1 is the supported opt-out
    for the single-pass full-batch step; ADVICE round 5).

    scan_only=True (the GSPMD DistributedTrainer build) excludes the fused
    loop AND the auto-split that exists only to reach it: the whole-loop
    Pallas custom_vjp has no partitioning rule, so dispatching it on
    GSPMD-sharded arrays — which the auto-split's single-chip heuristics
    evaluated against the GLOBAL batch could do — is a compile failure or
    full-replication OOM, not a speedup."""
    k, itemsize = resolve_route_keys(cfg, tcfg)
    kw = dict(
        remat=tcfg.remat,
        use_pallas=tcfg.use_pallas,
        itemsize=itemsize,
        custom_consensus=custom_consensus,
        scan_only=scan_only,
    )
    accum = pinned_grad_accum(tcfg)
    path = resolve_vjp_path(cfg, tcfg.batch_size // accum, k, **kw)
    if (
        tcfg.grad_accum is None
        and not scan_only
        and path != "fused_loop"
    ):
        a = 2
        while a <= 16 and tcfg.batch_size % a == 0 and tcfg.batch_size // a >= 8:
            if resolve_vjp_path(cfg, tcfg.batch_size // a, k, **kw) == "fused_loop":
                return a, "fused_loop"
            a *= 2
    return accum, path


def resolve_zero_stage(tcfg: TrainConfig, dp: int) -> int:
    """Effective ZeRO stage for this run — THE single resolution source
    (same discipline as resolve_vjp_path / effective_sp_strategy: both
    trainer paths call this once and stamp its output into every metrics
    record, so a run can never shard differently than its logs claim).
    dp == 1 has nothing to shard and resolves to 0 silently, mirroring
    seq <= 1 resolving sp_strategy to 'none'."""
    if tcfg.zero_stage not in (0, 1, 2):
        raise ValueError(
            f"zero_stage={tcfg.zero_stage!r}: must be 0 (replicated), "
            "1 (sharded optimizer state), or 2 (+ sharded grad accumulator)"
        )
    if dp <= 1:
        return 0
    return tcfg.zero_stage


def resolve_quantized_reduce(tcfg: TrainConfig, dp: int) -> bool:
    """Effective quantized-reduce flag — same single-source discipline as
    resolve_zero_stage: dp == 1 has no cross-replica reduction to emulate
    a wire hop on, so the flag resolves OFF (quantizing there would
    degrade gradients ~1e-2 rel for nothing while the comm counters
    correctly read zero). The resolved value is what the trainers apply
    AND stamp, so a record can never claim an emulation that didn't run."""
    return bool(tcfg.quantized_reduce) and dp > 1


class ZeroShardings(NamedTuple):
    """The two NamedSharding trees the GSPMD ZeRO step constrains with:
    `grads` (param-shaped, 'data'-sharded on each leaf's zero_shard_axis —
    the reduce-scatter layout, also the optimizer-moment layout) and
    `params` (the base data-replicated layout the all-gather restores)."""

    grads: Any
    params: Any


def default_optimizer(tcfg: TrainConfig) -> optax.GradientTransformation:
    lr = make_lr_schedule(tcfg)
    if tcfg.weight_decay > 0:
        return optax.adamw(lr, weight_decay=tcfg.weight_decay)
    return optax.adam(lr)


def make_train_step(
    cfg: GlomConfig,
    tcfg: TrainConfig,
    optimizer: optax.GradientTransformation,
    *,
    consensus_fn: Optional[ConsensusFn] = None,
    with_grad_norm: bool = True,
    zero_stage: int = 0,
    zero_shardings: Optional[ZeroShardings] = None,
    quantized_reduce: Optional[bool] = None,
    scan_only: bool = False,
) -> Callable[[TrainState, jnp.ndarray, jax.Array], Tuple[TrainState, dict]]:
    """Build the pure train step. Noise is generated ON DEVICE from the rng
    (no host->device transfer of noise tensors).

    with_grad_norm=False omits the grad-norm metric: optax.global_norm is
    a full extra sweep over every gradient buffer, pure observability —
    the fit loops compile BOTH variants and run the fast one on
    non-logging steps (the sustained-throughput step).

    zero_stage >= 1 with zero_shardings runs the GSPMD form of the ZeRO
    weight update (Xu et al. 2020): gradients are constrained to the
    data-sharded layout before optimizer.update — XLA lowers the DP
    reduction to a reduce-scatter instead of an allreduce — the update
    (reading the 1/dp optimizer-moment shard the state carries) computes
    only on the owned shard, and the updated params are constrained back
    to the replicated layout, which lowers to the all-gather. Stage 2
    additionally pushes the constraint inside the microbatch accumulation
    so the grad buffer itself lives sharded.

    quantized_reduce (None -> resolve from tcfg; trainers pass the
    resolve_quantized_reduce output) inserts the EQuARX-style int8
    wire-hop emulation. NOTE the GSPMD asymmetry vs the manual step: in
    SPMD tracing there is no per-replica gradient-contribution tensor
    (the compiler inserts the cross-replica reduction wherever the
    partitioner places it), so the hop here applies to the REDUCED
    gradient — the receive side of the wire — whereas the manual region
    quantizes each replica's local contribution before its explicit
    psum_scatter (the more faithful send-side form). Both are one
    quantization hop; comm_volume_model prices the hypothetical real
    quantized collective, not the emulation's op placement.

    scan_only=True (the GSPMD DistributedTrainer build) keeps both the
    fused-loop dispatch AND the auto grad-accum off this step — the Pallas
    whole-loop custom_vjp is illegal on GSPMD-sharded arrays.

    tcfg.telemetry_level != "off" adds the in-graph diagnostics
    (telemetry/diagnostics.py): grad/update/param norms and the NaN/Inf
    guard on EVERY variant including the fast one (a guard that only runs
    on logging steps misses 9 of every 10 anomalies), plus per-level
    consensus agreement and the quantization-error probe at "full"."""
    if tcfg.compute_dtype not in ("float32", "bfloat16"):
        raise ValueError(
            f"compute_dtype={tcfg.compute_dtype!r}: must be 'float32' or 'bfloat16'"
        )
    pinned = pinned_grad_accum(tcfg)
    if tcfg.batch_size % pinned != 0:
        raise ValueError(
            f"grad_accum={tcfg.grad_accum} must divide batch_size="
            f"{tcfg.batch_size}"
        )
    compute_dtype = jnp.bfloat16 if tcfg.compute_dtype == "bfloat16" else None
    # Auto-route oversized batches through exact microbatch accumulation
    # when that recovers the fused-loop VJP (see resolve_training_route);
    # the decision is static, exposed on the returned fn (.grad_accum /
    # .vjp_path), and logged by the trainers next to sp_strategy.
    grad_accum, vjp_path = resolve_training_route(
        cfg, tcfg, custom_consensus=consensus_fn is not None,
        scan_only=scan_only,
    )
    quantized = (
        bool(tcfg.quantized_reduce)
        if quantized_reduce is None
        else quantized_reduce
    )
    level = diag.resolve_telemetry_level(tcfg)
    full = level == "full"

    def loss_of(params, img, noise):
        return denoise_loss(
            params,
            img,
            noise,
            cfg,
            recon_index=tcfg.recon_iter_index,
            iters=tcfg.iters,
            remat=tcfg.remat,
            compute_dtype=compute_dtype,
            consensus_fn=consensus_fn,
            use_pallas=tcfg.use_pallas,
            unroll=tcfg.scan_unroll,
            with_diagnostics=full,
        )

    def train_step(state: TrainState, img: jnp.ndarray, rng: jax.Array):
        noise_rng = jax.random.fold_in(rng, state.step)
        noise = tcfg.noise_std * jax.random.normal(noise_rng, img.shape, img.dtype)

        if grad_accum > 1:
            if zero_stage >= 2 and zero_shardings is not None:
                constrain = lambda g: jax.lax.with_sharding_constraint(
                    g, zero_shardings.grads
                )
                gkw = dict(
                    grad_transform=constrain,
                    grad_init=lambda: constrain(
                        jax.tree_util.tree_map(jnp.zeros_like, state.params)
                    ),
                )
            else:
                gkw = {}
            loss, grads = accumulate_grads(
                loss_of, state.params, img, noise, grad_accum,
                has_aux=full, **gkw
            )
        else:
            loss, grads = jax.value_and_grad(loss_of, has_aux=full)(
                state.params, img, noise
            )
        aux = None
        if full:
            loss, aux = loss
        metrics = {}
        if quantized:
            from glom_tpu.parallel.quantized import quantize_dequantize

            dq = jax.tree_util.tree_map(quantize_dequantize, grads)
            if level != "off":
                # EQuARX wire-hop accuracy probe: what one quantized ride
                # cost THIS step's gradient, on the record next to the
                # loss it perturbs.
                metrics["quant_rel_err"] = diag.quantization_error(grads, dq)
            grads = dq
        if zero_stage >= 1 and zero_shardings is not None:
            # Reduce-scatter: the cross-replica grad reduction lands each
            # leaf already split on its zero_shard_axis.
            grads = jax.lax.with_sharding_constraint(grads, zero_shardings.grads)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        if zero_stage >= 1 and zero_shardings is not None:
            # All-gather the updated shards back to the replicated layout
            # the next forward reads.
            params = jax.lax.with_sharding_constraint(
                params, zero_shardings.params
            )
        metrics.update({"loss": loss, "step": state.step})
        if with_grad_norm or level != "off":
            grad_norm = optax.global_norm(grads)
        if with_grad_norm:
            metrics["grad_norm"] = grad_norm
        if level != "off":
            taps = diag.scalar_taps(
                loss=loss, grad_norm=grad_norm, updates=updates, params=params
            )
            nonfinite = taps.pop("nonfinite")
            if tcfg.nonfinite_policy == "skip":
                # Drop the poisoned update in-graph: params AND optimizer
                # state keep their previous values; the step counter still
                # advances so schedules/logs stay aligned.
                params = diag.guard_update(nonfinite, params, state.params)
                opt_state = diag.guard_update(
                    nonfinite, opt_state, state.opt_state
                )
                metrics["skipped_nonfinite"] = nonfinite.astype(jnp.int32)
            metrics.update(taps)
            metrics["nonfinite_step"] = nonfinite.astype(jnp.int32)
            if full and aux is not None:
                metrics["level_agreement"] = aux["level_agreement"]
        return TrainState(params, opt_state, state.step + 1), metrics

    # Static routing facts for the trainers' metric records (strings can't
    # ride the jitted metrics dict).
    train_step.grad_accum = grad_accum
    train_step.vjp_path = vjp_path
    return train_step


def _jsonable(v):
    """Metrics-record value -> JSON scalar (strings/bools/None pass
    through; device scalars fetch)."""
    if v is None or isinstance(v, (str, bool)):
        return v
    return float(v)


def fit_loop(
    step: Callable[[Any], dict],
    data: Iterator,
    num_steps: int,
    *,
    log_every: int = 10,
    metrics_writer=None,
    step_fast: Optional[Callable[[Any], dict]] = None,
    compile_tracker: Optional[set] = None,
    trace_capture=None,
    memory_probe: Optional[Callable[[], dict]] = None,
    aux_records_probe: Optional[Callable[[], list]] = None,
) -> list[dict]:
    """Shared training loop: pull batches, step, log every `log_every`.
    Used by both the single-device Trainer and the DistributedTrainer.
    step_fast (when given) runs the non-logging iterations — the variant
    without observability-only work (grad-norm sweep).

    Every logging record is a schema-stamped "train_step" event carrying
    the step-time histogram (compile split out per jit variant — see
    sinks.StepTimeStats for the async-dispatch reading of p50 vs p95); a
    step flagged non-finite by the in-graph guard emits a structured
    "anomaly" event into the metrics stream at the next logging step. The
    flags of NON-logging steps are kept as device scalars and fetched
    only at the log boundary (by then they are long computed, so the
    fetch adds no pipeline stall and every incident is reported — not
    just the ones landing on a logging step). The returned history stays
    homogeneous train_step records (consumers index loss/steps_per_sec);
    anomaly events go to the writer only.

    compile_tracker: pass a PERSISTENT set when calling fit_loop more than
    once over the same jitted steps (the trainers do — fit() per
    checkpoint span): the jit cache is warm in span 2+, and a fresh
    tracker would mislabel each span's first steps as compiles, faking a
    compile_time_s and dropping real samples from the percentiles.

    Tracing hooks (glom_tpu/tracing/, docs/OBSERVABILITY.md):
      * host spans — host_data_next / host_step_dispatch / host_log_fetch
        are aggregated per phase between logging steps (SpanAggregator:
        dict arithmetic, <1% of the CPU bench step by bench_train.py
        --span-ab) and drained as one "span" record per phase into the
        metrics stream at each log boundary;
      * trace_capture — a tracing.capture.TraceCapture whose [A, B] step
        window this loop advances (the capture's counter persists across
        fit() calls; the CALLER owns close());
      * memory_probe — called at logging steps; its dict (HBM watermarks
        + model drift, tracing.memory.memory_record) rides the record;
      * aux_records_probe — called at logging steps; returns a list of
        ALREADY-STAMPED standalone records written to the same stream
        (the collective-timing sampler's "collective_time" rows —
        DistributedTrainer wires it; docs/OBSERVABILITY.md, Capacity
        observatory);
      * flight recorder — every record this loop produces reaches the
        global recorder (via MetricsWriter.write, or directly when no
        writer is attached), and an unhandled exception dumps the buffer
        (`fit-loop-exception`) before re-raising — the crash postmortem
        rounds 4-5 never had."""
    from glom_tpu.telemetry import schema
    from glom_tpu.telemetry.sinks import StepTimeStats
    from glom_tpu.tracing import flight
    from glom_tpu.tracing.spans import SpanAggregator, span

    history = []
    stats = StepTimeStats()
    spans = SpanAggregator()
    # Which jit variant's compile step was seen, keyed by role (bound
    # methods get fresh ids per access, so identity keys wouldn't survive
    # a second fit() call even with a shared tracker).
    compiled = compile_tracker if compile_tracker is not None else set()
    pending_flags = []  # (step index, device-scalar nonfinite flag)
    t0 = time.perf_counter()
    i = -1
    try:
        for i in range(num_steps):
            logging_step = (i + 1) % log_every == 0 or i == num_steps - 1
            use_full = logging_step or step_fast is None
            fn = step if use_full else step_fast
            key = "step" if use_full else "step_fast"
            first_call = key not in compiled
            compiled.add(key)
            # Pull the batch BEFORE the timer: host data-generation time is
            # a data-pipeline signal, not step time — folding it in would
            # make a loader stall read as a step/compile regression on
            # every record.
            with span("host_data_next", aggregator=spans):
                batch = next(data)
            t_step = time.perf_counter()
            with span("host_step_dispatch", aggregator=spans):
                if trace_capture is not None:
                    with trace_capture.unit():
                        metrics = fn(batch)
                else:
                    metrics = fn(batch)
            # Each jit variant's first call is trace+compile — both the
            # fast step's (iteration 0) and the logging step's (first log
            # boundary) — and must not pollute the steady-state
            # percentiles.
            stats.observe(time.perf_counter() - t_step, is_compile=first_call)
            if "nonfinite_step" in metrics and not logging_step:
                pending_flags.append((i, metrics["nonfinite_step"]))
            if not logging_step:
                continue
            with span("host_log_fetch", aggregator=spans):
                metrics = diag.split_level_agreement(metrics)
                metrics = {k: _jsonable(v) for k, v in metrics.items()}
            metrics["steps_per_sec"] = (i + 1) / (time.perf_counter() - t0)
            metrics.update(stats.summary())
            if memory_probe is not None:
                metrics.update(memory_probe() or {})
            rec = schema.stamp(metrics, kind="train_step")
            history.append(rec)
            if metrics_writer is not None:
                metrics_writer.write(rec)
            else:
                # No writer: feed the flight recorder directly so a crash
                # in a writerless run still has a postmortem trail.
                flight.observe_event(rec)
            for srec in spans.records(extra={"step": rec.get("step", float(i))}):
                if metrics_writer is not None:
                    metrics_writer.write(srec)
                else:
                    flight.observe_event(srec)
            if aux_records_probe is not None:
                # Already-stamped standalone records minted at the logging
                # boundary (the collective-timing sampler's
                # "collective_time" rows — DistributedTrainer wires it):
                # unlike memory_probe's dict these do NOT merge into the
                # train_step record; they are their own schema kinds.
                for arec in aux_records_probe() or []:
                    if metrics_writer is not None:
                        metrics_writer.write(arec)
                    else:
                        flight.observe_event(arec)
            flagged = [k for k, v in pending_flags if float(v)]
            pending_flags = []
            if rec.get("nonfinite_step"):
                flagged.append(i)
            if flagged:
                anomaly = schema.stamp(
                    {
                        "step": rec.get("step", float(i)),
                        "reason": "nonfinite_loss_or_grad",
                        "policy": (
                            "skip" if "skipped_nonfinite" in rec else "warn"
                        ),
                        "count": len(flagged),
                        "flagged_iterations": flagged,
                        "loss": rec.get("loss"),
                        "grad_norm": rec.get("grad_norm"),
                    },
                    kind="anomaly",
                )
                if metrics_writer is not None:
                    metrics_writer.write(anomaly)
                else:
                    flight.observe_event(anomaly)
    except BaseException as e:
        # The postmortem the crash would otherwise take with it: dump the
        # last-N event buffer (no-op without a global recorder), then
        # re-raise unchanged.
        flight.dump_flight_recorder(
            "fit-loop-exception",
            context={
                "exception": f"{type(e).__name__}: {e}"[:300],
                "at_iteration": i,
            },
        )
        raise
    return history


class Trainer:
    """Single-host convenience wrapper: jit, data iteration, metric logging.

    The distributed path (glom_tpu.parallel.runtime.DistributedTrainer)
    reuses make_train_step under pjit — this class is the 1-device base.
    """

    def __init__(
        self,
        cfg: GlomConfig,
        tcfg: TrainConfig,
        *,
        optimizer: Optional[optax.GradientTransformation] = None,
        consensus_fn: Optional[ConsensusFn] = None,
        metrics_writer=None,
    ):
        self.cfg = cfg
        self.tcfg = tcfg
        key = jax.random.PRNGKey(tcfg.seed)
        self.rng, init_key = jax.random.split(key)
        self.state, self.optimizer = create_train_state(init_key, cfg, tcfg, optimizer)
        # Single device: dp == 1, so ZeRO resolves to 0 (validating the
        # configured value), quantized_reduce resolves OFF (no wire to
        # emulate a hop on), and the live-bytes model reports the fully
        # replicated layout with zero collective traffic — the baseline
        # row the distributed records are compared against.
        self.zero_stage = resolve_zero_stage(tcfg, 1)
        self.quantized_reduce = resolve_quantized_reduce(tcfg, 1)
        self.telemetry_level = diag.resolve_telemetry_level(tcfg)
        step_fn = make_train_step(
            cfg, tcfg, self.optimizer, consensus_fn=consensus_fn,
            quantized_reduce=self.quantized_reduce,
        )
        self.vjp_path = step_fn.vjp_path
        self.grad_accum = step_fn.grad_accum
        from glom_tpu.utils.metrics import comm_volume_model, live_bytes_model

        mem = live_bytes_model(
            self.state.params, self.state.opt_state, axis_sizes={},
            param_specs=None, opt_specs=None, grad_specs=None,
        )
        self._static_record = {
            "zero_stage": self.zero_stage,
            "quantized_reduce": self.quantized_reduce,
            "telemetry_level": self.telemetry_level,
            **mem,
            **comm_volume_model(
                mem["grads_bytes_per_replica"],
                mem["params_bytes_per_replica"],
                1,
                self.zero_stage,
            ),
        }
        from glom_tpu.tracing.memory import model_live_bytes_total

        self._model_live_bytes = model_live_bytes_total(self._static_record)
        self._step = jax.jit(step_fn, donate_argnums=(0,))
        fast_fn = make_train_step(
            cfg, tcfg, self.optimizer,
            consensus_fn=consensus_fn, with_grad_norm=False,
            quantized_reduce=self.quantized_reduce,
        )
        self._step_fast = jax.jit(fast_fn, donate_argnums=(0,))
        self.metrics_writer = metrics_writer
        # Persistent across fit() calls: span 2+ of a checkpointed run is
        # warm, and its first steps are steady-state samples, not compiles.
        self._compile_tracker = set()

    def _annotate(self, metrics) -> dict:
        """Static routing facts, attached OUTSIDE jit (strings can't ride
        the compiled metrics dict) — a run's records must name the backward
        it actually used (same discipline as sp_strategy). Watchdog backend
        state rides every record too (a dict read; the probe itself lives
        in the global watchdog, not here)."""
        from glom_tpu.telemetry.watchdog import backend_record

        metrics = dict(metrics)
        metrics["vjp_path"] = self.vjp_path
        metrics["grad_accum"] = self.grad_accum
        metrics.update(self._static_record)
        metrics.update(backend_record())
        return metrics

    def step(self, batch) -> dict:
        self.rng, step_rng = jax.random.split(self.rng)
        self.state, metrics = self._step(self.state, batch, step_rng)
        return self._annotate(metrics)

    def step_fast(self, batch) -> dict:
        """The sustained-throughput step: no grad-norm sweep (fit runs this
        on non-logging iterations)."""
        self.rng, step_rng = jax.random.split(self.rng)
        self.state, metrics = self._step_fast(self.state, batch, step_rng)
        return self._annotate(metrics)

    def _memory_record(self) -> dict:
        """Live HBM watermarks reconciled against the analytic live-bytes
        model (tracing/memory.py) — {} on backends with no allocator stats
        (the CPU fallback). fit_loop stamps this on every logging record."""
        from glom_tpu.tracing.memory import memory_record

        return memory_record(self._model_live_bytes)

    def fit(
        self,
        data: Iterator[jnp.ndarray],
        num_steps: int,
        *,
        log_every: int = 10,
        prefetch: int = 0,
        trace_capture=None,
    ) -> list[dict]:
        """Run `num_steps` updates pulling [b, c, H, W] batches from `data`.
        prefetch > 0 stages that many upcoming batches on device from a
        background thread (hides the host->device transfer).

        CAUTION: prefetch wraps `data` PER CALL. Calling fit(prefetch=N)
        repeatedly over one shared iterator (e.g. a checkpoint-span loop)
        discards up to N staged batches at every boundary, skewing the
        stream vs prefetch=0. For that pattern, wrap once yourself with
        data.prefetch_to_device and pass prefetch=0 here — see
        train/cli.py for the reference usage."""
        if prefetch > 0:
            from glom_tpu.data import prefetch_to_device

            data = prefetch_to_device(data, size=prefetch)
        return fit_loop(
            self.step,
            data,
            num_steps,
            log_every=log_every,
            metrics_writer=self.metrics_writer,
            step_fast=self.step_fast,
            compile_tracker=self._compile_tracker,
            trace_capture=trace_capture,
            memory_probe=self._memory_record,
        )
