"""Training objectives.

The reference ships no trainer — its only "training loop" is the README's
self-supervised denoising recipe (README :30-75, SURVEY.md §3.3):

    noised     = img + randn_like(img)
    all_levels = model(noised, return_all=True)     # [T+1, b, n, L, d]
    top        = all_levels[k, :, :, -1]            # mid-iteration top level
    recon      = patches_to_images(top)             # Linear(d -> p*p*c) + unpatchify
    loss       = F.mse_loss(img, recon)

This module provides that objective as a pure, jit/grad/pjit-composable
function. One deliberate optimization over the reference: the loss depends
only on iterations 1..k, so we scan exactly k iterations and take the final
top level instead of materializing the full [T+1, ...] stack — identical
math and gradients (iterations k+1..T are dead code for this loss; torch
autograd also never touches them), but O(1) rather than O(T) activation
memory before remat even enters the picture.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from glom_tpu.models.core import ConsensusFn, GlomParams, glom_forward, init_glom
from glom_tpu.ops.patch import LinearParams, init_linear, tokens_to_image
from glom_tpu.utils.config import GlomConfig


class DenoiseParams(NamedTuple):
    """GLOM params + the reconstruction head from the README recipe."""

    glom: GlomParams
    to_pixels: LinearParams  # Linear(d -> p*p*c)


def init_denoise(key: jax.Array, cfg: GlomConfig, dtype=jnp.float32) -> DenoiseParams:
    k_glom, k_pix = jax.random.split(key)
    return DenoiseParams(
        glom=init_glom(k_glom, cfg, dtype),
        to_pixels=init_linear(k_pix, cfg.dim, cfg.patch_dim, dtype),
    )


def default_recon_index(iters: int) -> int:
    """Which stacked state feeds the reconstruction head.

    The reference README hardcodes index 7 for L=6 (T=2L=12): the
    mid-iteration top level, after information has gone up and come back
    down once. Generalized as T//2 + 1, which reproduces 7 at T=12.
    """
    return iters // 2 + 1


def denoise_loss(
    params: DenoiseParams,
    img: jnp.ndarray,
    noise: jnp.ndarray,
    cfg: GlomConfig,
    *,
    recon_index: Optional[int] = None,
    iters: Optional[int] = None,
    remat: bool = False,
    compute_dtype=None,
    consensus_fn: Optional[ConsensusFn] = None,
    use_pallas: bool = False,
    unroll: bool = False,
    with_diagnostics: bool = False,
) -> jnp.ndarray:
    """MSE between the clean image and the reconstruction from the noised
    image's top level at iteration `recon_index`.

    with_diagnostics=True (telemetry_level="full") returns (loss, aux)
    where aux carries per-level consensus-agreement stats computed from
    the SAME final state the loss already materializes — one extra [L]
    reduction, no second forward (telemetry/diagnostics.level_agreement)."""
    T = iters if iters is not None else cfg.default_iters
    k = recon_index if recon_index is not None else default_recon_index(T)
    if not 1 <= k <= T:
        raise ValueError(f"recon_index {k} outside 1..{T}")

    noised = img + noise
    final = glom_forward(
        params.glom,
        noised,
        cfg,
        iters=k,  # iterations k+1..T are dead for this loss; don't run them
        remat=remat,
        compute_dtype=compute_dtype,
        consensus_fn=consensus_fn,
        use_pallas=use_pallas,
        unroll=unroll,
    )
    top = final[:, :, -1]  # [b, n, d] — the top level
    with jax.named_scope("reconstruction"):
        recon = tokens_to_image(
            params.to_pixels, top.astype(img.dtype), cfg.patch_size, cfg.image_size
        )
    loss = jnp.mean((img - recon) ** 2)
    if with_diagnostics:
        from glom_tpu.telemetry.diagnostics import level_agreement

        # Stop-gradient: the agreement stat is observability, not a term
        # of the objective — it must not leak into the backward.
        aux = {"level_agreement": level_agreement(jax.lax.stop_gradient(final))}
        return loss, aux
    return loss


def reconstruct(
    params: DenoiseParams,
    img: jnp.ndarray,
    cfg: GlomConfig,
    *,
    recon_index: Optional[int] = None,
    iters: Optional[int] = None,
    compute_dtype=None,
    consensus_fn: Optional[ConsensusFn] = None,
) -> jnp.ndarray:
    """Inference-side reconstruction (for eval / visual inspection).

    Pass the SAME consensus_fn the model was trained with — evaluating a
    custom-consensus model with the default dense op is a silent mismatch.
    """
    T = iters if iters is not None else cfg.default_iters
    k = recon_index if recon_index is not None else default_recon_index(T)
    final = glom_forward(
        params.glom,
        img,
        cfg,
        iters=k,
        compute_dtype=compute_dtype,
        consensus_fn=consensus_fn,
    )
    return tokens_to_image(
        params.to_pixels, final[:, :, -1].astype(img.dtype), cfg.patch_size, cfg.image_size
    )
