"""Crash flight recorder: the last N telemetry events, saved at the moment
of death.

Rounds 4-5 ended with `backend-init-unavailable` records and nothing else —
no record of what the final steps looked like before the backend wedged.
The flight recorder is the bounded postmortem buffer every long-running
system keeps: a ring of the last `capacity` schema-stamped events (steps,
spans, watchdog transitions, anomalies) fed by the sinks that already see
every record (MetricsWriter, sinks.emit, the fit loop), dumped to
`flight_<ts>.jsonl` when something dies:

    * a watchdog "down" transition lands in the stream,
    * an anomaly storm (>= storm_threshold "anomaly" events inside
      storm_window_s — the NaN-cascade signature),
    * SIGTERM / interpreter exit (install_process_hooks; the preemption
      path on TPU pods),
    * an unhandled exception inside fit_loop (trainer.py calls
      dump_flight_recorder before re-raising).

Dumps are plain JSONL: a stamped "note" header (trigger, event count,
context) followed by the buffered events in arrival order, each carrying a
monotonic `flight_seq` — `python -m glom_tpu.telemetry flight_*.jsonl`
lints a dump like any other log, and CI does. Pure stdlib, thread-safe,
and observe() never raises into the caller: the recorder must keep working
in exactly the broken states it exists to document.

Registration mirrors the watchdog's process-global pattern: sinks call
`observe_event(rec)` (a no-op until `set_global_flight_recorder`), so no
handle threading is needed.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from pathlib import Path
from typing import Callable, List, Optional


class FlightRecorder:
    """Bounded ring of stamped telemetry events + triggered JSONL dumps."""

    def __init__(
        self,
        dump_dir: str,
        capacity: int = 256,
        *,
        storm_threshold: int = 3,
        storm_window_s: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if capacity < 1:
            raise ValueError(f"capacity={capacity} must be >= 1")
        if storm_threshold < 1:
            raise ValueError(f"storm_threshold={storm_threshold} must be >= 1")
        self.dump_dir = Path(dump_dir)
        self.capacity = capacity
        self.storm_threshold = storm_threshold
        self.storm_window_s = storm_window_s
        self._clock = clock
        self._lock = threading.RLock()
        self._buf: deque = deque(maxlen=capacity)
        self._seq = 0
        self._last_dump_seq = 0
        self._anomaly_times: deque = deque()
        self.dumps: List[str] = []  # paths written, oldest first
        # Preemption checkpoint hook (set_checkpoint_hook): run a bounded
        # save before the SIGTERM dump so a preempted worker leaves a
        # RESUMABLE step, not just a postmortem.
        self._checkpoint_fn: Optional[Callable[[], Optional[int]]] = None
        self._checkpoint_deadline_s: float = 30.0

    # -- feed --------------------------------------------------------------

    def observe(self, rec: dict) -> None:
        """Buffer one stamped event; fire a dump when it is a trigger.
        Never raises — a postmortem buffer that can crash the run it
        documents is worse than none."""
        try:
            trigger = None
            with self._lock:
                self._seq += 1
                self._buf.append({**rec, "flight_seq": self._seq})
                kind = rec.get("kind")
                if kind == "watchdog" and rec.get("backend_state") == "down":
                    trigger = "backend-down"
                elif kind in ("anomaly", "slo_breach"):
                    # SLO breaches (telemetry/aggregate.SLOMonitor) count
                    # toward the same storm trigger as NaN anomalies: a
                    # burst of breaches is a serving incident, and the
                    # ring should dump itself while the evidence is hot.
                    now = self._clock()
                    self._anomaly_times.append(now)
                    while (
                        self._anomaly_times
                        and now - self._anomaly_times[0] > self.storm_window_s
                    ):
                        self._anomaly_times.popleft()
                    if len(self._anomaly_times) >= self.storm_threshold:
                        trigger = "anomaly-storm"
                        self._anomaly_times.clear()
            if trigger is not None:
                self.dump(trigger)
        except Exception:
            pass

    # Writer protocol: a FlightRecorder can sit anywhere a MetricsWriter
    # can (e.g. as a BackendWatchdog's writer).
    write = observe

    # -- dump --------------------------------------------------------------

    def dump(self, trigger: str, *, context: Optional[dict] = None) -> Optional[str]:
        """Write the buffered events to flight_<ts>_<seq>.jsonl; returns the
        path, or None when nothing new arrived since the last dump (the
        atexit hook after a triggered dump must not write an empty twin)."""
        from glom_tpu.telemetry import schema

        with self._lock:
            if self._seq == self._last_dump_seq:
                return None
            events = list(self._buf)
            self._last_dump_seq = self._seq
            self.dump_dir.mkdir(parents=True, exist_ok=True)
            ts = time.strftime("%Y%m%d_%H%M%S")
            path = self.dump_dir / f"flight_{ts}_{self._seq:06d}.jsonl"
            header = schema.stamp(
                {
                    "note": "flight-recorder dump",
                    "trigger": trigger,
                    "n_events": len(events),
                    "capacity": self.capacity,
                    "wall_time_s": round(time.time(), 3),
                    **(context or {}),
                },
                kind="note",
            )
            with open(path, "w") as fh:
                fh.write(json.dumps(header, default=str) + "\n")
                for e in events:
                    fh.write(json.dumps(e, default=str) + "\n")
            self.dumps.append(str(path))
            return str(path)

    # -- process hooks -----------------------------------------------------

    def set_checkpoint_hook(
        self,
        checkpoint_fn: Optional[Callable[[], Optional[int]]],
        *,
        deadline_s: float = 30.0,
    ) -> None:
        """Grow the SIGTERM (preemption) hook a checkpoint step: before the
        flight dump, `checkpoint_fn` — typically `lambda: save-and-wait the
        current TrainState, returning the step` — runs in a daemon thread
        bounded by `deadline_s` (TPU preemption notices give a fixed grace
        window; a save that can't land inside it must not stall the dump or
        the exit). The outcome is stamped as a schema "recovery" event
        (action "preemption-checkpoint", ok/step/elapsed_s) into the ring
        ahead of the dump, so the postmortem records whether a resumable
        step was left behind. A hook may return a dict instead of a bare
        step — its fields merge into the recovery record (the pod save
        barrier returns step/round/n_hosts that way). Installed separately from
        install_process_hooks because the trainer/manager usually exist
        only after the hooks do (train/cli.py installs hooks first thing).
        Pass None to remove."""
        with self._lock:
            self._checkpoint_fn = checkpoint_fn
            self._checkpoint_deadline_s = deadline_s

    def _preemption_checkpoint(self) -> None:
        """Run the bounded checkpoint hook; never raises (the SIGTERM
        handler must always reach the dump and the chained handler)."""
        with self._lock:
            fn = self._checkpoint_fn
            deadline = self._checkpoint_deadline_s
        if fn is None:
            return
        try:
            from glom_tpu.telemetry import schema

            result: List = [None, None]  # [step, exception]

            def run():
                try:
                    result[0] = fn()
                except BaseException as e:  # noqa: BLE001 — relayed on the record
                    result[1] = e

            t0 = time.monotonic()
            worker = threading.Thread(
                target=run, name="glom-preempt-ckpt", daemon=True
            )
            worker.start()
            worker.join(timeout=deadline)
            elapsed = time.monotonic() - t0
            ok = not worker.is_alive() and result[1] is None
            rec = {
                "action": "preemption-checkpoint",
                "ok": ok,
                "deadline_s": deadline,
                "elapsed_s": round(elapsed, 3),
                "wall_time_s": round(time.time(), 3),
            }
            if isinstance(result[0], dict):
                # Pod-mode hooks (resilience/coordinator.pod_preemption_
                # save) return the whole barrier outcome — committed
                # step, round id, n_hosts — which rides the recovery
                # record so one stamped event tells the coordinated
                # story; plain hooks keep returning the bare step.
                rec.update(result[0])
            elif result[0] is not None:
                rec["step"] = result[0]
            if worker.is_alive():
                rec["note"] = "save overran the deadline; dumping anyway"
                # The postmortem's first question is "stuck WHERE": snap
                # the overrunning thread's live stack into the record
                # (sys._current_frames is a point-in-time copy, no pause).
                import sys
                import traceback

                frame = sys._current_frames().get(worker.ident)
                if frame is not None:
                    rec["stuck_at"] = [
                        ln.strip()
                        for ln in traceback.format_stack(frame)[-4:]
                    ]
            elif result[1] is not None:
                rec["note"] = f"{type(result[1]).__name__}: {result[1]}"[:300]
            self.observe(schema.stamp(rec, kind="recovery"))
        except Exception:
            pass

    def install_process_hooks(self, *, sigterm: bool = True, on_exit: bool = True):
        """Dump on SIGTERM (the pod-preemption path) and at interpreter
        exit. SIGTERM chains any previously installed handler; installing
        from a non-main thread (where signal.signal raises) skips the
        signal hook silently. When a checkpoint hook is set
        (set_checkpoint_hook), SIGTERM first runs the bounded preemption
        save so the dump records a resumable step. Returns self."""
        if on_exit:
            import atexit

            atexit.register(self._dump_atexit)
        if sigterm:
            import signal

            try:
                prev = signal.getsignal(signal.SIGTERM)

                def _handler(signum, frame):
                    self._preemption_checkpoint()
                    self.dump("sigterm")
                    if callable(prev):
                        prev(signum, frame)
                    elif prev is signal.SIG_IGN:
                        # The host intentionally ignored SIGTERM; dumping
                        # must not convert 'ignored' into 'terminated'.
                        return
                    else:
                        raise SystemExit(128 + signum)

                signal.signal(signal.SIGTERM, _handler)
            except ValueError:
                pass
        return self

    def _dump_atexit(self) -> None:
        try:
            self.dump("atexit")
        except Exception:
            pass


# -- process-global registration (same pattern as the watchdog) ------------

_GLOBAL: Optional[FlightRecorder] = None


def set_global_flight_recorder(fr: Optional[FlightRecorder]) -> None:
    global _GLOBAL
    _GLOBAL = fr


def get_global_flight_recorder() -> Optional[FlightRecorder]:
    return _GLOBAL


def observe_event(rec: dict) -> None:
    """Feed one stamped event to the global recorder; no-op without one.
    Called by MetricsWriter.write, sinks.emit, the fit loop, and watchdog
    transitions — the places every telemetry record already flows through."""
    fr = _GLOBAL
    if fr is not None:
        fr.observe(rec)


def write_or_observe(writer, rec: dict) -> None:
    """THE writer-else-flight fallback every writerless sink takes: a
    stamped record goes to `writer` when one is attached (MetricsWriter
    already forwards to the flight ring — feeding both would double-buffer
    it), else straight to the global recorder so a writerless run still
    leaves a postmortem trail. One definition, not five copies (watchdog,
    serve engine/batcher, checkpoint spans, prefetch spans)."""
    if writer is not None:
        writer.write(rec)
    else:
        observe_event(rec)


def dump_flight_recorder(
    trigger: str, *, context: Optional[dict] = None
) -> Optional[str]:
    """Force a dump of the global recorder (the fit-loop exception path);
    no-op without one. Never raises."""
    fr = _GLOBAL
    if fr is None:
        return None
    try:
        return fr.dump(trigger, context=context)
    except Exception:
        return None
