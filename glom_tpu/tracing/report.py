"""Perf reporting: the MFU rollup and the rolling step timer.

Moved verbatim from the utils/profiling.py stub when it grew into the
tracing package (that module re-exports these for compatibility); built on
the analytic FLOP model in utils/metrics.py.
"""

from __future__ import annotations

import time
from typing import Optional

from glom_tpu.utils.config import GlomConfig
from glom_tpu.utils.metrics import flops_per_column_iter, mfu


def perf_report(
    cfg: GlomConfig,
    *,
    column_iters_per_sec: float,
    chip: str = "v5e",
    num_chips: int = 1,
    backward: bool = False,
) -> dict:
    """Assemble the north-star metrics dict from a measured rate."""
    return {
        "column_iters_per_sec_per_chip": column_iters_per_sec / num_chips,
        "flops_per_column_iter": flops_per_column_iter(cfg),
        "mfu": mfu(
            cfg, column_iters_per_sec / num_chips, chip=chip, backward=backward
        ),
        "chip": chip,
        "num_chips": num_chips,
    }


class StepTimer:
    """Rolling wall-clock step timer that syncs on a supplied scalar, for
    platforms where block_until_ready is unreliable (see bench.py)."""

    def __init__(self):
        self._t0: Optional[float] = None
        self.history: list[float] = []

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self, sync_scalar=None) -> float:
        if sync_scalar is not None:
            float(sync_scalar)  # host fetch = real synchronization
        dt = time.perf_counter() - self._t0
        self.history.append(dt)
        return dt

    @property
    def best(self) -> float:
        return min(self.history)
