"""HBM accounting: live device watermarks reconciled against the model.

`utils/metrics.live_bytes_model` PRICES the train state's live bytes from
abstract shapes (the "recorded even with no chip" contract). This module
MEASURES the other side: the runtime's allocator stats
(`device.memory_stats()` — bytes_in_use / peak_bytes_in_use / bytes_limit
on TPU backends) — and stamps the reconciliation between the two on every
logging record, the same measured-vs-modeled discipline as PR 2's
collective counters (`comm_model_drift`):

    hbm_model_drift = (hbm_bytes_in_use - model_live_bytes) / model_live_bytes

Reading it: the analytic model prices the train-state tenants only (params
+ grad buffer + optimizer moments), so between steps the drift ≈ the
allocator's overhead + anything else resident; DURING a step the gap to
`hbm_peak_bytes` is the activation working set — which is why both
watermarks ride the record. A drift that grows step over step is a leak;
a peak near `hbm_bytes_limit` explains the next OOM before it happens.

Every function here degrades to {} instead of raising: CPU backends return
no stats (memory_stats() is None), and memory accounting must never be the
thing that takes a run down.
"""

from __future__ import annotations

from typing import Optional

# memory_stats key -> stamped record field. Allocator key names vary by
# backend/runtime version; only the ones present are stamped.
_STAT_FIELDS = (
    ("bytes_in_use", "hbm_bytes_in_use"),
    ("peak_bytes_in_use", "hbm_peak_bytes"),
    ("bytes_limit", "hbm_bytes_limit"),
    ("largest_free_block_bytes", "hbm_largest_free_block_bytes"),
)


def device_memory_stats(device=None) -> Optional[dict]:
    """Raw allocator stats for `device` (default: first local device), or
    None when the backend has none (CPU) or jax itself is unavailable."""
    try:
        if device is None:
            import jax

            device = jax.local_devices()[0]
        stats = device.memory_stats()
    except Exception:
        return None
    return stats or None


def hbm_watermarks(device=None) -> dict:
    """The stamped watermark fields, or {} when the backend reports none."""
    stats = device_memory_stats(device)
    if not stats:
        return {}
    out = {}
    for src, dst in _STAT_FIELDS:
        v = stats.get(src)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[dst] = int(v)
    return out


def memory_record(model_live_bytes: Optional[int] = None, device=None) -> dict:
    """Watermarks + model reconciliation for a metrics record. Never
    raises; {} when the backend has no allocator stats (the CPU fallback —
    the analytic model keys on the record are then the only memory story,
    exactly as before)."""
    try:
        out = hbm_watermarks(device)
    except Exception:  # pragma: no cover - hbm_watermarks already guards
        return {}
    if not out:
        return {}
    if model_live_bytes and model_live_bytes > 0 and "hbm_bytes_in_use" in out:
        out["hbm_model_live_bytes"] = int(model_live_bytes)
        out["hbm_model_drift"] = round(
            (out["hbm_bytes_in_use"] - model_live_bytes) / model_live_bytes, 6
        )
    return out


def model_live_bytes_total(static_record: dict) -> int:
    """The analytic live-bytes total the drift reconciles against: the
    three train-state tenants the trainers already stamp (live_bytes_model
    keys in their _static_record)."""
    return int(
        static_record.get("params_bytes_per_replica", 0)
        + static_record.get("grads_bytes_per_replica", 0)
        + static_record.get("opt_bytes_per_replica", 0)
    )
