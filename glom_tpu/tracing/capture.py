"""Programmatic XLA trace capture: step-windowed XProf traces on demand.

`--profile-dir` (train/cli.py) wraps a WHOLE run in one trace — unusable
past a few hundred steps (multi-GB trace, compile noise swamping steady
state). TraceCapture is the step-windowed form every serious harness ends
up with: `--trace-steps A:B` opens `jax.profiler.start_trace` right before
step A and closes it after step B, stamps the window's metadata (trace
dir, first/last step) into the telemetry event stream as "note" records,
and marks each captured step with `jax.profiler.StepTraceAnnotation` so
XProf's step view lines up with the trainer's step numbers.

The step counter lives on the TraceCapture object itself, so a window can
span checkpoint-span boundaries (the CLI calls fit() once per span over
one shared capture). jax is imported lazily inside methods: constructing
and parsing never touches a backend, and tests monkeypatch `jax.profiler`
to run without one.

View captures with: tensorboard --logdir <trace_dir>  (or xprof).
"""

from __future__ import annotations

import contextlib
from typing import Tuple


def parse_trace_steps(spec: str) -> Tuple[int, int]:
    """'A:B' -> (first, last) inclusive; a bare 'A' captures one step."""
    parts = spec.split(":")
    try:
        if len(parts) == 1:
            first = last = int(parts[0])
        elif len(parts) == 2:
            first, last = int(parts[0]), int(parts[1])
        else:
            raise ValueError
    except ValueError:
        raise ValueError(
            f"--trace-steps {spec!r}: expected 'A:B' (or a bare step 'A')"
        ) from None
    if first < 0 or last < first:
        raise ValueError(
            f"--trace-steps {spec!r}: need 0 <= first <= last"
        )
    return first, last


class TraceCapture:
    """A [first, last]-inclusive step window around jax.profiler traces.

    Wrap each training step (or bench measurement unit) in `unit()`; the
    capture opens the trace when its internal counter hits `first` and
    closes it after `last`. `writer` (anything with .write(dict)) receives
    the stamped start/stop metadata events; without one they fall through
    to telemetry.sinks.emit (stdout), so bench logs carry them too.

    NOTE on async dispatch: the window bounds step DISPATCH; device
    execution of the last steps may spill slightly past stop_trace. The
    profiler still attributes whatever executed inside the window — for
    exact per-step walls read the StepTraceAnnotation markers, not the
    window edges.
    """

    def __init__(self, first: int, last: int, trace_dir: str, *, writer=None):
        if first < 0 or last < first:
            raise ValueError(f"need 0 <= first <= last, got {first}:{last}")
        self.first = first
        self.last = last
        self.trace_dir = trace_dir
        self.writer = writer
        self._count = 0  # units seen (monotonic across fit() spans)
        self._active = False
        self._captured = 0
        self._closed = False

    @classmethod
    def parse(cls, spec: str, trace_dir: str, *, writer=None) -> "TraceCapture":
        first, last = parse_trace_steps(spec)
        return cls(first, last, trace_dir, writer=writer)

    # -- event plumbing ----------------------------------------------------

    def _emit(self, rec: dict) -> None:
        from glom_tpu.telemetry import schema

        rec = schema.stamp(rec, kind="note")
        if self.writer is not None:
            self.writer.write(rec)
        else:
            from glom_tpu.telemetry.sinks import emit

            emit(rec, kind="note")

    # -- the window --------------------------------------------------------

    def _start(self) -> None:
        import jax

        jax.profiler.start_trace(self.trace_dir)
        self._active = True
        self._emit(
            {
                "note": "xla-trace-start",
                "trace_dir": self.trace_dir,
                "first_step": self._count,
                "trace_steps": f"{self.first}:{self.last}",
            }
        )

    def _stop(self, *, reason: str = "window-complete") -> None:
        import jax

        try:
            jax.profiler.stop_trace()
        finally:
            self._active = False
        self._emit(
            {
                "note": "xla-trace-stop",
                "trace_dir": self.trace_dir,
                "last_step": self._count - 1 if self._captured else None,
                "steps_captured": self._captured,
                "reason": reason,
            }
        )

    @contextlib.contextmanager
    def unit(self):
        """Wrap ONE step/measurement unit; yields the unit's index."""
        i = self._count
        if not self._closed and not self._active and i == self.first:
            self._start()
        ann = None
        if self._active:
            try:
                import jax

                ann = jax.profiler.StepTraceAnnotation("step", step_num=i)
                ann.__enter__()
            except Exception:
                ann = None
        try:
            yield i
        finally:
            if ann is not None:
                try:
                    ann.__exit__(None, None, None)
                except Exception:
                    pass
            self._count += 1
            if self._active:
                self._captured += 1
                if i >= self.last:
                    self._stop()

    def close(self) -> None:
        """Idempotent teardown: stops a still-open window (a run that ended
        before reaching step B must not leak a profiler session) and stamps
        the truncation in the event stream."""
        if self._closed:
            return
        self._closed = True
        if self._active:
            self._stop(reason="truncated-by-close")


# -- whole-block capture (the original profiling.py surface) ---------------


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/glom_tpu_trace"):
    """Capture a profiler trace of the enclosed block.

    View with: tensorboard --logdir <log_dir>  (or xprof).
    """
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


def start_server(port: int = 9999):
    """On-demand profiling: connect TensorBoard's profile tab to this port
    while training runs (the 'attach to a live job' workflow)."""
    import jax

    return jax.profiler.start_server(port)


def annotate(name: str):
    """Trace annotation decorator for host-side phases (data loading, eval)."""

    def deco(fn):
        import jax

        return jax.profiler.annotate_function(fn, name=name)

    return deco
