"""Tracing: software spans, programmatic XLA capture, HBM accounting, and
the crash flight recorder (docs/OBSERVABILITY.md).

The 90-line profiling stub grew into this subsystem because the telemetry
stream (PR 2) can say a run was slow or died, but not WHERE the time and
HBM went or what the last N steps looked like before the crash. Layers:

    spans    — host-side span() context manager + per-phase aggregation,
               emitting versioned "span" JSONL events; works on the CPU
               fallback where XProf cannot
    capture  — programmatic XLA trace windows (--trace-steps A:B around
               jax.profiler.start_trace/stop_trace) + the whole-block
               trace() context manager and profiler server
    memory   — live HBM watermarks from device memory stats, reconciled
               against the analytic live-bytes model (utils/metrics.py)
    flight   — bounded ring buffer of the last N telemetry events, dumped
               to flight_<ts>.jsonl on backend-down, anomaly storm,
               SIGTERM/atexit, or an unhandled fit_loop exception
    report   — MFU perf report + the rolling StepTimer (moved from the
               utils/profiling.py stub, which re-exports for compat)

Re-exports are LAZY (PEP 562, same pattern as glom_tpu/telemetry): spans
and flight are pure stdlib and must stay importable in a jax-broken
environment (the wedged-image scenario the flight recorder exists for);
capture/memory import jax only inside the functions that need it.
"""

_EXPORTS = {
    "PHASES": "spans",
    "SpanAggregator": "spans",
    "span": "spans",
    "spanned": "spans",
    "TraceCapture": "capture",
    "annotate": "capture",
    "start_server": "capture",
    "trace": "capture",
    "hbm_watermarks": "memory",
    "memory_record": "memory",
    "FlightRecorder": "flight",
    "dump_flight_recorder": "flight",
    "get_global_flight_recorder": "flight",
    "observe_event": "flight",
    "set_global_flight_recorder": "flight",
    "StepTimer": "report",
    "perf_report": "report",
}
_SUBMODULES = ("spans", "capture", "memory", "flight", "report")

__all__ = sorted([*_EXPORTS, *_SUBMODULES])


def __getattr__(name):
    import importlib

    if name in _SUBMODULES:
        return importlib.import_module(f"glom_tpu.tracing.{name}")
    if name in _EXPORTS:
        module = importlib.import_module(f"glom_tpu.tracing.{_EXPORTS[name]}")
        return getattr(module, name)
    raise AttributeError(f"module 'glom_tpu.tracing' has no attribute {name!r}")
