"""Host-side software spans: where the WALL TIME went, on any backend.

XProf answers "where did device time go" — but only when a profiler backend
exists, which is exactly what rounds 4-5 did not have. These spans are the
host-side complement: a `span()` context manager that times a named block
with `time.perf_counter`, tracks nesting on a thread-local stack, and emits
versioned "span" JSONL events into the same stream every other telemetry
record rides, so a CPU-fallback run (or a wedged-tunnel postmortem) still
attributes time per phase.

Naming: in-graph phases already carry `jax.named_scope` names (bottom_up /
top_down / consensus / mean_update in models/core.py — mirrored here as
PHASES so span streams and XProf traces group under one vocabulary); host
phases the fit loop times are prefixed `host_` (host_data_next,
host_step_dispatch, host_log_fetch). `span(..., annotate=True)` also enters
a `jax.profiler.TraceAnnotation`, so when an XLA capture window is open the
same block shows up in XProf under the same name.

Cost: a bare span (aggregator only, no writer) is two perf_counter calls
plus dict arithmetic — single-digit microseconds. The fit loop therefore
aggregates per-name between logging steps (SpanAggregator) and emits one
rollup span event per phase per logging record instead of two JSONL lines
per step; `python bench_train.py --span-ab` keeps the measured overhead
under the 1% bar. Pure stdlib: importable with jax broken or absent.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Optional

# The scan body's jax.named_scope vocabulary (models/core.py) — span names
# for in-graph phases must come from here so host events and XProf traces
# group identically.
PHASES = ("bottom_up", "top_down", "consensus", "mean_update")

# The serving stack's host phases (glom_tpu/serve): one request's path is
# enqueue -> (gathered into a) batch -> dispatch (the compiled forward) ->
# fetch (device->host of the valid rows). The batcher aggregates these the
# same way fit_loop aggregates its host_ phases.
SERVE_PHASES = (
    "serve_enqueue",
    "serve_batch",
    "serve_dispatch",
    "serve_fetch",
)

_local = threading.local()


def _stack() -> list:
    if not hasattr(_local, "stack"):
        _local.stack = []
    return _local.stack


def current_span() -> Optional[str]:
    """Name of the innermost open span on this thread, or None."""
    stack = _stack()
    return stack[-1] if stack else None


class SpanAggregator:
    """Per-name rollup of closed spans (count / total / max), drained into
    stamped "span" records at each logging boundary — the <1%-overhead form
    of per-step span events. Thread-safe: the prefetch thread's spans can
    land in the same aggregator as the fit loop's."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stats: dict = {}  # name -> [count, total_s, max_s]

    def observe(self, name: str, dur_s: float) -> None:
        with self._lock:
            st = self._stats.get(name)
            if st is None:
                self._stats[name] = [1, dur_s, dur_s]
            else:
                st[0] += 1
                st[1] += dur_s
                if dur_s > st[2]:
                    st[2] = dur_s

    def records(self, *, reset: bool = True, extra: Optional[dict] = None):
        """One stamped span record per name seen since the last drain:
        dur_s is the TOTAL seconds in that phase (the attribution number);
        count/mean_ms/max_ms unpack it."""
        from glom_tpu.telemetry import schema

        with self._lock:
            stats = self._stats
            if reset:
                self._stats = {}
            else:
                stats = dict(stats)
        out = []
        for name in sorted(stats):
            count, total, mx = stats[name]
            rec = {
                "name": name,
                "dur_s": round(total, 6),
                "count": count,
                "mean_ms": round(1e3 * total / count, 4),
                "max_ms": round(1e3 * mx, 4),
            }
            if extra:
                rec.update(extra)
            out.append(schema.stamp(rec, kind="span"))
        return out


@contextmanager
def span(
    name: str,
    *,
    writer=None,
    aggregator: Optional[SpanAggregator] = None,
    annotate: bool = False,
    **fields,
):
    """Time the enclosed block as a named span.

    `writer` (anything with .write(dict), e.g. MetricsWriter) receives one
    stamped "span" event per close — start wall time, duration, nesting
    depth, and the enclosing span's name. `aggregator` rolls the duration
    into a SpanAggregator instead (the cheap fit-loop form; both may be
    given). `annotate=True` additionally enters jax.profiler.TraceAnnotation
    so an open XLA capture window shows the block under the same name —
    skipped silently when jax is broken or absent (the span itself must
    work in exactly that environment). Extra keyword `fields` ride the
    emitted event."""
    stack = _stack()
    parent = stack[-1] if stack else None
    stack.append(name)
    ann = None
    if annotate:
        try:
            import jax

            ann = jax.profiler.TraceAnnotation(name)
            ann.__enter__()
        except Exception:
            ann = None
    t_wall = time.time()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dur = time.perf_counter() - t0
        if ann is not None:
            try:
                ann.__exit__(None, None, None)
            except Exception:
                pass
        stack.pop()
        if aggregator is not None:
            aggregator.observe(name, dur)
        if writer is not None:
            from glom_tpu.telemetry import schema, tracectx

            rec = {
                "name": name,
                "dur_s": round(dur, 6),
                "t_start": round(t_wall, 3),
                "depth": len(stack),
            }
            if parent is not None:
                rec["parent"] = parent
            rec.update(fields)
            # A span closed under a serve dispatch scope carries that
            # dispatch's trace context — host time joins the request's
            # causal tree like every other stamped record.
            if not any(k in rec for k in ("trace_id", "trace_ids")):
                rec.update(tracectx.current_fields())
            writer.write(schema.stamp(rec, kind="span"))


def spanned(name: str, **span_kw):
    """Decorator form: time every call of `fn` as a span."""

    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with span(name, **span_kw):
                return fn(*args, **kwargs)

        return wrapper

    return deco
