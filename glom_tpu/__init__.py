"""glom_tpu — a TPU-native GLOM framework (JAX / XLA / Pallas / pjit).

A from-scratch, TPU-first implementation of the capabilities of the reference
`glom-pytorch` (Hinton's GLOM, arXiv:2102.12627): patch columns of L level
embeddings, iteratively updated by the mean of (previous value, bottom-up MLP,
top-down MLP, same-level cross-column consensus attention).

Layering (bottom to top):
  ops/       pure tensor ops (grouped per-level MLP, consensus attention,
             patchify) — the math contract, verified against a NumPy oracle
  kernels/   Pallas TPU kernels: fused grouped-MLP, blockwise consensus
             fused with the 4-way mean update (O(n) memory, block-sparse
             local masking)
  models/    the functional GLOM core (lax.scan over iterations) and the
             reference-compatible `Glom` API class
  train/     self-supervised denoising trainer, temporal/video mode
  parallel/  mesh (ICI + multi-slice DCN) / sharding / ring + halo + Ulysses
             sequence parallelism / the fully-manual shard_map path that
             runs the Pallas kernels under DP x SP
  serve/     batched inference engine: AOT-warmed compiled forwards per
             bucket, dynamic batching with shed, consensus early exit
  utils/     config presets, checkpointing, metrics, profiling
"""

from glom_tpu.version import __version__

__all__ = ["__version__"]


def __getattr__(name):
    # Lazy re-exports so `import glom_tpu` stays cheap and avoids importing
    # jax until a symbol is actually used.
    try:
        if name in ("Glom", "GlomParams", "glom_forward", "init_glom"):
            from glom_tpu.models import api, core

            mapping = {
                "Glom": api.Glom,
                "GlomParams": core.GlomParams,
                "glom_forward": core.glom_forward,
                "init_glom": core.init_glom,
            }
            return mapping[name]
        if name == "GlomConfig":
            from glom_tpu.utils.config import GlomConfig

            return GlomConfig
    except ImportError as e:
        raise AttributeError(f"module 'glom_tpu' has no attribute {name!r}") from e
    raise AttributeError(f"module 'glom_tpu' has no attribute {name!r}")
