"""Structured sinks: step-time histograms and the stamped bench emitter.

Two host-side pieces that complete the telemetry loop:

  * StepTimeStats — wall-clock per-step durations with the COMPILE step
    split out (the first step of a jitted loop is trace+compile; folding
    it into steady-state percentiles made round 4's "slow step" reports
    unreadable). Logging records carry p50/p95/max of steady state plus
    the compile time, so a step-time regression and a compile-time
    regression are separately attributable. Dispatch is async under jax —
    non-logging steps measure enqueue time, logging steps (which fetch the
    metrics) absorb the device sync, so p95/max bound the true step time
    while p50 tracks dispatch; docs/OBSERVABILITY.md spells out the
    reading. Pure host arithmetic: nanoseconds per step of overhead.

  * emit() — the benches' print(json.dumps(...)) replacement: stamps
    schema_version/kind and the current watchdog backend state on the
    record, so driver-parsed bench lines, trainer JSONL, and hw-queue rows
    are one schema (`python -m glom_tpu.telemetry.schema` lints them all).

  * bench_bootstrap() — the shared fail-fast gate every bench entrypoint
    runs before touching a backend: probe through the watchdog (throwaway
    subprocess — a wedged plugin HANGS in-process init), register it
    globally so every subsequent record stamps backend_state, fall back to
    CPU when the default platform is down, and when even CPU cannot
    initialize emit ONE schema-v2 "error" record with `value: null` —
    never the round-5 dead zero the trajectory tooling then ingested.
"""

from __future__ import annotations

import json
import sys
from typing import List, Optional

from glom_tpu.telemetry import schema, watchdog


def nearest_rank(sorted_samples: List[float], q: float) -> float:
    """Nearest-rank quantile over pre-sorted samples — THE 'p99'
    definition for the whole stack (per-host step histograms here, pod
    rollups in telemetry/aggregate.py), so the two never drift apart."""
    if not sorted_samples:
        return 0.0
    idx = min(len(sorted_samples) - 1, int(q * (len(sorted_samples) - 1) + 0.5))
    return sorted_samples[idx]


class StepTimeStats:
    """Streaming per-step wall-time stats with compile split out.

    observe(dt, is_compile=None): is_compile=None (standalone use) treats
    the FIRST observation as the compile step; fit_loop passes it
    explicitly per jit variant — BOTH the fast step's first call and the
    logging step's first call are trace+compile, and a multi-second
    compile landing in the steady-state samples would make p95/max
    unreadable. compile_time_s accumulates (total seconds spent
    compiling); the samples hold only steady-state steps."""

    def __init__(self, max_samples: int = 4096):
        self.compile_time_s: Optional[float] = None
        self._samples: List[float] = []
        self._max = max_samples
        self._count = 0
        self._running_max = 0.0

    def observe(self, dt_s: float, is_compile: Optional[bool] = None) -> None:
        if is_compile is None:
            is_compile = self.compile_time_s is None
        if is_compile:
            self.compile_time_s = (self.compile_time_s or 0.0) + dt_s
            return
        self._count += 1
        self._running_max = max(self._running_max, dt_s)
        if len(self._samples) < self._max:
            self._samples.append(dt_s)
        else:
            # Reservoir-free decimation: keep every other sample once full
            # (percentiles stay representative, memory stays bounded).
            self._samples = self._samples[::2]
            self._max = max(self._max, 2 * len(self._samples))
            self._samples.append(dt_s)

    _quantile = staticmethod(nearest_rank)

    def summary(self) -> dict:
        """The stamped histogram fields (milliseconds; compile in s)."""
        s = sorted(self._samples)
        return {
            "compile_time_s": round(self.compile_time_s or 0.0, 4),
            "step_time_p50_ms": round(1e3 * self._quantile(s, 0.50), 3),
            "step_time_p95_ms": round(1e3 * self._quantile(s, 0.95), 3),
            "step_time_p99_ms": round(1e3 * self._quantile(s, 0.99), 3),
            "step_time_max_ms": round(1e3 * self._running_max, 3),
            "steps_timed": self._count,
        }


def emit(rec: dict, kind: str = "bench", stream=None) -> dict:
    """Stamp (schema_version, kind, watchdog backend state) and print one
    JSON line. Returns the stamped record (benches reuse it for totals).
    Keys already present win — a bench that carries its own backend
    timeline is not overwritten."""
    stamped = schema.stamp(rec, kind=kind)
    for k, v in watchdog.backend_record().items():
        stamped.setdefault(k, v)
    from glom_tpu.tracing.flight import observe_event

    observe_event(stamped)
    print(json.dumps(stamped), file=stream or sys.stdout, flush=True)
    return stamped


def bench_bootstrap(
    metric: str,
    unit: str = "column-iters/s/chip",
    *,
    probe_timeout: float = 120.0,
) -> bool:
    """Fail-fast backend gate for bench entrypoints. Returns True when a
    backend (the default platform, or the CPU fallback it downgrades to)
    is measurable; on total failure emits the UNMEASURED record — kind
    "error", `value: null` (NEVER 0.0: round 5's zero rows polluted the
    bench trajectory, and `python -m glom_tpu.telemetry compare` treats
    these as missing) with the full watchdog outage timeline — and returns
    False. The watchdog stays registered either way, so every line the
    bench then emits carries the backend state."""
    import os

    from glom_tpu.telemetry.watchdog import BackendWatchdog, set_global_watchdog
    from glom_tpu.utils.metrics import apply_env_platform

    wd = BackendWatchdog(probe_timeout=probe_timeout)
    set_global_watchdog(wd)
    if wd.probe_once() == "down":
        os.environ["JAX_PLATFORMS"] = "cpu"
        if wd.probe_once() == "down":
            # The metric label stays the BARE one the measured rows carry:
            # the compare gate matches rows by label, and a decorated
            # label would make the outage read as a vanished metric
            # instead of an UNMEASURED one. The error field carries the
            # machine-readable cause.
            emit(
                {
                    "metric": metric,
                    "value": None,
                    "unit": unit,
                    "error": "backend-init-unavailable",
                    "note": "UNMEASURED: jax backend init failed or hung",
                    "watchdog_timeline": wd.timeline(),
                },
                kind="error",
            )
            return False
    # A successful probe validated the platform JAX_PLATFORMS names (the
    # probe honors it at config level); mirror it here so the bench cannot
    # initialize a different — possibly wedged — backend past the gate.
    apply_env_platform()
    return True
