"""`python -m glom_tpu.telemetry ...` — the telemetry CLI.

Seven subcommands sharing one entry point (all pure stdlib — they must
run in a jax-broken environment, the exact wedged-image scenario they
exist for):

    python -m glom_tpu.telemetry FILE...            lint JSONL logs against
                                                    the versioned schema
    python -m glom_tpu.telemetry compare BASE NEW   bench-trajectory
                                                    regression gate
    python -m glom_tpu.telemetry perfetto FILE...   span/flight JSONL ->
                                                    Perfetto JSON trace
    python -m glom_tpu.telemetry trace FILE...      reconstruct one
                                                    request's causal tree
                                                    (+ conservation check)
    python -m glom_tpu.telemetry aggregate PATH...  merge N hosts' streams
                                                    into one pod rollup
    python -m glom_tpu.telemetry watch DIR --slo R=T  live SLO monitor,
                                                    stamps slo_breach
    python -m glom_tpu.telemetry audit FILE...      replay the elastic
                                                    decision chain: evidence
                                                    conservation + regret

(`-m ...telemetry.schema` / `-m ...telemetry.compare` work too but trip
runpy's already-imported warning.)
"""

import sys

if __name__ == "__main__":
    argv = sys.argv[1:]
    if argv and argv[0] == "compare":
        from glom_tpu.telemetry.compare import main as compare_main

        sys.exit(compare_main(argv[1:]))
    if argv and argv[0] == "perfetto":
        from glom_tpu.telemetry.perfetto import main as perfetto_main

        sys.exit(perfetto_main(argv[1:]))
    if argv and argv[0] == "trace":
        from glom_tpu.telemetry.tracectx import main as trace_main

        sys.exit(trace_main(argv[1:]))
    if argv and argv[0] == "aggregate":
        from glom_tpu.telemetry.aggregate import aggregate_main

        sys.exit(aggregate_main(argv[1:]))
    if argv and argv[0] == "watch":
        from glom_tpu.telemetry.aggregate import watch_main

        sys.exit(watch_main(argv[1:]))
    if argv and argv[0] == "audit":
        from glom_tpu.telemetry.audit import main as audit_main

        sys.exit(audit_main(argv[1:]))
    from glom_tpu.telemetry.schema import main

    sys.exit(main(argv))
