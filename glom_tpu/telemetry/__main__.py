"""`python -m glom_tpu.telemetry ...` — the telemetry CLI.

Three subcommands sharing one entry point (all pure stdlib — they must run
in a jax-broken environment, the exact wedged-image scenario they exist
for):

    python -m glom_tpu.telemetry FILE...            lint JSONL logs against
                                                    the versioned schema
    python -m glom_tpu.telemetry compare BASE NEW   bench-trajectory
                                                    regression gate
    python -m glom_tpu.telemetry perfetto FILE...   span/flight JSONL ->
                                                    Perfetto JSON trace

(`-m ...telemetry.schema` / `-m ...telemetry.compare` work too but trip
runpy's already-imported warning.)
"""

import sys

if __name__ == "__main__":
    argv = sys.argv[1:]
    if argv and argv[0] == "compare":
        from glom_tpu.telemetry.compare import main as compare_main

        sys.exit(compare_main(argv[1:]))
    if argv and argv[0] == "perfetto":
        from glom_tpu.telemetry.perfetto import main as perfetto_main

        sys.exit(perfetto_main(argv[1:]))
    from glom_tpu.telemetry.schema import main

    sys.exit(main(argv))
