"""`python -m glom_tpu.telemetry FILE...` — lint JSONL logs against the
versioned event schema (the clean entry point; `-m ...telemetry.schema`
works too but trips runpy's already-imported warning)."""

import sys

from glom_tpu.telemetry.schema import main

if __name__ == "__main__":
    sys.exit(main())
