"""Measured collective counters for the manual shard_map path.

`utils/metrics.comm_volume_model` PRICES the gradient/update wire schedule
from top-level aggregates (G, P, dp, stage). This module MEASURES it: the
explicit collectives in `parallel/manual.py` (the seq-psum, the ZeRO
psum_scatter / pmean, the param all-gather) report their per-replica ring
wire bytes from the ACTUAL arrays at each call site while the step traces,
so aggregation decisions the model cannot see — leaves with no dp-divisible
axis falling back to a replicated allreduce, the seq-axis pre-reduction,
per-microbatch scatters — show up as measured-vs-modeled drift, which is
itself a stamped metric (`comm_model_drift`).

Recording is trace-time: collective shapes are static, so one abstract
trace (jax.eval_shape in DistributedTrainer) captures exactly what every
compiled step will move. Counters record only inside a `recording(...)`
context — re-traces of the same step (the with/without-grad-norm jit pair)
cannot double-count.

Wire formulas (ring algorithms, matching comm_volume_model's pricing):
  psum (allreduce)   2*(k-1)/k * B      B = local payload bytes
  psum_scatter       (k-1)/k   * B
  pmean fallback     2*(k-1)/k * B      (replicated leaf: full allreduce)
  all_gather         (k-1)     * B_sh   B_sh = per-shard bytes
Quantized-reduce arms price the REDUCE payload at the int8+scales wire
size (`quantized_wire_bytes`) — the same hypothetical-real-collective
convention the model uses; the gather stays f32 (EQuARX quantizes the
reduce, not the weights).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import List, Optional


class CollectiveCounters:
    """Accumulated per-replica per-step wire bytes by collective kind.

    `sites` additionally keeps one entry per NAMED site registration
    (the `timed_collective` wrapper passes site metadata; legacy
    byte-only `record_collective` calls contribute to the totals but not
    the registry): {site, axis, collective, wire_bytes (per call),
    calls, shape, dtype, dim} — the raw material the per-collective
    wall-time harness (telemetry/comm_time.py) re-dispatches and the
    capacity observatory's α-β time model is fitted from."""

    def __init__(self):
        self.reduce_bytes = 0  # psum + psum_scatter + pmean (gradient path)
        self.gather_bytes = 0  # all_gather (param path)
        self.n_reduce = 0
        self.n_gather = 0
        self.sites: List[dict] = []

    def record(self, kind: str, wire_bytes: int) -> None:
        if kind == "gather":
            self.gather_bytes += int(wire_bytes)
            self.n_gather += 1
        else:
            self.reduce_bytes += int(wire_bytes)
            self.n_reduce += 1

    def record_site(
        self,
        *,
        site: str,
        axis: str,
        collective: str,
        wire_bytes: int,
        calls: int,
        shape,
        dtype,
        dim: int,
    ) -> None:
        """One named-site registration (same (site, shape) seen again —
        e.g. a re-trace of the with/without-grad-norm jit pair inside one
        recording — accumulates calls rather than duplicating)."""
        for s in self.sites:
            if s["site"] == site and s["shape"] == tuple(shape):
                s["calls"] += calls
                return
        self.sites.append(
            {
                "site": site,
                "axis": axis,
                "collective": collective,
                "wire_bytes": int(wire_bytes),
                "calls": int(calls),
                "shape": tuple(int(d) for d in shape),
                "dtype": str(dtype),
                "dim": int(dim),
            }
        )

    def totals(self) -> dict:
        """The stamped record fields (measured counterpart of
        comm_volume_model's comm_*_bytes_per_step keys)."""
        return {
            "comm_measured_reduce_bytes_per_step": self.reduce_bytes,
            "comm_measured_gather_bytes_per_step": self.gather_bytes,
            "comm_measured_bytes_per_step": self.reduce_bytes + self.gather_bytes,
            "comm_measured_collective_count": self.n_reduce + self.n_gather,
        }


_local = threading.local()


def _stack() -> List[CollectiveCounters]:
    if not hasattr(_local, "stack"):
        _local.stack = []
    return _local.stack


@contextmanager
def recording(counters: CollectiveCounters):
    """Activate `counters` for collectives recorded on THIS thread (tracing
    is single-threaded per step; thread-local keeps parallel test runs
    honest)."""
    _stack().append(counters)
    try:
        yield counters
    finally:
        _stack().pop()


def _scale() -> int:
    return getattr(_local, "scale", 1)


@contextmanager
def scaled(k: int):
    """Multiply recorded bytes by `k` inside this context: a collective
    site inside a lax.scan body TRACES once but EXECUTES per iteration —
    the stage-2 per-microbatch reduce-scatter hook wraps itself in
    scaled(grad_accum) so the measured count prices every execution, not
    the single trace."""
    prev = _scale()
    _local.scale = prev * int(k)
    try:
        yield
    finally:
        _local.scale = prev


def record_collective(kind: str, wire_bytes: int) -> None:
    """Called from the instrumented collective sites in parallel/manual.py.
    No-op unless a recording() context is active — the sites stay free to
    trace/retrace without double-counting."""
    scale = _scale()
    for c in _stack():
        c.record(kind, wire_bytes * scale)


# -- per-collective wall-time (the capacity observatory's timing layer) -----

# tcfg.collective_timing / scfg.collective_timing vocabulary, resolved ONCE
# per path like telemetry_level (docs/OBSERVABILITY.md, "Capacity
# observatory"):
#   "off"     — no timing anywhere (the default; the overhead A/Bs hold the
#               off-mode step bit-identical to the pre-timing program);
#   "sampled" — every Nth step/dispatch OUTSIDE jit, each registered site's
#               collective is re-dispatched as its own timed sub-graph
#               (telemetry/comm_time.CollectiveTimeSampler): exact
#               block_until_ready wall clocks, zero hot-path cost between
#               samples. The mode every path supports.
#   "full"    — every execution of every registered site is bracketed
#               IN-GRAPH by dataflow-ordered io_callbacks stamping host
#               clocks (the only way to see per-execution variance, e.g. a
#               congested link on one while_loop trip). Supported only on
#               paths with an AOT trace seam (the serve engine's
#               .lower().compile()); the jit-on-first-call trainer paths
#               degrade to "sampled" loudly — the stamped mode is always
#               the resolved one.
TIMING_MODES = ("off", "sampled", "full")


def resolve_collective_timing(
    mode: str, *, supports_full: bool = True, path: str = ""
) -> str:
    """THE single resolution source for the collective-timing mode (the
    resolve_telemetry_level discipline): validates the vocabulary and
    degrades full -> sampled loudly where per-execution bracketing has no
    trace seam to ride."""
    if mode not in TIMING_MODES:
        raise ValueError(
            f"collective_timing={mode!r}: one of {TIMING_MODES}"
        )
    if mode == "full" and not supports_full:
        import warnings

        warnings.warn(
            f"collective_timing='full' is unavailable on {path or 'this'} "
            "path (no AOT trace seam to insert the io_callback brackets); "
            "running 'sampled' — the stamped mode is the resolved one",
            stacklevel=3,
        )
        return "sampled"
    return mode


class CollectiveTimeLog:
    """Host-side sink for the full-mode io_callback brackets: thread-safe
    (engine worker threads dispatch concurrently), bounded (a long-running
    server must not grow one entry per collective execution forever —
    drain() aggregates per site and resets)."""

    def __init__(self, max_events: int = 100_000):
        self._events: List[tuple] = []
        self._lock = threading.Lock()
        self._max = max_events
        self.base = time.perf_counter()

    def add(self, site: str, axis: str, collective: str,
            wire_bytes: int, dt_s: float) -> None:
        with self._lock:
            if len(self._events) < self._max:
                self._events.append(
                    (site, axis, collective, int(wire_bytes), float(dt_s))
                )

    def drain(self) -> List[dict]:
        """Aggregate and reset: one dict per (site, axis) with the mean /
        max wall_ms over the drained executions (each shard's callback
        pair contributes one sample)."""
        with self._lock:
            events, self._events = self._events, []
        agg: dict = {}
        for site, axis, collective, nbytes, dt in events:
            slot = agg.setdefault(
                (site, axis, nbytes),
                {"site": site, "axis": axis, "collective": collective,
                 "wire_bytes": nbytes, "calls": 0, "_sum": 0.0, "_max": 0.0},
            )
            slot["calls"] += 1
            slot["_sum"] += dt
            slot["_max"] = max(slot["_max"], dt)
        out = []
        for slot in agg.values():
            calls = slot.pop("calls")
            total = slot.pop("_sum")
            mx = slot.pop("_max")
            out.append(
                dict(
                    slot,
                    calls=calls,
                    wall_ms=round(1e3 * total / calls, 6) if calls else 0.0,
                    wall_ms_max=round(1e3 * mx, 6),
                    mode="full",
                )
            )
        return sorted(out, key=lambda r: r["site"])


def _timing_state():
    return getattr(_local, "timing", None)


@contextmanager
def timing(mode: str, log: Optional[CollectiveTimeLog]):
    """Activate a collective-timing mode for code TRACED on this thread
    (the serve engine wraps its AOT .lower() in timing('full', log) so the
    compiled program carries the callback brackets; 'sampled'/'off' insert
    nothing — the sampler runs outside jit entirely)."""
    prev = _timing_state()
    _local.timing = (mode, log)
    try:
        yield
    finally:
        _local.timing = prev


def timed_collective(
    site: str,
    axis_name: str,
    kind: str,
    wire_bytes: int,
    fn,
    x,
    *,
    collective: str,
    dim: int = 0,
):
    """THE shared timing wrapper every registered collective site routes
    through (glom-lint's collective-coverage checker enforces it: a site
    that hand-rolls clocks or callbacks around a collective inside traced
    code is a finding — the trace-purity checker already bans bare host
    clocks there, and this wrapper is the one sanctioned route).

    Always: records the wire bytes exactly as record_collective did, plus
    the site's identity/shape into the active recording's site registry
    (what the sampled-mode re-dispatch and the α-β time model read).

    Under timing('full', log) — active only during an AOT trace — the
    collective is additionally bracketed by io_callbacks whose ORDER is
    enforced by dataflow, not ordered effects (ordered effects are not
    legal inside shard_map): the enter callback's clock value is tied to
    the collective's input through lax.optimization_barrier (bitwise
    no-op on the payload), and the exit callback takes both that clock
    and a scalar read of the output, so it cannot run before the
    collective completes. Each shard's pair contributes one wall-clock
    sample to the log at every execution."""
    record_collective(kind, wire_bytes)
    scale = _scale()
    for c in _stack():
        c.record_site(
            site=site, axis=axis_name, collective=collective,
            wire_bytes=wire_bytes, calls=scale,
            shape=getattr(x, "shape", ()), dtype=getattr(x, "dtype", "?"),
            dim=dim,
        )
    state = _timing_state()
    if not state or state[0] != "full" or state[1] is None:
        return fn(x)
    log = state[1]
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import io_callback

    base = log.base

    def _enter(_witness):
        import numpy as np

        return np.float32(time.perf_counter() - base)

    def _exit(t0, _witness):
        log.add(
            site, axis_name, collective, wire_bytes,
            (time.perf_counter() - base) - float(t0),
        )

    # f32 seconds since the log's base keep the clock's resolution in the
    # microseconds for hours of uptime — far under the callback dispatch
    # noise this mode already carries (the sampled mode is the calibrated
    # route; full mode buys per-execution VISIBILITY, not precision).
    witness_in = jnp.ravel(x)[0] if getattr(x, "ndim", 0) else x
    t0 = io_callback(
        _enter, jax.ShapeDtypeStruct((), jnp.float32), witness_in
    )
    x, t0 = lax.optimization_barrier((x, t0))
    out = fn(x)
    witness_out = jnp.ravel(out)[0] if getattr(out, "ndim", 0) else out
    io_callback(_exit, None, t0, witness_out)
    return out


# -- wire-byte helpers for the instrumented sites --------------------------


def _nbytes(x) -> int:
    import numpy as np

    size = 1
    for s in x.shape:
        size *= int(s)
    return size * np.dtype(x.dtype).itemsize


def ring_allreduce_bytes(x, k: int) -> int:
    return int(2 * (k - 1) / k * _nbytes(x)) if k > 1 else 0


def ring_reduce_scatter_bytes(x, k: int, *, quantized: bool = False) -> int:
    if k <= 1:
        return 0
    nbytes = _nbytes(x)
    if quantized:
        from glom_tpu.parallel.quantized import quantized_wire_bytes

        # f32 elements -> int8 payload + per-block scales (the wire the
        # real quantized collective would carry).
        nbytes = quantized_wire_bytes(nbytes // 4)
    return int((k - 1) / k * nbytes)


def ring_all_gather_bytes(x_shard, k: int) -> int:
    return int((k - 1) * _nbytes(x_shard)) if k > 1 else 0


def comm_drift(measured: dict, modeled: dict) -> dict:
    """Measured-vs-modeled reconciliation, itself a stamped metric: the
    relative drift of total per-step wire bytes ((measured - modeled) /
    modeled). A model that stops matching the collectives a step actually
    emits is a silent-pricing bug — stamping the drift on every record is
    what makes it impossible to miss."""
    meas = measured.get("comm_measured_bytes_per_step", 0)
    model = modeled.get("comm_bytes_per_step", 0)
    if model <= 0:
        drift = 0.0 if meas == 0 else float("inf")
    else:
        drift = (meas - model) / model
    return {"comm_model_drift": round(drift, 6) if drift != float("inf") else 1e9}
