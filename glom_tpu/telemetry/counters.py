"""Measured collective counters for the manual shard_map path.

`utils/metrics.comm_volume_model` PRICES the gradient/update wire schedule
from top-level aggregates (G, P, dp, stage). This module MEASURES it: the
explicit collectives in `parallel/manual.py` (the seq-psum, the ZeRO
psum_scatter / pmean, the param all-gather) report their per-replica ring
wire bytes from the ACTUAL arrays at each call site while the step traces,
so aggregation decisions the model cannot see — leaves with no dp-divisible
axis falling back to a replicated allreduce, the seq-axis pre-reduction,
per-microbatch scatters — show up as measured-vs-modeled drift, which is
itself a stamped metric (`comm_model_drift`).

Recording is trace-time: collective shapes are static, so one abstract
trace (jax.eval_shape in DistributedTrainer) captures exactly what every
compiled step will move. Counters record only inside a `recording(...)`
context — re-traces of the same step (the with/without-grad-norm jit pair)
cannot double-count.

Wire formulas (ring algorithms, matching comm_volume_model's pricing):
  psum (allreduce)   2*(k-1)/k * B      B = local payload bytes
  psum_scatter       (k-1)/k   * B
  pmean fallback     2*(k-1)/k * B      (replicated leaf: full allreduce)
  all_gather         (k-1)     * B_sh   B_sh = per-shard bytes
Quantized-reduce arms price the REDUCE payload at the int8+scales wire
size (`quantized_wire_bytes`) — the same hypothetical-real-collective
convention the model uses; the gather stays f32 (EQuARX quantizes the
reduce, not the weights).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import List


class CollectiveCounters:
    """Accumulated per-replica per-step wire bytes by collective kind."""

    def __init__(self):
        self.reduce_bytes = 0  # psum + psum_scatter + pmean (gradient path)
        self.gather_bytes = 0  # all_gather (param path)
        self.n_reduce = 0
        self.n_gather = 0

    def record(self, kind: str, wire_bytes: int) -> None:
        if kind == "gather":
            self.gather_bytes += int(wire_bytes)
            self.n_gather += 1
        else:
            self.reduce_bytes += int(wire_bytes)
            self.n_reduce += 1

    def totals(self) -> dict:
        """The stamped record fields (measured counterpart of
        comm_volume_model's comm_*_bytes_per_step keys)."""
        return {
            "comm_measured_reduce_bytes_per_step": self.reduce_bytes,
            "comm_measured_gather_bytes_per_step": self.gather_bytes,
            "comm_measured_bytes_per_step": self.reduce_bytes + self.gather_bytes,
            "comm_measured_collective_count": self.n_reduce + self.n_gather,
        }


_local = threading.local()


def _stack() -> List[CollectiveCounters]:
    if not hasattr(_local, "stack"):
        _local.stack = []
    return _local.stack


@contextmanager
def recording(counters: CollectiveCounters):
    """Activate `counters` for collectives recorded on THIS thread (tracing
    is single-threaded per step; thread-local keeps parallel test runs
    honest)."""
    _stack().append(counters)
    try:
        yield counters
    finally:
        _stack().pop()


def _scale() -> int:
    return getattr(_local, "scale", 1)


@contextmanager
def scaled(k: int):
    """Multiply recorded bytes by `k` inside this context: a collective
    site inside a lax.scan body TRACES once but EXECUTES per iteration —
    the stage-2 per-microbatch reduce-scatter hook wraps itself in
    scaled(grad_accum) so the measured count prices every execution, not
    the single trace."""
    prev = _scale()
    _local.scale = prev * int(k)
    try:
        yield
    finally:
        _local.scale = prev


def record_collective(kind: str, wire_bytes: int) -> None:
    """Called from the instrumented collective sites in parallel/manual.py.
    No-op unless a recording() context is active — the sites stay free to
    trace/retrace without double-counting."""
    scale = _scale()
    for c in _stack():
        c.record(kind, wire_bytes * scale)


# -- wire-byte helpers for the instrumented sites --------------------------


def _nbytes(x) -> int:
    import numpy as np

    size = 1
    for s in x.shape:
        size *= int(s)
    return size * np.dtype(x.dtype).itemsize


def ring_allreduce_bytes(x, k: int) -> int:
    return int(2 * (k - 1) / k * _nbytes(x)) if k > 1 else 0


def ring_reduce_scatter_bytes(x, k: int, *, quantized: bool = False) -> int:
    if k <= 1:
        return 0
    nbytes = _nbytes(x)
    if quantized:
        from glom_tpu.parallel.quantized import quantized_wire_bytes

        # f32 elements -> int8 payload + per-block scales (the wire the
        # real quantized collective would carry).
        nbytes = quantized_wire_bytes(nbytes // 4)
    return int((k - 1) / k * nbytes)


def ring_all_gather_bytes(x_shard, k: int) -> int:
    return int((k - 1) * _nbytes(x_shard)) if k > 1 else 0


def comm_drift(measured: dict, modeled: dict) -> dict:
    """Measured-vs-modeled reconciliation, itself a stamped metric: the
    relative drift of total per-step wire bytes ((measured - modeled) /
    modeled). A model that stops matching the collectives a step actually
    emits is a silent-pricing bug — stamping the drift on every record is
    what makes it impossible to miss."""
    meas = measured.get("comm_measured_bytes_per_step", 0)
    model = modeled.get("comm_bytes_per_step", 0)
    if model <= 0:
        drift = 0.0 if meas == 0 else float("inf")
    else:
        drift = (meas - model) / model
    return {"comm_model_drift": round(drift, 6) if drift != float("inf") else 1e9}
