"""Per-collective wall-time: the measured half of the capacity observatory.

`telemetry/counters.py` prices the manual paths' collectives in BYTES;
ROADMAP's standing backlog was the other axis — the CLOCK. GLOM's
per-iteration consensus makes wall-time a function of communication as much
as compute, and a topology-aware schedule (TASP, PAPERS.md) can only be
*picked* from a time model after the time model is grounded per site
against measurement. This module grounds it:

  * `CollectiveTimeSampler` — the "sampled" timing mode's engine: from the
    site registry a counting trace populated (counters.CollectiveCounters
    .sites), it builds ONE tiny shard_map per registered site that runs
    exactly that collective (same local shape, dtype, axis, scatter/gather
    dim) on the same mesh, and times it outside jit with
    block_until_ready wall clocks (min over repeats — the bench timing
    convention). The number is the ISOLATED collective: an upper bound on
    the blocking cost inside the real step (where XLA may overlap it),
    and exactly the per-site latency/bandwidth point the α-β fit needs.

  * the α-β time model — the classic latency-bandwidth form
    `wall_ms = alpha_ms + beta_ms_per_byte * wire_bytes` (ring collectives
    are linear in payload once per-hop latency is split out), fitted by
    closed-form least squares from the measured points and stamped back
    onto every record as `comm_time_model_ms` + `comm_time_model_drift`
    (the comm_model_drift discipline: a model diverging from measurement
    must be visible on the record itself, not in a notebook).

  * `collective_time_records` — the schema-v7 "collective_time" rows
    (site, axis, collective, bytes, wall_ms, bytes_per_s, mode, model
    drift) plus one `comm_time_model` summary row carrying the fitted
    alpha/beta — what `telemetry compare` classifies as costs and the
    Perfetto export renders as per-(site, axis) counter tracks.

The model math is pure stdlib (it must run over a crashed run's records in
a jax-broken environment); only the sampler imports jax, lazily.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from glom_tpu.telemetry import schema


# -- the α-β time model ------------------------------------------------------


def fit_time_model(points: List[dict]) -> dict:
    """Least-squares `wall_ms = alpha + beta * wire_bytes` over measured
    site points ({wire_bytes, wall_ms}). Degenerate inputs stay honest:
    one point (or all points at one byte size) pins alpha to the mean and
    beta to 0 — a model claiming bandwidth it never measured would fake a
    fit. beta is clamped at 0 (a negative marginal byte cost is noise,
    and extrapolating it would predict negative time)."""
    pts = [
        (float(p["wire_bytes"]), float(p["wall_ms"]))
        for p in points
        if isinstance(p.get("wire_bytes"), (int, float))
        and isinstance(p.get("wall_ms"), (int, float))
    ]
    n = len(pts)
    if n == 0:
        return {"alpha_ms": 0.0, "beta_ms_per_byte": 0.0, "n_points": 0}
    mean_x = sum(x for x, _ in pts) / n
    mean_y = sum(y for _, y in pts) / n
    var_x = sum((x - mean_x) ** 2 for x, _ in pts)
    if var_x <= 0.0:
        return {
            "alpha_ms": round(mean_y, 6),
            "beta_ms_per_byte": 0.0,
            "n_points": n,
        }
    beta = sum((x - mean_x) * (y - mean_y) for x, y in pts) / var_x
    beta = max(0.0, beta)
    alpha = max(0.0, mean_y - beta * mean_x)
    return {
        "alpha_ms": round(alpha, 6),
        "beta_ms_per_byte": beta,
        "n_points": n,
    }


def predict_ms(model: dict, wire_bytes: float) -> float:
    return float(model.get("alpha_ms", 0.0)) + float(
        model.get("beta_ms_per_byte", 0.0)
    ) * float(wire_bytes)


def time_model_drift(wall_ms: float, model_ms: float) -> float:
    """(measured - modeled) / modeled — the comm_model_drift convention,
    including its inf -> 1e9 JSON-safe clamp."""
    if model_ms <= 0.0:
        return 0.0 if wall_ms == 0.0 else 1e9
    drift = (wall_ms - model_ms) / model_ms
    return round(drift, 6)


def collective_time_records(
    samples: List[dict],
    *,
    path: str,
    mode: str,
    model: Optional[dict] = None,
) -> List[dict]:
    """Stamped schema-v7 "collective_time" rows from raw site samples
    ({site, axis, collective, wire_bytes, wall_ms[, calls, wall_ms_max]}).
    The α-β model is fitted from THESE points unless a pre-fitted one is
    passed (the hw-queue re-fit step passes last window's model to price
    drift against it), and every row stamps its own model drift; a final
    `comm_time_model` row carries the fit itself plus the aggregate
    drift — the one-number health signal the compare gate tracks."""
    if not samples:
        return []
    fitted = model if model is not None else fit_time_model(samples)
    out = []
    total_measured = 0.0
    total_modeled = 0.0
    for s in sorted(samples, key=lambda r: str(r.get("site"))):
        wall = float(s["wall_ms"])
        nbytes = int(s.get("wire_bytes", 0))
        pred = predict_ms(fitted, nbytes)
        total_measured += wall
        total_modeled += pred
        rec = {
            "site": str(s["site"]),
            "axis": s.get("axis"),
            "collective": s.get("collective"),
            "path": path,
            "mode": mode,
            "wire_bytes": nbytes,
            "wall_ms": wall,
            "bytes_per_s": (
                round(nbytes / (wall / 1e3), 1) if wall > 0 else None
            ),
            "comm_time_model_ms": round(pred, 6),
            "comm_time_model_drift": time_model_drift(wall, pred),
        }
        for k in ("calls", "wall_ms_max"):
            if k in s:
                rec[k] = s[k]
        out.append(schema.stamp(rec, kind="collective_time"))
    out.append(
        schema.stamp(
            {
                "site": "comm_time_model",
                "path": path,
                "mode": mode,
                "wall_ms": round(total_measured, 6),
                "alpha_ms": fitted["alpha_ms"],
                "beta_ms_per_byte": fitted["beta_ms_per_byte"],
                "n_points": fitted["n_points"],
                "comm_time_model_ms": round(total_modeled, 6),
                "comm_time_model_drift": time_model_drift(
                    total_measured, total_modeled
                ),
            },
            kind="collective_time",
        )
    )
    return out


# -- the sampled-mode re-dispatch harness ------------------------------------


class CollectiveTimeSampler:
    """Re-dispatches each registered collective site as its own timed
    sub-graph on the live mesh — the "sampled" timing mode.

    Built from a counting trace's site registry (each entry carries the
    SHARD-LOCAL operand shape/dtype, the axis, and the scatter/gather
    dimension, so the rebuilt collective moves exactly the bytes the real
    site moves). Compiles lazily on the first sample (compile time is
    excluded from the timing: the first call warms, then `repeats` timed
    calls take the min — the bench convention); `maybe_sample(step)`
    rate-limits to every `interval`-th call, so a fit loop can invoke it
    at every logging boundary for free in between."""

    def __init__(
        self,
        mesh,
        sites: List[dict],
        *,
        interval: int = 10,
        repeats: int = 2,
    ):
        if interval < 1:
            raise ValueError(f"interval {interval} must be >= 1")
        if repeats < 1:
            raise ValueError(f"repeats {repeats} must be >= 1")
        self.mesh = mesh
        # Only sites that move wire (a k==1 axis registers nothing at the
        # call sites, but a defensive filter keeps a zero-byte site from
        # wasting a compile on a no-op), DEDUPLICATED by what actually
        # determines wall time — (site, axis, collective, payload bytes,
        # dtype): two parameter leaves of different shapes but identical
        # payload ride one timed sub-graph instead of two compiles and
        # two dispatches per sample (their `calls` merge, so the α-β
        # fit's per-point weight is unchanged).
        self._uniq: Dict[tuple, dict] = {}
        self._merge(sites)
        self.interval = int(interval)
        self.repeats = int(repeats)
        self._fns: Dict[str, object] = {}
        self._calls = 0

    @staticmethod
    def _key(s: dict) -> tuple:
        return (
            s["site"], s["axis"], s["collective"], s["wire_bytes"],
            s.get("dtype"),
        )

    def _merge(self, sites: List[dict]) -> None:
        for s in sites:
            if s.get("wire_bytes", 0) <= 0:
                continue
            key = self._key(s)
            if key in self._uniq:
                self._uniq[key]["calls"] += s.get("calls", 1)
            else:
                self._uniq[key] = dict(s)

    @property
    def sites(self) -> List[dict]:
        return list(self._uniq.values())

    def update_sites(self, sites: List[dict]) -> None:
        """Merge sites registered AFTER construction — a lazy mid-traffic
        compile of a new signature adds registry entries, and a frozen
        sampler would silently never time them (their sub-graphs compile
        on the next sample like any first-seen site). Byte-identical
        shapes dedupe exactly as at construction, so re-merging an
        already-known site only bumps its call weight... which would
        DOUBLE-count on repeated update calls — already-known keys are
        therefore skipped entirely here."""
        for s in sites:
            if s.get("wire_bytes", 0) <= 0:
                continue
            self._uniq.setdefault(self._key(s), dict(s))

    def _build(self, site: dict):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from glom_tpu.utils.compat import shard_map

        collective = site["collective"]
        axis = site["axis"]
        shape = tuple(site["shape"])
        dtype = jnp.dtype(np.dtype(site["dtype"]))
        dim = int(site.get("dim", 0))

        # The axis here REPLAYS a recorded site registration: it was
        # vocabulary-checked (and wire-counted) at the original call site
        # in parallel/manual.py or serve_mesh.py, so the re-dispatch
        # carries reasoned suppressions rather than a fake static axis.
        def body():
            x = jnp.zeros(shape, dtype)
            if collective == "psum":
                return lax.psum(x, axis)  # glom-lint: ok[collective-coverage] replayed site, axis checked at origin
            if collective == "pmean":
                return lax.pmean(x, axis)  # glom-lint: ok[collective-coverage] replayed site, axis checked at origin
            if collective == "psum_scatter":
                return lax.psum_scatter(  # glom-lint: ok[collective-coverage] replayed site, axis checked at origin
                    x, axis, scatter_dimension=dim, tiled=True
                )
            if collective == "all_gather":
                return lax.all_gather(x, axis, axis=dim, tiled=True)  # glom-lint: ok[collective-coverage] replayed site, axis checked at origin
            raise ValueError(f"unknown collective {collective!r}")

        return jax.jit(
            shard_map(
                body, mesh=self.mesh, in_specs=(), out_specs=P(),
                check_vma=False,
            )
        )

    def sample(self) -> List[dict]:
        """One timed pass over every registered site: min-of-repeats wall
        clock around the jitted collective with a terminal
        block_until_ready. Returns raw site samples (feed them to
        collective_time_records for the stamped rows)."""
        import jax

        out = []
        for site in self.sites:
            key = f"{site['site']}:{site['shape']}"
            fn = self._fns.get(key)
            if fn is None:
                fn = self._fns[key] = self._build(site)
                jax.block_until_ready(fn())  # compile + warm, untimed
            best = float("inf")
            for _ in range(self.repeats):
                t0 = time.perf_counter()
                jax.block_until_ready(fn())
                best = min(best, time.perf_counter() - t0)
            out.append(
                {
                    "site": site["site"],
                    "axis": site["axis"],
                    "collective": site["collective"],
                    "wire_bytes": site["wire_bytes"],
                    "calls": site.get("calls", 1),
                    "wall_ms": round(best * 1e3, 6),
                }
            )
        return out

    def maybe_sample(self, *, path: str) -> List[dict]:
        """Every `interval`-th call: sample + fit + return the stamped
        collective_time records (empty between samples, and on the very
        first call only after `interval` calls have accrued — the loop's
        first boundaries are compile-dominated anyway)."""
        self._calls += 1
        if self._calls % self.interval != 0:
            return []
        return collective_time_records(
            self.sample(), path=path, mode="sampled"
        )
