"""Pod-scale telemetry aggregation + the live SLO monitor.

PRs 7-9 made runs multi-host and multi-engine, but every JSONL stream was
still read alone: two hosts' evidence of the SAME pod event (a save
barrier, an engine failover, one request's continuation hops) sat in
separate files with heterogeneous clocks, and "what is the pod's p99"
had no answer an operator could query. This module is the missing merge:

  * `merge_timeline` reconciles N hosts' streams onto ONE pod time axis.
    Clock families follow perfetto.py's vocabulary (CLOCK_KEYS /
    EPOCH_CUTOFF_S): epoch clocks (wall_time_s and friends) are pod-wide
    by construction; run-relative clocks (MetricsWriter's wall_time,
    the watchdog's t) are mapped onto the epoch axis via each host's
    ANCHOR records — records carrying both families at once (every
    watchdog transition and barrier event written through MetricsWriter
    does). A host mixing families with no anchor is a CLOCK-FAMILY
    VIOLATION: its events cannot be honestly interleaved, and the
    aggregator says so instead of guessing.

  * `rollup` folds the merged streams into the pod-level numbers the
    paper's cost model cares about: per-host / per-engine / per-bucket
    dispatch-latency percentiles, per-request latency + EXECUTED-ITERS
    histograms (from the v6 resolve leaves — work, not just wall time),
    cache hit rates, and the failover / ladder / barrier event timelines.

  * `SLOMonitor` evaluates windowed SLO rules over a live stream and
    stamps a schema "slo_breach" record per violation — delivered through
    the writer-else-flight path (the flight recorder counts breaches
    toward its anomaly-storm dump trigger) and stamped with the current
    watchdog backend state, so a breach during an outage is attributable
    at a glance.

CLI (both registered in glom_tpu/telemetry/__main__.py):

    python -m glom_tpu.telemetry aggregate PATH...   merged rollup + checks
    python -m glom_tpu.telemetry watch DIR --slo p99_ms=50 [--once]

`watch` tails every *.jsonl under DIR (new files included), evaluates the
rules each interval, and exits nonzero if any rule was breached — the CI
smoke replays a seeded breach fixture with `--once`. Pure stdlib, like
the rest of the telemetry surface: all of this must run against a crashed
run's dumps in a jax-broken environment.
"""

from __future__ import annotations

import json
import sys
import time
from collections import OrderedDict, deque
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple

from glom_tpu.telemetry import schema
from glom_tpu.telemetry.perfetto import EPOCH_CUTOFF_S, CLOCK_KEYS
from glom_tpu.telemetry.sinks import nearest_rank


def percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile over unsorted values (delegates to the
    one shared definition in telemetry/sinks.py)."""
    return nearest_rank(sorted(values), q)


def _pcts(values: List[float]) -> dict:
    return {
        "p50": round(percentile(values, 0.50), 3),
        "p95": round(percentile(values, 0.95), 3),
        "p99": round(percentile(values, 0.99), 3),
        "n": len(values),
    }


# -- host streams -----------------------------------------------------------


def expand_paths(paths: Iterable[str]) -> "OrderedDict[str, str]":
    """host label -> file path. A directory contributes every *.jsonl
    under it (sorted — chaos workdirs name streams metrics_h0, _h1, ...);
    a file contributes itself. Labels are file stems, qualified by the
    parent directory on collision."""
    out: "OrderedDict[str, str]" = OrderedDict()
    files: List[Path] = []
    for p in paths:
        path = Path(p)
        if path.is_dir():
            files.extend(sorted(path.glob("*.jsonl")))
        else:
            files.append(path)
    for f in files:
        # Qualify with ever more parent directories until unique, then a
        # numeric suffix as the last resort — a third runX/pod/metrics_h0
        # must never silently overwrite the second's stream.
        parts = f.parts
        label = f.stem
        depth = 1
        while label in out and depth < len(parts):
            depth += 1
            label = "/".join(parts[-depth:-1] + (f.stem,))
        n = 2
        while label in out:
            label = f"{f.stem}#{n}"
            n += 1
        out[label] = str(f)
    return out


def load_host_records(
    hosts: "OrderedDict[str, str]",
) -> "OrderedDict[str, List[dict]]":
    out: "OrderedDict[str, List[dict]]" = OrderedDict()
    for host, path in hosts.items():
        with open(path) as fh:
            out[host] = [rec for _, rec in schema.iter_json_lines(fh)]
    return out


# -- clock-family reconciliation --------------------------------------------


def _clocks(rec: dict) -> Tuple[Optional[float], Optional[float]]:
    """(run_relative, epoch) seconds carried by one record — either may
    be None. Family membership is by magnitude (EPOCH_CUTOFF_S), not key
    name: MetricsWriter's `wall_time` is run-relative while the barrier
    events' `wall_time_s` is an epoch, and a record routed through the
    writer carries BOTH (the anchor this reconciliation needs)."""
    rel = epoch = None
    for key in CLOCK_KEYS:
        v = rec.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        if v > EPOCH_CUTOFF_S:
            if epoch is None:
                epoch = float(v)
        elif rel is None:
            rel = float(v)
    return rel, epoch


def merge_timeline(
    host_records: "OrderedDict[str, List[dict]]",
) -> dict:
    """{"events": [{t, host, clock, rec}...] sorted on ONE pod axis,
    "violations": [str...]}.

    Per host: epoch-clock records land directly on the pod axis;
    run-relative records map through the host's anchor offset (min over
    records carrying both families — min, because the offset is wall
    epoch minus run-relative age, and any later anchor only adds queueing
    delay); clockless records inherit the previous record's time plus
    1ms, preserving stream order. The whole axis is then shifted to start
    at ~0. Violations name what could NOT be reconciled — a host mixing
    families with no anchor, or a host with no epoch mapping at all while
    the pod has one (its events order only within the host)."""
    events: List[dict] = []
    violations: List[str] = []
    anchored_hosts = 0
    hosts_with_rel_only = []
    for host, recs in host_records.items():
        offsets = []
        has_rel = has_epoch = False
        for rec in recs:
            rel, epoch = _clocks(rec)
            has_rel = has_rel or rel is not None
            has_epoch = has_epoch or epoch is not None
            if rel is not None and epoch is not None:
                offsets.append(epoch - rel)
        offset = min(offsets) if offsets else None
        if has_rel and has_epoch and offset is None:
            violations.append(
                f"host {host}: stream mixes run-relative and epoch clocks "
                "with no anchor record carrying both — its families "
                "cannot be reconciled onto one pod timeline"
            )
        if has_epoch or offset is not None:
            anchored_hosts += 1
        elif has_rel:
            hosts_with_rel_only.append(host)
        prev_t: Optional[float] = None
        prev_on_axis = False
        for rec in recs:
            rel, epoch = _clocks(rec)
            if epoch is not None:
                t, clock, on_axis = epoch, "epoch", True
            elif rel is not None and offset is not None:
                t, clock, on_axis = rel + offset, "anchored", True
            elif rel is not None:
                t, clock, on_axis = rel, "relative", False
            else:
                # Clockless: 1ms after the previous record, INHERITING
                # its axis — a seq record trailing an epoch-clock one
                # must shift with the pod axis or it strands ~50 years
                # out when the axis is re-zeroed below.
                t = (prev_t + 1e-3) if prev_t is not None else 0.0
                clock, on_axis = "seq", prev_on_axis
            prev_t, prev_on_axis = t, on_axis
            events.append(
                {"t": t, "host": host, "clock": clock,
                 "on_axis": on_axis, "rec": rec}
            )
    if anchored_hosts and hosts_with_rel_only:
        violations.append(
            "hosts "
            + ", ".join(hosts_with_rel_only)
            + ": no epoch anchor while the pod timeline has one — these "
            "hosts' events order only within the host, not across it"
        )
    on_axis = [e["t"] for e in events if e["on_axis"]]
    zero = min(on_axis) if on_axis else 0.0
    for e in events:
        if e.pop("on_axis"):
            e["t"] = round(e["t"] - zero, 6)
    events.sort(key=lambda e: e["t"])
    return {"events": events, "violations": violations}


# -- pod rollups ------------------------------------------------------------


def rollup(host_records: "OrderedDict[str, List[dict]]") -> dict:
    """The pod-level numbers, folded from every host's stream. Latency
    and executed-iters come from the per-record evidence (dispatch
    records, v6 resolve leaves), not the end-of-run summaries, so a
    crashed host still contributes everything it stamped; cache counters
    come from each host's LAST summary (they are cumulative)."""
    per_host: "OrderedDict[str, dict]" = OrderedDict()
    per_engine: Dict[str, dict] = {}
    per_bucket: Dict[str, dict] = {}
    request_ms: List[float] = []
    response_ms: List[float] = []
    dispatch_ms: List[float] = []
    iters_hist: Dict[str, int] = {}
    iters_total = 0
    n_resolved = n_shed = n_responses = n_failed_responses = 0
    cache_totals: Dict[str, int] = {}
    seen_cache = False
    class_totals: Dict[str, Dict[str, int]] = {}
    class_ms: Dict[str, List[float]] = {}
    failover_timeline: List[dict] = []
    ladder_timeline: List[dict] = []
    barrier_rounds: Dict[str, Dict[str, List[dict]]] = {}
    decision_fleets: Dict[str, dict] = {}
    for host, recs in host_records.items():
        h = per_host.setdefault(
            host,
            {"n_records": 0, "n_dispatches": 0, "n_resolved": 0,
             "n_shed": 0, "n_train_steps": 0, "dispatch_ms": []},
        )
        last_summary = None
        for rec in recs:
            h["n_records"] += 1
            kind = rec.get("kind")
            if kind == "train_step":
                h["n_train_steps"] += 1
                continue
            if kind == "barrier":
                rnd = str(rec.get("round"))
                phase = str(rec.get("phase"))
                barrier_rounds.setdefault(rnd, {}).setdefault(
                    phase, []
                ).append({"host": host, "step": rec.get("step")})
                continue
            if kind == "capacity":
                # The capacity observatory's headroom rollup: last + min
                # per engine across the pod — what the scale-out decision
                # reads at pod scope.
                h = rec.get("headroom")
                if isinstance(h, (int, float)) and not isinstance(h, bool):
                    eng = per_engine.setdefault(
                        str(rec.get("engine")),
                        {"n_dispatches": 0, "latency": [], "n_valid": 0,
                         "n_failovers": 0, "n_deaths": 0, "n_rejoins": 0},
                    )
                    eng["headroom_last"] = float(h)
                    eng["headroom_min"] = min(
                        float(h), eng.get("headroom_min", float(h))
                    )
                continue
            if kind == "decision":
                # The decision observatory (schema v10): per-fleet
                # decision counts at pod scope. The full chain/evidence
                # audit is `python -m glom_tpu.telemetry audit`; the
                # rollup just surfaces how often each fleet acted and
                # how often it acted LATE (after a live breach).
                fleet = str(rec.get("fleet", "fleet0"))
                d = decision_fleets.setdefault(
                    fleet,
                    {"n_decisions": 0, "n_scale_outs": 0,
                     "n_scale_ins": 0, "decisions_late": 0},
                )
                d["n_decisions"] += 1
                action = rec.get("action")
                if action == "scale_out":
                    d["n_scale_outs"] += 1
                    ev = rec.get("evidence")
                    if isinstance(ev, dict) and ev.get("breaches"):
                        d["decisions_late"] += 1
                elif action == "scale_in":
                    d["n_scale_ins"] += 1
                continue
            if kind != "serve":
                continue
            event = rec.get("event")
            if event == "dispatch":
                h["n_dispatches"] += 1
                eng = per_engine.setdefault(
                    str(rec.get("engine")),
                    {"n_dispatches": 0, "latency": [], "n_valid": 0,
                     "n_failovers": 0, "n_deaths": 0, "n_rejoins": 0},
                )
                eng["n_dispatches"] += 1
                if isinstance(rec.get("n_valid"), int):
                    eng["n_valid"] += rec["n_valid"]
                bkt = per_bucket.setdefault(
                    str(rec.get("bucket")),
                    {"n_dispatches": 0, "latency": []},
                )
                bkt["n_dispatches"] += 1
                ms = rec.get("latency_ms")
                if isinstance(ms, (int, float)):
                    dispatch_ms.append(float(ms))
                    h["dispatch_ms"].append(float(ms))
                    eng["latency"].append(float(ms))
                    bkt["latency"].append(float(ms))
            elif event == "resolve":
                n_resolved += 1
                h["n_resolved"] += 1
                ms = rec.get("latency_ms")
                if isinstance(ms, (int, float)):
                    request_ms.append(float(ms))
                    cls = rec.get("slo_class")
                    if isinstance(cls, str):
                        class_ms.setdefault(cls, []).append(float(ms))
                it = rec.get("iters_total")
                if isinstance(it, (int, float)):
                    iters_hist[str(int(it))] = (
                        iters_hist.get(str(int(it)), 0) + 1
                    )
                    iters_total += int(it)
            elif event == "shed":
                n_shed += 1
                h["n_shed"] += 1
            elif event == "response":
                n_responses += 1
                if rec.get("ok") is False:
                    n_failed_responses += 1
                else:
                    ms = rec.get("latency_ms")
                    if isinstance(ms, (int, float)):
                        response_ms.append(float(ms))
            elif event in ("engine_failover", "engine_dead",
                           "engine_rejoin"):
                name = str(rec.get("engine"))
                eng = per_engine.setdefault(
                    name,
                    {"n_dispatches": 0, "latency": [], "n_valid": 0,
                     "n_failovers": 0, "n_deaths": 0, "n_rejoins": 0},
                )
                key = {
                    "engine_failover": "n_failovers",
                    "engine_dead": "n_deaths",
                    "engine_rejoin": "n_rejoins",
                }[event]
                eng[key] += 1
                failover_timeline.append(
                    {"host": host, "event": event, "engine": name}
                )
            elif event == "ladder":
                ladder_timeline.append(
                    {"host": host, "rung": rec.get("rung"),
                     "direction": rec.get("direction")}
                )
            elif event == "summary":
                last_summary = rec
        if last_summary is not None:
            cc = last_summary.get("column_cache")
            if isinstance(cc, dict):
                seen_cache = True
                for k in ("n_hits", "n_misses", "n_writes", "n_evictions"):
                    v = cc.get(k)
                    if isinstance(v, int):
                        cache_totals[k] = cache_totals.get(k, 0) + v
            classes = last_summary.get("classes")
            if isinstance(classes, dict):
                # Per-SLO-class pod rollup (schema v11, serve/qos.py):
                # each host's summary carries its per-tenant
                # conservation counters — sum them across the pod.
                for cls, cnt in classes.items():
                    if not isinstance(cnt, dict):
                        continue
                    tot = class_totals.setdefault(str(cls), {})
                    for k in ("n_requests", "n_served", "n_shed",
                              "n_failed", "n_degraded"):
                        v = cnt.get(k)
                        if isinstance(v, int):
                            tot[k] = tot.get(k, 0) + v
    for h in per_host.values():
        h["dispatch_latency_ms"] = _pcts(h.pop("dispatch_ms"))
    for eng in per_engine.values():
        eng["latency_ms"] = _pcts(eng.pop("latency"))
    for bkt in per_bucket.values():
        bkt["latency_ms"] = _pcts(bkt.pop("latency"))
    # Successes for the shed rate and the request-latency histogram:
    # resolve leaves when the stream has them, ok responses otherwise —
    # max/fallback rather than sum, because a traced stream carries BOTH
    # per request while an UNTRACED one (trace_requests=False) carries
    # only responses; counting resolves alone would read such a stream's
    # one shed as shed_rate 1.0 (same convention as SLOMonitor.observed).
    n_ok_responses = n_responses - n_failed_responses
    served_or_shed = max(n_resolved, n_ok_responses) + n_shed
    if not request_ms:
        request_ms = response_ms
    per_class = None
    if class_totals or class_ms:
        per_class = {}
        for cls in sorted(set(class_totals) | set(class_ms)):
            tot = dict(class_totals.get(cls, {}))
            req = tot.get("n_requests", 0)
            tot["served_fraction"] = (
                round(tot.get("n_served", 0) / req, 4) if req else None
            )
            tot["latency_ms"] = _pcts(class_ms.get(cls, []))
            per_class[cls] = tot
    cache = None
    if seen_cache:
        looked = cache_totals.get("n_hits", 0) + cache_totals.get(
            "n_misses", 0
        )
        cache = dict(
            cache_totals,
            hit_rate=(
                round(cache_totals.get("n_hits", 0) / looked, 4)
                if looked else None
            ),
        )
    return {
        "n_hosts": len(per_host),
        "n_records": sum(h["n_records"] for h in per_host.values()),
        "requests": {
            "n_resolved": n_resolved,
            "n_shed": n_shed,
            "n_responses": n_responses,
            "n_failed_responses": n_failed_responses,
            "shed_rate": (
                round(n_shed / served_or_shed, 4) if served_or_shed else None
            ),
        },
        "latency_ms": {
            "request": _pcts(request_ms),
            "dispatch": _pcts(dispatch_ms),
        },
        "executed_iters": {
            "histogram": iters_hist,
            "mean": (
                round(iters_total / n_resolved, 3) if n_resolved else None
            ),
            "n": n_resolved,
        },
        "per_host": per_host,
        "per_engine": per_engine,
        "per_bucket": per_bucket,
        "per_class": per_class,
        "cache": cache,
        "decisions": decision_fleets or None,
        "timelines": {
            "failover": failover_timeline,
            "ladder": ladder_timeline,
            "barrier": barrier_rounds,
        },
    }


# Every barrier round that COMMITTED must show the full phase chain on
# every participating host — the pod-consistency check the preempt-pod
# chaos evidence is held to (docs/RESILIENCE.md).
BARRIER_CHAIN = ("propose", "commit", "saved", "complete")


def check_barrier_chains(barrier_rounds: Dict[str, Dict[str, list]]) -> List[str]:
    problems = []
    for rnd, phases in sorted(barrier_rounds.items()):
        if "abort" in phases or "commit" not in phases:
            # Aborted / never-committed rounds are their own story — but
            # a COMMITTED round is held to the full chain: a host dying
            # between commit and complete is exactly the partial pod
            # checkpoint this check exists to flag.
            continue
        hosts = {e["host"] for es in phases.values() for e in es}
        for phase in BARRIER_CHAIN:
            got = {e["host"] for e in phases.get(phase, [])}
            if got != hosts:
                problems.append(
                    f"barrier round {rnd}: phase {phase!r} seen on "
                    f"{sorted(got)}, expected every participant "
                    f"{sorted(hosts)}"
                )
        commits = {e.get("step") for e in phases.get("commit", [])}
        if len(commits) > 1:
            problems.append(
                f"barrier round {rnd}: hosts committed DIFFERENT steps "
                f"{sorted(commits, key=str)} — the one-common-step "
                "contract is broken"
            )
    return problems


# -- the live SLO monitor ---------------------------------------------------

# rule name -> (what it bounds, unit). Upper bounds unless listed in
# SLO_LOWER_BOUND_RULES: observed > threshold is a breach.
SLO_RULES = {
    "p50_ms": "windowed p50 of per-request latency_ms",
    "p95_ms": "windowed p95 of per-request latency_ms",
    "p99_ms": "windowed p99 of per-request latency_ms",
    "mean_ms": "windowed mean of per-request latency_ms",
    "shed_rate": "sheds / (sheds + resolved) over the window",
    "failure_rate": "failed responses / responses over the window",
    "mean_iters": "windowed mean of per-request executed iterations",
    "headroom": "windowed MIN of capacity.headroom across engines "
    "(LOWER bound: breach when it drops below the threshold — the "
    "scale-out signal, docs/OBSERVABILITY.md 'Capacity observatory')",
    "forecast_abs_err": "windowed mean of forecast.forecast_abs_err "
    "across matured windows (schema v9, telemetry/forecast.py): the "
    "load forecast's predicted-vs-realized error — a drifting model "
    "breaches here before PR 18's policy would act on bad predictions",
}
# Rules where LESS is the emergency: observed < threshold breaches.
SLO_LOWER_BOUND_RULES = frozenset({"headroom"})

# Rules that accept an SLO-class scope — "p99_ms[premium]=40" windows
# ONLY premium's requests (schema v11, serve/qos.py). Per-request rules
# only: headroom and forecast_abs_err are fleet-level signals with no
# per-tenant meaning.
CLASS_SCOPED_RULES = frozenset(
    {"p50_ms", "p95_ms", "p99_ms", "mean_ms", "shed_rate",
     "failure_rate", "mean_iters"}
)


def split_slo_rule(name: str) -> Tuple[str, Optional[str]]:
    """'p99_ms[premium]' -> ('p99_ms', 'premium'); unscoped names ->
    (name, None). Loud on a malformed scope — '[' with no closing
    bracket or an empty class is a typo, not a rule."""
    base, sep, rest = name.partition("[")
    if not sep:
        return name, None
    if not rest.endswith("]") or not rest[:-1].strip():
        raise ValueError(
            f"SLO rule {name!r}: class scope must be RULE[CLASS]"
        )
    return base, rest[:-1].strip()


def parse_slo(spec: str) -> Tuple[str, float]:
    """'p99_ms=50' -> ('p99_ms', 50.0); 'p99_ms[premium]=40' keeps the
    composite name as the rule key (the monitor windows that class
    alone). Unknown rules fail loudly with the full vocabulary (a
    typo'd SLO that silently never fires is worse than none)."""
    name, sep, value = spec.partition("=")
    base, cls = split_slo_rule(name) if sep else (name, None)
    if not sep or base not in SLO_RULES:
        raise ValueError(
            f"--slo {spec!r}: expected RULE=THRESHOLD with RULE one of "
            f"{sorted(SLO_RULES)} (optionally RULE[CLASS]=THRESHOLD for "
            f"{sorted(CLASS_SCOPED_RULES)})"
        )
    if cls is not None and base not in CLASS_SCOPED_RULES:
        raise ValueError(
            f"--slo {spec!r}: rule {base!r} is fleet-level and takes no "
            f"class scope; class-scoped rules: {sorted(CLASS_SCOPED_RULES)}"
        )
    try:
        return name, float(value)
    except ValueError:
        raise ValueError(f"--slo {spec!r}: threshold {value!r} is not a "
                         "number") from None


class SLOMonitor:
    """Windowed SLO evaluation over a stream of stamped records.

    observe() feeds one record (per-request latency comes from the v6
    "resolve" leaves, falling back to CLI "response" events — records
    sharing a trace_id count ONCE, the resolve/response double-emission
    dedup); evaluate() computes every rule over the trailing window and
    stamps one "slo_breach" record per violated rule through the
    writer-else-flight path. The clock is injectable so tests never
    sleep; window_s=None disables windowing (the --once replay mode)."""

    def __init__(
        self,
        rules: Dict[str, float],
        *,
        window_s: Optional[float] = 60.0,
        min_samples: int = 1,
        writer=None,
        clock=time.monotonic,
    ):
        unknown = []
        for name in rules:
            try:
                base, cls = split_slo_rule(name)
            except ValueError:
                unknown.append(name)
                continue
            if base not in SLO_RULES or (
                cls is not None and base not in CLASS_SCOPED_RULES
            ):
                unknown.append(name)
        if unknown:
            raise ValueError(f"unknown SLO rules {sorted(unknown)}; valid: "
                             f"{sorted(SLO_RULES)} (class-scoped: "
                             f"{sorted(CLASS_SCOPED_RULES)})")
        if window_s is not None and window_s <= 0:
            raise ValueError(f"window_s {window_s} must be > 0 or None")
        if min_samples < 1:
            raise ValueError(f"min_samples {min_samples} must be >= 1")
        self.rules = dict(rules)
        self.window_s = window_s
        self.min_samples = min_samples
        self.writer = writer
        self._clock = clock
        self._latency: deque = deque()   # (t, latency_ms)
        self._iters: deque = deque()     # (t, iters_total)
        self._outcomes: deque = deque()  # (t, "resolved"|"shed"|"failed"|"ok")
        self._headroom: deque = deque()  # (t, headroom)
        self._forecast_err: deque = deque()  # (t, forecast_abs_err)
        self._latency_traces: set = set()
        # Per-SLO-class windows (schema v11, serve/qos.py), fed from
        # class-stamped resolve/settle/shed records. Outcome entries are
        # MUTABLE [t, rid, outcome] triples indexed by request_id: a
        # shed's settle-"failed" fires BEFORE its "shed" leaf (the
        # ticket fails first), so the later, richer terminal reclassifies
        # the same entry instead of double-counting the request.
        self._class_latency: Dict[str, deque] = {}   # (t, ms, rid)
        self._class_lat_rids: Dict[str, set] = {}
        self._class_iters: Dict[str, deque] = {}     # (t, iters_total)
        self._class_events: Dict[str, deque] = {}    # [t, rid, outcome]
        self._class_rid: Dict[str, dict] = {}        # rid -> entry
        self.n_breaches = 0

    def observe(self, rec: dict) -> None:
        if rec.get("kind") == "capacity":
            # The capacity observatory's per-engine headroom rollup
            # (serve/batcher.capacity_records, emitted on every summary):
            # the windowed MIN across engines feeds the one lower-bound
            # rule — one exhausted engine IS the scale-out signal, even
            # while its siblings idle. Engines stamped DRAINING or
            # PROBATION are excluded: a deliberately draining engine's
            # headroom is not load, and counting it would fire a
            # permanent false breach that re-triggers the very
            # autoscaler that caused the drain (schema v8,
            # serve/elastic.py).
            if rec.get("state") in ("draining", "probation"):
                return
            h = rec.get("headroom")
            if isinstance(h, (int, float)) and not isinstance(h, bool):
                now = self._clock()
                self._headroom.append((now, float(h)))
                self._prune(now)
            return
        if rec.get("kind") == "forecast":
            # Forecast evidence (schema v9, telemetry/forecast.py): only
            # matured windows carry a numeric forecast_abs_err — null
            # means the horizon hasn't elapsed yet and is NOT a zero, so
            # it never enters the window.
            err = rec.get("forecast_abs_err")
            if isinstance(err, (int, float)) and not isinstance(err, bool):
                now = self._clock()
                self._forecast_err.append((now, float(err)))
                self._prune(now)
            return
        if rec.get("kind") != "serve":
            return
        now = self._clock()
        event = rec.get("event")
        if event in ("resolve", "response"):
            ok = rec.get("ok", True)
            if event == "resolve" or ok:
                ms = rec.get("latency_ms")
                trace = rec.get("trace_id")
                duplicate = (
                    isinstance(trace, str) and trace in self._latency_traces
                )
                if isinstance(ms, (int, float)) and not duplicate:
                    t_id = trace if isinstance(trace, str) else None
                    self._latency.append((now, float(ms), t_id))
                    if t_id is not None:
                        self._latency_traces.add(t_id)
            if event == "resolve":
                self._outcomes.append((now, "resolved"))
                it = rec.get("iters_total")
                if isinstance(it, (int, float)):
                    self._iters.append((now, float(it)))
            else:
                self._outcomes.append((now, "ok" if ok else "failed"))
        elif event == "shed":
            self._outcomes.append((now, "shed"))
        # Per-class windows (schema v11): class-stamped resolve/settle/
        # shed records feed the class-scoped rules. A request's terminal
        # counts ONCE per class window (request_id-deduped), with the
        # richer "shed" leaf reclassifying its preceding settle-"failed".
        cls = rec.get("slo_class")
        if isinstance(cls, str):
            rid = rec.get("request_id")
            if event == "resolve":
                self._class_terminal(cls, rid, "resolved", now)
                self._class_lat(cls, rid, rec.get("latency_ms"), now)
                it = rec.get("iters_total")
                if isinstance(it, (int, float)) and not isinstance(it, bool):
                    self._class_iters.setdefault(cls, deque()).append(
                        (now, float(it))
                    )
            elif event == "settle":
                outcome = rec.get("outcome")
                if outcome == "served":
                    self._class_terminal(cls, rid, "resolved", now)
                    self._class_lat(cls, rid, rec.get("latency_ms"), now)
                elif outcome == "failed":
                    self._class_terminal(cls, rid, "failed", now)
            elif event == "shed":
                self._class_terminal(cls, rid, "shed", now)
        self._prune(now)

    def _class_terminal(
        self, cls: str, rid, outcome: str, now: float
    ) -> None:
        by_rid = self._class_rid.setdefault(cls, {})
        entry = by_rid.get(rid) if rid is not None else None
        if entry is None:
            entry = [now, rid, outcome]
            self._class_events.setdefault(cls, deque()).append(entry)
            if rid is not None:
                by_rid[rid] = entry
        elif outcome == "shed":
            # The shed leaf arrives AFTER its settle-"failed" (the
            # ticket fails first) — same request, richer terminal.
            entry[2] = "shed"

    def _class_lat(self, cls: str, rid, ms, now: float) -> None:
        if not isinstance(ms, (int, float)) or isinstance(ms, bool):
            return
        rids = self._class_lat_rids.setdefault(cls, set())
        if rid is not None and rid in rids:
            return  # resolve + settle double-emission: count once
        self._class_latency.setdefault(cls, deque()).append(
            (now, float(ms), rid)
        )
        if rid is not None:
            rids.add(rid)

    def _prune(self, now: float) -> None:
        if self.window_s is None:
            return
        horizon = now - self.window_s
        while self._latency and self._latency[0][0] < horizon:
            _, _, t_id = self._latency.popleft()
            # The dedup set ages with the window — a monitor meant to run
            # for days must not grow one entry per request forever.
            if t_id is not None:
                self._latency_traces.discard(t_id)
        for q in (
            self._iters, self._outcomes, self._headroom, self._forecast_err
        ):
            while q and q[0][0] < horizon:
                q.popleft()
        for cls, q in self._class_latency.items():
            rids = self._class_lat_rids.get(cls, set())
            while q and q[0][0] < horizon:
                _, _, rid = q.popleft()
                if rid is not None:
                    rids.discard(rid)
        for cls, q in self._class_events.items():
            by_rid = self._class_rid.get(cls, {})
            while q and q[0][0] < horizon:
                e = q.popleft()
                if e[1] is not None:
                    by_rid.pop(e[1], None)
        for q in self._class_iters.values():
            while q and q[0][0] < horizon:
                q.popleft()

    def observed(self) -> Dict[str, Optional[float]]:
        """Current windowed value of every configured rule (None = not
        enough samples to say)."""
        # Pruning on observe() alone is not enough: a live watch over an
        # idle stream evaluates without ever observing, so a stale burst
        # would keep firing breaches long after it left the window.
        self._prune(self._clock())
        lat = [v for _, v, _ in self._latency]
        iters = [v for _, v in self._iters]
        outcomes = [o for _, o in self._outcomes]
        sheds = outcomes.count("shed")
        responses = outcomes.count("ok") + outcomes.count("failed")
        failed = outcomes.count("failed")
        # Successes for the shed rate: resolve leaves when the stream has
        # them, ok responses otherwise — max of the two, because a traced
        # CLI stream carries BOTH per request (summing would halve the
        # rate) while an UNTRACED stream carries only responses (counting
        # resolves alone would read one shed as shed_rate 1.0).
        resolved = max(outcomes.count("resolved"), outcomes.count("ok"))
        out: Dict[str, Optional[float]] = {}
        for rule in self.rules:
            base, cls = split_slo_rule(rule)
            if cls is not None:
                out[rule] = self._class_observed(base, cls)
                continue
            if rule in ("p50_ms", "p95_ms", "p99_ms", "mean_ms"):
                if len(lat) < self.min_samples:
                    out[rule] = None
                elif rule == "mean_ms":
                    out[rule] = sum(lat) / len(lat)
                else:
                    q = {"p50_ms": 0.5, "p95_ms": 0.95, "p99_ms": 0.99}[rule]
                    out[rule] = percentile(lat, q)
            elif rule == "shed_rate":
                total = sheds + resolved
                out[rule] = sheds / total if total >= self.min_samples else None
            elif rule == "failure_rate":
                out[rule] = (
                    failed / responses
                    if responses >= self.min_samples else None
                )
            elif rule == "mean_iters":
                out[rule] = (
                    sum(iters) / len(iters)
                    if len(iters) >= self.min_samples else None
                )
            elif rule == "headroom":
                vals = [v for _, v in self._headroom]
                out[rule] = (
                    min(vals) if len(vals) >= self.min_samples else None
                )
            elif rule == "forecast_abs_err":
                vals = [v for _, v in self._forecast_err]
                out[rule] = (
                    sum(vals) / len(vals)
                    if len(vals) >= self.min_samples else None
                )
        return out

    def _class_observed(self, base: str, cls: str) -> Optional[float]:
        """One class-scoped rule's windowed value from that class's own
        windows (None = not enough of THAT class's samples — another
        tenant's traffic can never arm or mask a class rule)."""
        if base in ("p50_ms", "p95_ms", "p99_ms", "mean_ms"):
            lat = [v for _, v, _ in self._class_latency.get(cls, ())]
            if len(lat) < self.min_samples:
                return None
            if base == "mean_ms":
                return sum(lat) / len(lat)
            q = {"p50_ms": 0.5, "p95_ms": 0.95, "p99_ms": 0.99}[base]
            return percentile(lat, q)
        outcomes = [e[2] for e in self._class_events.get(cls, ())]
        if base == "shed_rate":
            sheds = outcomes.count("shed")
            total = sheds + outcomes.count("resolved")
            return sheds / total if total >= self.min_samples else None
        if base == "failure_rate":
            total = len(outcomes)
            return (
                outcomes.count("failed") / total
                if total >= self.min_samples else None
            )
        if base == "mean_iters":
            vals = [v for _, v in self._class_iters.get(cls, ())]
            return (
                sum(vals) / len(vals)
                if len(vals) >= self.min_samples else None
            )
        return None

    def evaluate(self) -> List[dict]:
        """One stamped "slo_breach" record per rule whose windowed value
        exceeds its threshold, delivered writer-else-flight (the flight
        recorder counts breaches toward its anomaly-storm trigger) and
        returned. The record carries the watchdog's current backend state
        like every serve row, so a breach during an outage is
        attributable without a join."""
        from glom_tpu.telemetry.watchdog import backend_record
        from glom_tpu.tracing.flight import write_or_observe

        breaches = []
        values = self.observed()
        n_samples = {
            "shed_rate": len(self._outcomes),
            "failure_rate": len(self._outcomes),
            "mean_iters": len(self._iters),
            "headroom": len(self._headroom),
            "forecast_abs_err": len(self._forecast_err),
        }
        for rule, threshold in sorted(self.rules.items()):
            observed = values.get(rule)
            if observed is None:
                continue
            if rule in SLO_LOWER_BOUND_RULES:
                if observed >= threshold:
                    continue
            elif observed <= threshold:
                continue
            base, cls = split_slo_rule(rule)
            if cls is not None:
                ns = (
                    len(self._class_events.get(cls, ()))
                    if base in ("shed_rate", "failure_rate")
                    else len(self._class_iters.get(cls, ()))
                    if base == "mean_iters"
                    else len(self._class_latency.get(cls, ()))
                )
            else:
                ns = n_samples.get(rule, len(self._latency))
            rec = schema.stamp(
                {
                    "rule": rule,
                    "threshold": threshold,
                    "observed": round(observed, 4),
                    "bound": (
                        "lower" if rule in SLO_LOWER_BOUND_RULES
                        else "upper"
                    ),
                    "window_s": self.window_s,
                    "n_samples": ns,
                    "wall_time_s": round(time.time(), 3),
                },
                kind="slo_breach",
            )
            if cls is not None:
                # The breach names its tenant — the elastic policy
                # reads this to decide whether the breach is BINDING
                # (serve/elastic.py low_classes).
                rec["slo_class"] = cls
            for k, v in backend_record().items():
                rec.setdefault(k, v)
            write_or_observe(self.writer, rec)
            breaches.append(rec)
            self.n_breaches += 1
        return breaches


# -- CLIs -------------------------------------------------------------------


def aggregate_main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m glom_tpu.telemetry aggregate",
        description="Merge N hosts' JSONL streams into one pod-level "
        "rollup + timeline (docs/OBSERVABILITY.md, Pod aggregation)",
    )
    ap.add_argument(
        "paths", nargs="+",
        help="host JSONL files and/or directories of *.jsonl",
    )
    ap.add_argument(
        "--out", default=None,
        help="also write the full rollup object to this JSON file",
    )
    ap.add_argument(
        "--timeline", type=int, default=0, metavar="N",
        help="print the first N merged timeline entries (0 = none)",
    )
    ap.add_argument(
        "--strict", action="store_true",
        help="exit nonzero on clock-family violations or broken barrier "
        "chains (the hw-queue / chaos gating mode)",
    )
    args = ap.parse_args(argv)
    hosts = expand_paths(args.paths)
    if not hosts:
        print(f"no JSONL streams under {args.paths}", file=sys.stderr)
        return 1
    try:
        records = load_host_records(hosts)
    except OSError as e:
        print(f"cannot read host stream: {e}", file=sys.stderr)
        return 1
    merged = merge_timeline(records)
    roll = rollup(records)
    problems = list(merged["violations"])
    problems += check_barrier_chains(roll["timelines"]["barrier"])
    for i, e in enumerate(merged["events"][: args.timeline]):
        rec = e["rec"]
        label = (
            rec.get("event")
            or (f"{rec.get('kind')}:{rec.get('phase')}"
                if rec.get("kind") == "barrier" else rec.get("kind"))
        )
        print(
            f"{e['t']:>12.6f}s  {e['host']:<16} {e['clock']:<9} {label}",
            file=sys.stderr,
        )
    for p in problems:
        print(f"AGGREGATE: {p}", file=sys.stderr)
    summary = schema.stamp(
        {
            "summary": True,
            "pod_rollup": roll,
            "n_timeline_events": len(merged["events"]),
            "n_violations": len(problems),
            "hosts": list(hosts),
        },
        kind="summary",
    )
    print(json.dumps(summary))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(
                {"rollup": roll, "violations": problems,
                 "hosts": dict(hosts)},
                fh, indent=2,
            )
    return 1 if (args.strict and problems) else 0


def watch_main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m glom_tpu.telemetry watch",
        description="Live SLO monitor: tail JSONL streams, evaluate "
        "windowed SLO rules, stamp slo_breach events "
        "(docs/OBSERVABILITY.md, SLO watch)",
    )
    ap.add_argument(
        "paths", nargs="+",
        help="JSONL files and/or directories to tail (*.jsonl; new files "
        "are picked up between intervals)",
    )
    ap.add_argument(
        "--slo", action="append", required=True, metavar="RULE=THRESHOLD",
        help=f"repeatable; rules: {', '.join(sorted(SLO_RULES))}",
    )
    ap.add_argument(
        "--window", type=float, default=60.0, metavar="S",
        help="sliding evaluation window in seconds (default 60)",
    )
    ap.add_argument(
        "--interval", type=float, default=2.0, metavar="S",
        help="evaluation cadence while tailing (default 2)",
    )
    ap.add_argument(
        "--min-samples", type=int, default=1, metavar="N",
        help="a rule stays silent below N windowed samples (default 1)",
    )
    ap.add_argument(
        "--once", action="store_true",
        help="replay mode: read everything now, evaluate ONCE over the "
        "whole stream (no window), exit — nonzero iff any rule breached "
        "(the CI smoke / postmortem mode)",
    )
    ap.add_argument(
        "--max-seconds", type=float, default=0.0, metavar="S",
        help="stop tailing after S seconds (0 = until interrupted); exit "
        "nonzero iff any breach fired while watching",
    )
    args = ap.parse_args(argv)
    try:
        rules = dict(parse_slo(s) for s in args.slo)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2

    monitor = SLOMonitor(
        rules,
        window_s=None if args.once else args.window,
        min_samples=args.min_samples,
    )
    offsets: Dict[str, int] = {}

    def drain() -> int:
        n = 0
        for _, path in expand_paths(args.paths).items():
            try:
                with open(path, "rb") as fh:
                    start = offsets.get(path, 0)
                    fh.seek(start)
                    data = fh.read()
            except OSError:
                continue
            # Only consume up to the last complete line: a writer may be
            # mid-flush, and advancing past a torn line would silently
            # drop that record (the next read would start inside it).
            cut = len(data) if args.once else data.rfind(b"\n") + 1
            if cut == 0:
                continue
            offsets[path] = start + cut
            lines = data[:cut].decode("utf-8", "replace").splitlines()
            for _, rec in schema.iter_json_lines(lines):
                monitor.observe(rec)
                n += 1
        return n

    def report(breaches: List[dict]) -> None:
        for b in breaches:
            print(json.dumps(b), flush=True)
            window = (
                f"{b['window_s']}s" if b["window_s"] is not None else "all"
            )
            op = "<" if b.get("bound") == "lower" else ">"
            print(
                f"SLO BREACH: {b['rule']} observed {b['observed']} {op} "
                f"threshold {b['threshold']} "
                f"(n={b['n_samples']}, window={window})",
                file=sys.stderr,
            )

    if args.once:
        if drain() == 0:
            print("no records found to evaluate", file=sys.stderr)
            return 2
        report(monitor.evaluate())
        return 1 if monitor.n_breaches else 0

    deadline = (
        time.monotonic() + args.max_seconds if args.max_seconds > 0 else None
    )
    try:
        while True:
            drain()
            report(monitor.evaluate())
            if deadline is not None and time.monotonic() >= deadline:
                break
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    return 1 if monitor.n_breaches else 0


if __name__ == "__main__":
    sys.exit(aggregate_main())
