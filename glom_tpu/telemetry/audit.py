"""Decision-chain audit: replay every autoscaling decision from JSONL alone.

PR 17 made the elastic fleet's *inputs* observable (scored forecasts,
spawn-lead-time quantiles, replayable workload artifacts); PR 18 makes the
*decisions* observable: every `ElasticPolicy.decide()` that acts stamps a
schema-v10 "decision" record carrying the full EVIDENCE BUNDLE it believed
— forecast window, `forecast_abs_err` at decision time, lead-time
quantile, headroom/dwell inputs, breach set — plus the `decision_id`
chain it extends. This module is both halves of that contract:

  * The PURE POLICY FUNCTION. `policy_action(evidence)` maps one stamped
    evidence bundle to "scale_out" / "scale_in" / None, and
    `anticipated_deficit(evidence)` computes the predicted load excess at
    `now + lead_time_ms` over the fleet's target-utilization capacity.
    serve/elastic.py calls THESE functions on the very dict it stamps, so
    the audit below can re-run them on the JSONL and demand bit-for-bit
    agreement — a decision whose stamped inputs do not reproduce its
    action is corrupted evidence, not a judgment call.

  * The AUDIT. `audit_records()` reconstructs the per-fleet decision
    chain (contiguous decision_ids, each linking its predecessor via
    `prev_decision_id`), checks EVIDENCE CONSERVATION (replayed action ==
    stamped action), checks ACTION COVERAGE (every spawn / drain /
    rollback / spare promotion traces to a stamped decision of the right
    family, and every decision actuated *something*), and scores
    per-decision REGRET: the failure evidence (sheds, failed settles,
    SLO breaches) that landed inside the decision's cover window — the
    interval the spawn was supposed to beat. `python -m glom_tpu.telemetry
    audit FILE... [--strict] [--baseline FILE]` is the CLI; the elastic
    A/B gate runs it over its own output in CI.

Pure stdlib — importable from conftest-less subprocesses and the hw
queue without touching jax or numpy (the same contract as schema.py and
forecast.py). No clock appears anywhere: every timestamp comes off the
records, so replayed artifacts audit deterministically.
"""

from __future__ import annotations

import json
import sys
from typing import Dict, Iterable, List, Optional, Tuple

from glom_tpu.telemetry import schema


def _num(v: object) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


# ---------------------------------------------------------------------------
# The pure policy function (serve/elastic.py ElasticPolicy.decide() calls
# these on the evidence bundle it stamps — keep them dependency-free).
# ---------------------------------------------------------------------------

def rule_class(rule: str) -> Optional[str]:
    """The SLO class a composite rule name is scoped to, or None for a
    fleet-level rule: "p99_ms[premium]" -> "premium", "p99_ms" -> None.
    Mirrors telemetry/aggregate.split_slo_rule, re-derived here so the
    audit stays import-light (the stdlib-only contract); a malformed
    scope is treated as fleet-level rather than raising — the audit
    reads hostile JSONL."""
    base, bracket, rest = str(rule).partition("[")
    if not bracket or not rest.endswith("]") or not base:
        return None
    cls = rest[:-1]
    return cls if cls else None


def binding_breaches(evidence: dict) -> list:
    """The breaches that BIND the policy: every stamped breach whose
    rule is not scoped to one of the evidence's `low_classes`. A bundle
    without `low_classes` (classless, or pre-v11) binds on everything —
    the PR 18 semantics bit-for-bit."""
    breaches = evidence.get("breaches") or []
    low = evidence.get("low_classes")
    if not low:
        return list(breaches)
    low_set = {str(c) for c in low}
    return [r for r in breaches if rule_class(r) not in low_set]


def anticipated_deficit(evidence: dict) -> Optional[float]:
    """Predicted load excess (rps) at `now + lead_time_ms` over the
    fleet's usable capacity, or None when the anticipatory inputs are
    not all present and matured.

    The maturity gate is deliberate: `predicted` null (degenerate fit),
    `forecast_abs_err` null (no prediction has matured — the model has
    never been scored against reality), `lead_time_ms` null (no spawn
    evidence), or a non-positive measured service rate each pin the
    deficit to None, and None means REACTIVE SEMANTICS BIT-FOR-BIT — an
    unproven forecast never spends hardware."""
    if not evidence.get("anticipatory"):
        return None
    fc = evidence.get("forecast")
    if not isinstance(fc, dict):
        return None
    predicted = fc.get("predicted")
    abs_err = fc.get("forecast_abs_err")
    lead_ms = evidence.get("lead_time_ms")
    rate = evidence.get("fleet_service_rate_rps")
    if not (_num(predicted) and _num(abs_err) and _num(lead_ms) and _num(rate)):
        return None
    if rate <= 0:
        return None
    horizon_s = fc.get("horizon_s")
    horizon_s = float(horizon_s) if _num(horizon_s) else 0.0
    trend = fc.get("trend_per_s")
    trend = float(trend) if _num(trend) else 0.0
    # The forecast already looks horizon_s ahead; extrapolate the fitted
    # trend over the REMAINING gap to the spawn-lead instant (never
    # backwards — a lead shorter than the horizon keeps the forecast).
    lead_s = float(lead_ms) / 1e3
    predicted_at_lead = float(predicted) + trend * max(0.0, lead_s - horizon_s)
    target = evidence.get("target_utilization")
    target = float(target) if _num(target) and target > 0 else 1.0
    capacity = float(rate) * target
    return round(predicted_at_lead - capacity, 6)


def policy_action(evidence: dict) -> Optional[str]:
    """The pure decision: one stamped evidence bundle -> "scale_out" /
    "scale_in" / None. This IS the policy — ElasticPolicy.decide() calls
    it on the bundle it is about to stamp, so the audit's replay of the
    same bundle must reproduce the action bit-for-bit.

    Reactive semantics (breach precedence, dwell hysteresis, min/max
    clamps) are the PR 14 contract verbatim; the anticipatory extension
    adds exactly one signal — a positive `anticipated_deficit` arms
    scale-out AND vetoes scale-in (predicted pressure is treated like a
    live breach), and a None deficit changes nothing.

    The QoS extension (evidence key `low_classes`, stamped only when
    SLO classes are declared): breaches scoped to a low class are
    NON-BINDING — they neither force scale-out nor veto an earned
    scale-in. Batch-tenant pressure alone never moves the fleet."""
    n = evidence.get("n_engines")
    if not _num(n):
        return None
    breaches = binding_breaches(evidence)
    dwell_s = evidence.get("dwell_s")
    dwell_s = float(dwell_s) if _num(dwell_s) else 0.0
    held = evidence.get("below_held_s")
    below = _num(held) and held >= dwell_s
    held = evidence.get("above_held_s")
    above = _num(held) and held >= dwell_s
    deficit = anticipated_deficit(evidence)
    anticipated = deficit is not None and deficit > 0
    max_engines = evidence.get("max_engines")
    min_engines = evidence.get("min_engines")
    if (
        (breaches or below or anticipated)
        and _num(max_engines)
        and n < max_engines
    ):
        return "scale_out"
    if breaches or anticipated:
        # Breach precedence, extended: capacity is never removed from a
        # fleet that is failing its SLO — or PREDICTED to, inside the
        # spawn lead the removal could not be undone within.
        return None
    if above and _num(min_engines) and n > min_engines:
        return "scale_in"
    return None


# ---------------------------------------------------------------------------
# The audit: chain + conservation + coverage + regret from JSONL alone.
# ---------------------------------------------------------------------------

# Serve events that belong to a scale-OUT decision's actuation chain vs a
# scale-IN decision's (serve/elastic.py SCALE_EVENTS + the batcher's
# detail-stamped drain/add events). An event outside both families that
# carries a decision_id only needs the decision to EXIST (cache_migrate
# rides the drain detail).
OUT_CHAIN_EVENTS = (
    "scale_out_decision",
    "scale_out",
    "admission_open",
    "spawn_rollback",
    "spare_promote",
    "engine_add",
)
IN_CHAIN_EVENTS = (
    "scale_in_decision",
    "drain_begin",
    "drain_flush",
    "drain_migrate",
    "drain_release",
    "drain_abort",
    "spare_demote",
)

# Events whose presence REQUIRES a stamped decision: the actuations. (The
# acceptance contract: every spawn/drain traces to a decision whose
# inputs reproduce its action.)
ACTUATION_EVENTS = (
    "scale_out",
    "spawn_rollback",
    "spare_promote",
    "drain_release",
    "drain_abort",
    "spare_demote",
)

# Failure evidence for the regret score: what the spawn was supposed to
# prevent, had it landed in time.
_FAILED_OUTCOMES = ("failed", "shed")


def _failure_class(rec: dict) -> Optional[str]:
    """The SLO class one failure record charges: the v11 `slo_class`
    stamp on sheds/settles/breaches, falling back to the breach rule's
    scope. None = classless (weight 1.0)."""
    cls = rec.get("slo_class")
    if isinstance(cls, str) and cls:
        return cls
    rule = rec.get("rule")
    if isinstance(rule, str):
        return rule_class(rule)
    return None


def _ts(rec: dict) -> Optional[float]:
    """The record's run-relative timestamp: `wall_time` (MetricsWriter's
    one clock per stream) first, the record's own `t` otherwise."""
    for key in ("wall_time", "t"):
        if _num(rec.get(key)):
            return float(rec[key])
    return None


def _fleet(rec: dict) -> str:
    f = rec.get("fleet")
    return f if isinstance(f, str) and f else "fleet0"


def audit_records(
    records: Iterable[dict],
    *,
    default_cover_s: float = 1.0,
) -> dict:
    """Audit one record stream (ONE fleet run per fleet label — do not
    concatenate two runs of the same fleet into one stream; their
    decision chains would collide). Returns the report dict; `errors`
    non-empty means the evidence is structurally broken, `warnings`
    flags suspicious-but-survivable shapes (--strict fails them too)."""
    decisions: Dict[Tuple[str, int], dict] = {}
    chain_events: List[dict] = []
    # (t, slo_class-or-None): v11 failure evidence carries the tenant
    # class, so regret can be scored class-weighted. Classless records
    # land with None and weight 1.0 — the raw count is unchanged.
    failures: List[Tuple[float, Optional[str]]] = []
    errors: List[str] = []
    warnings: List[str] = []
    n_records = 0
    for rec in records:
        if not isinstance(rec, dict):
            continue
        n_records += 1
        kind = rec.get("kind")
        if kind == "decision":
            did = rec.get("decision_id")
            if not isinstance(did, int) or isinstance(did, bool):
                errors.append(
                    f"decision record with non-int decision_id {did!r}"
                )
                continue
            key = (_fleet(rec), did)
            if key in decisions:
                errors.append(
                    f"duplicate decision_id {did} in fleet {key[0]!r}"
                )
                continue
            decisions[key] = rec
        elif kind == "serve":
            event = rec.get("event")
            if "decision_id" in rec and rec.get("decision_id") is not None:
                chain_events.append(rec)
            elif event in ACTUATION_EVENTS:
                errors.append(
                    f"serve.{event} carries no decision_id — an actuation "
                    "outside the decision chain"
                )
            if event == "shed" or (
                event == "settle" and rec.get("outcome") in _FAILED_OUTCOMES
            ):
                t = _ts(rec)
                if t is not None:
                    failures.append((t, _failure_class(rec)))
        elif kind == "slo_breach":
            t = _ts(rec)
            if t is not None:
                failures.append((t, _failure_class(rec)))
    failures.sort(key=lambda f: f[0])

    # -- chain: per fleet, contiguous ids, each linking its predecessor --
    fleets = sorted({f for f, _ in decisions})
    for fleet in fleets:
        ids = sorted(i for f, i in decisions if f == fleet)
        prev = None
        for i in ids:
            rec = decisions[(fleet, i)]
            if prev is not None and i != prev + 1:
                errors.append(
                    f"fleet {fleet!r} decision chain gap: {prev} -> {i}"
                )
            stamped_prev = rec.get("prev_decision_id")
            if stamped_prev != prev:
                errors.append(
                    f"fleet {fleet!r} decision {i} stamps "
                    f"prev_decision_id {stamped_prev!r}, expected {prev!r}"
                )
            prev = i

    # -- conservation: the stamped inputs must reproduce the action -----
    n_conserved = 0
    for (fleet, did), rec in sorted(decisions.items()):
        action = rec.get("action")
        evidence = rec.get("evidence")
        if not isinstance(evidence, dict):
            errors.append(
                f"fleet {fleet!r} decision {did} carries no evidence bundle"
            )
            continue
        replayed = policy_action(evidence)
        if replayed != action:
            errors.append(
                f"fleet {fleet!r} decision {did}: stamped action "
                f"{action!r} but the evidence replays to {replayed!r}"
            )
        else:
            n_conserved += 1

    # -- coverage: every actuation traces to a decision of its family ---
    actuated: Dict[Tuple[str, int], int] = {}
    for rec in chain_events:
        did = rec.get("decision_id")
        if not isinstance(did, int) or isinstance(did, bool):
            errors.append(
                f"serve.{rec.get('event')} carries non-int decision_id "
                f"{did!r}"
            )
            continue
        key = (_fleet(rec), did)
        dec = decisions.get(key)
        if dec is None:
            errors.append(
                f"serve.{rec.get('event')} references decision_id {did} "
                f"(fleet {key[0]!r}) but no decision record stamps it"
            )
            continue
        actuated[key] = actuated.get(key, 0) + 1
        event = rec.get("event")
        if event in OUT_CHAIN_EVENTS and dec.get("action") != "scale_out":
            errors.append(
                f"serve.{event} chains to decision {did} whose action is "
                f"{dec.get('action')!r}, not scale_out"
            )
        elif event in IN_CHAIN_EVENTS and dec.get("action") != "scale_in":
            errors.append(
                f"serve.{event} chains to decision {did} whose action is "
                f"{dec.get('action')!r}, not scale_in"
            )
    for key, rec in sorted(decisions.items()):
        if key not in actuated:
            warnings.append(
                f"fleet {key[0]!r} decision {key[1]} actuated no serve "
                "event (truncated stream?)"
            )

    # -- regret: failure evidence inside each scale-out's cover window --
    spawn_ms_by_decision: Dict[Tuple[str, int], float] = {}
    for rec in chain_events:
        if rec.get("event") in ("scale_out", "spare_promote"):
            ms = rec.get("spawn_ms")
            if not _num(ms):
                ms = rec.get("promote_ms")
            if _num(ms):
                key = (_fleet(rec), rec.get("decision_id"))
                spawn_ms_by_decision[key] = float(ms)
    regret_total = 0
    regret_weighted_total = 0.0
    decisions_late = 0
    lead_violations = 0
    per_decision: List[dict] = []
    for key, rec in sorted(decisions.items()):
        if rec.get("action") != "scale_out":
            continue
        evidence = rec.get("evidence") or {}
        late = bool(binding_breaches(evidence))
        if late:
            # Scaled AFTER the SLO already broke: the reactive failure
            # mode the anticipatory policy exists to avoid. A breach
            # scoped to a low class is not "late" — it could not have
            # driven the decision.
            decisions_late += 1
        lead_ms = evidence.get("lead_time_ms")
        spawn_ms = spawn_ms_by_decision.get(key)
        if _num(lead_ms) and _num(spawn_ms) and spawn_ms > lead_ms:
            lead_violations += 1
        if _num(lead_ms):
            cover_s = float(lead_ms) / 1e3
        elif _num(spawn_ms):
            cover_s = float(spawn_ms) / 1e3
        else:
            cover_s = default_cover_s
        t = _ts(rec)
        if t is not None:
            covered = [
                cls for ft, cls in failures if t <= ft <= t + cover_s
            ]
            regret = len(covered)
            # Class-weighted regret: each covered failure charges its
            # class's stamped weight (the decision's own evidence — the
            # audit invents nothing), classless failures charge 1.0.
            weights = evidence.get("class_weights") or {}
            regret_weighted = round(
                sum(
                    float(weights.get(cls, 1.0)) if cls else 1.0
                    for cls in covered
                ),
                6,
            )
        else:
            regret = None
            regret_weighted = None
        if regret is not None:
            regret_total += regret
            regret_weighted_total += regret_weighted
        per_decision.append(
            {
                "fleet": key[0],
                "decision_id": key[1],
                "regret": regret,
                "regret_weighted": regret_weighted,
                "cover_s": round(cover_s, 6),
                "late": late,
            }
        )

    return {
        "n_records": n_records,
        "fleets": fleets,
        "n_decisions": len(decisions),
        "n_conserved": n_conserved,
        "n_chain_events": len(chain_events),
        "n_failure_signals": len(failures),
        "regret_total": regret_total,
        "regret_weighted": round(regret_weighted_total, 6),
        "regret_per_decision": per_decision,
        "decisions_late": decisions_late,
        "spawn_lead_violations": lead_violations,
        "errors": errors,
        "warnings": warnings,
    }


def load_records(path: str) -> List[dict]:
    with open(path) as fh:
        return [rec for _, rec in schema.iter_json_lines(fh)]


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m glom_tpu.telemetry audit",
        description=(
            "Reconstruct the elastic fleet's decision chain from JSONL "
            "evidence: chain integrity, evidence conservation (stamped "
            "inputs replay to the stamped action through the pure policy "
            "function), actuation coverage, and per-decision regret."
        ),
    )
    ap.add_argument("paths", nargs="+", help="JSONL evidence streams")
    ap.add_argument(
        "--strict", action="store_true",
        help="fail on warnings too (un-actuated decisions)",
    )
    ap.add_argument(
        "--baseline", default=None,
        help="a second stream (e.g. the reactive arm of the same replay) "
        "to audit and diff regret against — the counterfactual",
    )
    ap.add_argument(
        "--default-cover-s", type=float, default=1.0,
        help="regret cover window when a decision stamps no lead time "
        "and no spawn latency landed (default 1.0)",
    )
    args = ap.parse_args(argv)

    rc = 0
    totals = {"regret_total": 0, "regret_weighted": 0.0,
              "decisions_late": 0, "spawn_lead_violations": 0,
              "n_decisions": 0}
    for path in args.paths:
        report = audit_records(
            load_records(path), default_cover_s=args.default_cover_s
        )
        for e in report["errors"]:
            print(f"{path}: ERROR: {e}", file=sys.stderr)
        for w in report["warnings"]:
            print(f"{path}: WARNING: {w}", file=sys.stderr)
        if report["errors"] or (args.strict and report["warnings"]):
            rc = 1
        for k in totals:
            totals[k] += report[k]
        summary = {
            "audit": path,
            "ok": not report["errors"],
            **{
                k: report[k]
                for k in (
                    "n_records", "fleets", "n_decisions", "n_conserved",
                    "n_chain_events", "n_failure_signals", "regret_total",
                    "regret_weighted", "decisions_late",
                    "spawn_lead_violations",
                )
            },
            "n_errors": len(report["errors"]),
            "n_warnings": len(report["warnings"]),
        }
        print(json.dumps(schema.stamp(summary, kind="summary")))
    if args.baseline is not None:
        base = audit_records(
            load_records(args.baseline),
            default_cover_s=args.default_cover_s,
        )
        delta = {
            "audit": "baseline-delta",
            "baseline": args.baseline,
            "baseline_regret_total": base["regret_total"],
            "regret_total": totals["regret_total"],
            # Negative = the audited streams beat the counterfactual.
            "regret_delta": totals["regret_total"] - base["regret_total"],
            "regret_weighted_delta": round(
                totals["regret_weighted"] - base["regret_weighted"], 6
            ),
            "decisions_late_delta": (
                totals["decisions_late"] - base["decisions_late"]
            ),
        }
        print(json.dumps(schema.stamp(delta, kind="summary")))
    return rc


if __name__ == "__main__":
    sys.exit(main())
