"""Versioned JSONL event schema — the one record contract every sink speaks.

Rounds 4-5 went blind because each evidence trail had its own ad-hoc shape
(driver-parsed bench lines, MetricsWriter dicts, a shell watcher.log): when
the backend wedged there was no machine-checkable stream to reconstruct the
outage from. This module is the fix's foundation: every record any part of
the framework writes — trainer metrics, bench lines, watchdog transitions,
anomaly events — carries `schema_version` and a `kind`, and validates
against the field contract below. `python -m glom_tpu.telemetry.schema
FILE...` lints any log (JSON lines mixed with shell noise are fine; noise
is skipped, stamped records must validate) — run_hw_queue.sh and CI both
call it on bench output.

Versioning: SCHEMA_VERSION bumps on any breaking field change; readers
accept records with version <= theirs. Pure stdlib — importable from
conftest-less subprocesses and the hw queue without touching jax.
"""

from __future__ import annotations

import json
import sys
from typing import Iterable, List, Optional, Tuple

# v2 added the "span" kind (host-side tracing, glom_tpu/tracing/spans.py)
# and the "error" kind (UNMEASURED bench rows: value null + a machine-
# readable error string, so trajectory tooling never ingests dead zeros).
# v3 added the "serve" kind (glom_tpu/serve: inference-engine lifecycle —
# warmup compiles, batch dispatches, request responses, shed decisions).
# v4 added the "fault" kind (glom_tpu/resilience/faults.py: one INJECTED
# failure — the chaos harness's ground truth, so recovery can be verified
# against exactly what was injected) and the "recovery" kind (one recovery
# decision or action: checkpoint resume, dispatch retry, torn-checkpoint
# skip, preemption save — docs/RESILIENCE.md).
# v5 added the "barrier" kind (glom_tpu/resilience/coordinator.py: one
# phase of a pod-coordination round — a preemption save barrier's
# propose/commit/saved/complete/abort, a gang-restart rendezvous — so a
# multi-process chaos run can reconcile every host's view of the SAME
# round from the per-host evidence streams alone).
# v6 added request-scoped TRACE CONTEXT (telemetry/tracectx.py): serve
# records of request-scoped events carry trace_id/span_id/parent_span
# (batch-level records the parallel trace_ids/parent_spans lists), the
# new "resolve" serve event is the per-request conservation leaf, and
# the new "slo_breach" kind is one windowed SLO-rule violation from the
# live monitor (`python -m glom_tpu.telemetry watch`,
# telemetry/aggregate.py).
# v7 is the capacity observatory (docs/OBSERVABILITY.md): the new
# "collective_time" kind is one registered collective site's measured
# wall time (telemetry/comm_time.py — site/axis/bytes/wall_ms/bytes_per_s
# plus the α-β comm_time_model fit and its drift), the new "capacity"
# kind is one engine's headroom rollup (service-rate estimate x live
# queue/continuation/affinity/page-pool occupancy — the signal
# `telemetry watch --slo headroom=X` tails and the elastic-serving
# control loop will read), and serve "dispatch" records split latency_ms
# into queue_wait/pack/h2d/device/resolve phase fields that sum to it
# bit-exactly (conservation extended by `telemetry trace`).
# v8 is elastic serving (glom_tpu/serve/elastic.py, docs/SERVING.md
# "Elastic serving"): new serve events for the autoscaler's decision and
# transition chain — "scale_out_decision"/"scale_in_decision" (the
# triggering signal window embedded), "scale_out" (+spawn_ms),
# "admission_open" (a spawned replica opens for traffic strictly after
# its warmup precompile), "spawn_rollback" (a failed scale-out rolled
# back loudly), "drain_begin"/"drain_flush"/"drain_migrate"/
# "drain_release" (the graceful scale-in state machine), "engine_add",
# "cache_migrate" (one session's paged columns moved to a sibling pool)
# — each carrying the decision_id that chains it to its decision; and
# "capacity" records now stamp `state` ("ok" | "draining" | "probation"
# | "dead") so the SLO monitor can EXCLUDE deliberately draining or
# probing engines from the headroom windowed-min.
# v9 is the workload observatory (serve/workload.py,
# telemetry/forecast.py, docs/OBSERVABILITY.md "Workload observatory"):
# the new "workload" kind is one OFFERED request — arrival time `t`
# (seconds, run-relative), shape `signature` ("bucket:CxHxW" |
# "ragged:<pages>p" | "delta:CxHxW"), and `outcome` ("served" | "shed" |
# "failed" | "unresolved" | "offered" — the last is a scenario-generated
# request not yet realized); a workload JSONL artifact replays
# deterministically (bench_serve.py --replay, python -m glom_tpu.serve
# --replay). The new "forecast" kind is one scored short-horizon
# prediction — `metric` names the forecast series ("arrival_rate_rps",
# "service_rate_rps", "spawn_lead_time"), `horizon_s` how far ahead it
# looked, and the `forecast_abs_err` KEY must be PRESENT on every
# record (null = no prediction matured yet — degenerate fits pin
# honestly like the α-β model; an ABSENT key means the emitter never
# scored itself, which is a lint failure, not a silent gap). The new
# serve event "engine_husk_retired" folds a pruned drained-husk's
# counters into the evidence stream so summary conservation still
# reconciles after retention trims the engines nest.
# v10 is the decision observatory (serve/elastic.py, telemetry/audit.py,
# docs/OBSERVABILITY.md "Decision observatory"): the new "decision" kind
# is one autoscaling decision that ACTED — `action` ("scale_out" |
# "scale_in"), `decision_id` extending the per-fleet chain
# (`prev_decision_id` links backwards; `fleet` labels the chain), and
# the `evidence` KEY must be PRESENT on every record: the full input
# bundle (headroom/dwell/breach state, the forecast window believed at
# decision time with its forecast_abs_err, the spawn-lead-time quantile,
# the measured fleet service rate) that the pure policy function
# (telemetry/audit.py policy_action) must replay to the stamped action
# bit-for-bit — `python -m glom_tpu.telemetry audit` enforces it. New
# serve events "spare_spawn" / "spare_promote" / "spare_demote" stamp
# the warm-pool spare lifecycle (pre-spawned engines held outside
# admission), each promotion/demotion carrying its owning decision_id.
# v11 is multi-tenant QoS (serve/qos.py, docs/SERVING.md "SLO classes"):
# REQUEST-scoped serve events ("admit" / "shed" / "settle" / "resolve")
# and "workload" records must carry the `slo_class` KEY (null = a
# classless config — fine; ABSENT = an emit site that never threaded
# the class, a lint failure — the v6 trace-key presence precedent).
# The serve "summary" grows per-class `classes` + `class_scheduler`
# nests, "capacity" records a per-class `class_fill`, and decision
# evidence stamps `low_classes` / `class_weights` so `telemetry audit`
# can replay class-aware policy and score class-weighted regret.
SCHEMA_VERSION = 11

_NUM = (int, float)
_STR = (str,)

# kind -> {required field: allowed JSON types}. Extra fields are always
# allowed (records grow; the schema pins the load-bearing core).
KINDS = {
    # One optimizer step's metrics (trainer fit loops).
    "train_step": {"step": _NUM, "loss": _NUM},
    # One benchmark measurement (bench*.py; the driver tail-parses these).
    "bench": {"metric": _STR, "value": _NUM, "unit": _STR},
    # A backend-liveness state transition (telemetry/watchdog.py).
    "watchdog": {"backend_state": _STR, "t": _NUM},
    # Something went wrong inside a run (NaN/Inf guard, skip-step, ...).
    "anomaly": {"step": _NUM, "reason": _STR},
    # End-of-run rollups (loss-curve summaries etc.).
    "summary": {},
    # Free-text context lines (e.g. bench cpu-fallback notes).
    "note": {"note": _STR},
    # A timed host-side span (glom_tpu/tracing/spans.py): dur_s is the
    # (total) seconds attributed to `name`.
    "span": {"name": _STR, "dur_s": _NUM},
    # A measurement that could NOT be taken (backend down, OOM): carries
    # `value: null` — NEVER 0.0 — plus the error string; the compare gate
    # and trajectory tooling treat these as missing, not zero.
    "error": {"error": _STR},
    # One inference-serving lifecycle event (glom_tpu/serve): `event` names
    # it — "warmup" (one AOT compile per bucket), "dispatch" (one batched
    # forward), "response" (one request served), "shed" (admission
    # rejected), "ladder" (one degradation-ladder rung transition),
    # "summary" (end-of-run rollup). Extra fields (bucket, n_valid,
    # latency_ms, iters_run, rung, queue_fill, ...) ride per event.
    "serve": {"event": _STR},
    # One INJECTED failure (glom_tpu/resilience/faults.py): `fault` names
    # the fault class ("backend-flap", "dispatch-error", "nan-storm",
    # "ckpt-write", "queue-stall", ...); `site` and `index` pin where and
    # which occurrence, so a chaos run's recovery events can be reconciled
    # one-to-one against what the harness actually injected.
    "fault": {"fault": _STR},
    # One recovery decision or action (docs/RESILIENCE.md): `action` names
    # it — "resume-from-checkpoint", "restart", "dispatch-retry",
    # "skip-torn-checkpoint", "preemption-checkpoint", "give-up",
    # "quarantine-half-step", "gang-stop". Extra fields (step, attempt,
    # backoff_s, ...) ride per action.
    "recovery": {"action": _STR},
    # One phase of a pod-coordination round (resilience/coordinator.py):
    # `phase` names it — "propose" (this host's highest dispatchable
    # step), "commit" (the round's agreed min), "saved" (this host landed
    # the committed step), "complete" (every host acked), "abort" (the
    # deadline passed or a peer aborted — NO partial pod checkpoint may
    # masquerade as complete), "arrive" (gang-restart rendezvous).
    # `round` identifies the round; host/n_hosts/step ride per phase.
    "barrier": {"phase": _STR, "round": _STR},
    # One windowed SLO-rule violation from the live monitor
    # (telemetry/aggregate.py, `python -m glom_tpu.telemetry watch`):
    # `rule` names the violated rule ("p99_ms", "shed_rate", ...);
    # threshold/observed/window_s/n_samples ride per breach. The flight
    # recorder counts these toward its anomaly-storm trigger.
    "slo_breach": {"rule": _STR},
    # One registered collective site's measured wall time
    # (telemetry/comm_time.py): `site` names the record_collective-
    # registered site, `wall_ms` its measured wall clock; axis /
    # collective / wire_bytes / bytes_per_s / mode ("sampled" | "full")
    # / comm_time_model_ms / comm_time_model_drift ride per row, and the
    # `site: "comm_time_model"` row carries the fitted α-β form itself.
    "collective_time": {"site": _STR, "wall_ms": _NUM},
    # One engine's capacity/headroom rollup (serve/batcher.py,
    # docs/OBSERVABILITY.md "Capacity observatory"): `headroom` in [0, 1]
    # is 1 - the worst live occupancy across the engine's lanes (queue /
    # continuation / affinity / page pool); service_rate_rps estimates
    # the sustainable requests/s from the measured dispatch latencies.
    # `telemetry watch --slo headroom=X` breaches when it drops BELOW X
    # (the one lower-bound rule).
    "capacity": {"engine": _STR, "headroom": _NUM},
    # One OFFERED serving request (serve/workload.py WorkloadRecorder,
    # docs/OBSERVABILITY.md "Workload observatory"): `t` is the arrival
    # time in run-relative seconds, `signature` the admission shape
    # ("bucket:CxHxW" | "ragged:<pages>p" | "delta:CxHxW"), `outcome`
    # what became of it ("served" | "shed" | "failed" | "unresolved" |
    # "offered"). session / shape / seed / latency_ms / detail ride
    # per record; a stream of these IS the replayable artifact.
    "workload": {"t": _NUM, "signature": _STR, "outcome": _STR},
    # One scored short-horizon prediction (telemetry/forecast.py):
    # `metric` names the series, `horizon_s` the look-ahead. predicted /
    # realized / forecast_abs_err / lead_time_ms / trend_per_s /
    # seasonal / n_samples / reason ride per record; the
    # forecast_abs_err KEY must be present on every v9 record (null =
    # nothing matured yet; absent = the emitter never scored itself —
    # enforced by validate_record below).
    "forecast": {"metric": _STR, "horizon_s": _NUM},
    # One autoscaling decision that acted (serve/elastic.py,
    # telemetry/audit.py, docs/OBSERVABILITY.md "Decision observatory"):
    # `action` is "scale_out" | "scale_in", `decision_id` extends the
    # per-fleet chain (prev_decision_id / fleet / t ride per record),
    # and the `evidence` key — the full input bundle the pure policy
    # function replays bit-for-bit — must be present on every v10
    # record (enforced by validate_record below).
    "decision": {"action": _STR, "decision_id": _NUM},
}

# Serve events that are REQUEST-scoped and must carry trace context on
# schema-v6 records (telemetry/tracectx.py mints and reconstructs it; the
# key may be null — an explicitly UNTRACED record, ServeConfig.
# trace_requests=False — but it must be PRESENT, so an emit site that
# forgot the threading is a lint failure, not a silent gap in the tree).
TRACE_REQUIRED_EVENTS = (
    "dispatch",
    "continuation",
    "shed",
    "resolve",
    "engine_failover",
    "dispatch_error",
    "response",
)
_TRACE_KEYS = ("trace_id", "trace_ids")

# Serve events that are scoped to ONE request and must carry the SLO
# class key on schema-v11 records (serve/qos.py; null = classless config,
# absent = the emit site never threaded the class — the same
# present-but-nullable contract as the v6 trace keys above).
CLASS_REQUIRED_EVENTS = (
    "admit",
    "shed",
    "settle",
    "resolve",
)
_CLASS_KEY = "slo_class"

WATCHDOG_STATES = ("unknown", "up", "down", "flapping")


class SchemaError(ValueError):
    pass


def infer_kind(rec: dict) -> str:
    """Best-effort kind for legacy records written before stamping."""
    if "fault" in rec:
        return "fault"
    if "site" in rec and "wall_ms" in rec:
        return "collective_time"
    if "headroom" in rec and "engine" in rec:
        return "capacity"
    if "phase" in rec and "round" in rec:
        return "barrier"
    if "backend_state" in rec and ("t" in rec or "event" in rec):
        return "watchdog"
    if "name" in rec and "dur_s" in rec:
        return "span"
    if "error" in rec and not isinstance(rec.get("value"), _NUM):
        # An UNMEASURED row (value null/absent + error string) is an
        # "error" record; a MEASURED row that merely carries an error
        # context field still infers by its numeric value below.
        return "error"
    if "metric" in rec and "value" in rec:
        return "bench"
    if "reason" in rec and "step" in rec:
        return "anomaly"
    if "note" in rec:
        return "note"
    if "summary" in rec:
        return "summary"
    if "loss" in rec or "step" in rec:
        return "train_step"
    return "summary"


def stamp(rec: dict, kind: Optional[str] = None) -> dict:
    """Return a copy of `rec` carrying schema_version + kind (idempotent:
    existing stamps are preserved, so double-stamping through nested sinks
    cannot relabel a record)."""
    out = dict(rec)
    out.setdefault("schema_version", SCHEMA_VERSION)
    out.setdefault("kind", kind if kind is not None else infer_kind(rec))
    return out


def validate_record(rec: object) -> List[str]:
    """Errors for one decoded record; empty list = valid."""
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not an object"]
    errs = []
    v = rec.get("schema_version")
    if not isinstance(v, int) or isinstance(v, bool):
        errs.append(f"schema_version {v!r} is not an int")
    elif not 1 <= v <= SCHEMA_VERSION:
        errs.append(f"schema_version {v} outside 1..{SCHEMA_VERSION}")
    kind = rec.get("kind")
    if kind not in KINDS:
        errs.append(f"kind {kind!r} not one of {sorted(KINDS)}")
        return errs
    for field, types in KINDS[kind].items():
        if field not in rec:
            errs.append(f"{kind} record missing required field {field!r}")
        elif not isinstance(rec[field], types) or isinstance(rec[field], bool):
            errs.append(
                f"{kind}.{field} is {type(rec[field]).__name__}, "
                f"expected {'/'.join(t.__name__ for t in types)}"
            )
    if kind == "watchdog" and rec.get("backend_state") not in WATCHDOG_STATES:
        errs.append(
            f"watchdog.backend_state {rec.get('backend_state')!r} not one "
            f"of {WATCHDOG_STATES}"
        )
    if (
        kind == "serve"
        and isinstance(v, int)
        and v >= 6
        and rec.get("event") in TRACE_REQUIRED_EVENTS
        and not any(k in rec for k in _TRACE_KEYS)
    ):
        # v6's request-tracing contract: request-scoped serve events must
        # carry trace context (null = explicitly untraced is fine; an
        # ABSENT key means an emit site never threaded the context and
        # this record can never join its request's tree).
        errs.append(
            f"serve.{rec.get('event')} record (v{v}) carries no trace "
            f"context key ({'/'.join(_TRACE_KEYS)}) — see "
            "telemetry/tracectx.py"
        )
    if (
        isinstance(v, int)
        and v >= 11
        and (
            (kind == "serve" and rec.get("event") in CLASS_REQUIRED_EVENTS)
            or kind == "workload"
        )
        and _CLASS_KEY not in rec
    ):
        # v11's multi-tenant contract (the v6 trace-key pattern):
        # request-scoped serve events and workload records must carry
        # the slo_class KEY — null on a classless config, but never
        # silently absent, so per-tenant conservation can always be
        # reconciled (see serve/qos.py).
        what = (
            f"serve.{rec.get('event')}" if kind == "serve" else "workload"
        )
        errs.append(
            f"{what} record (v{v}) carries no {_CLASS_KEY} key — the SLO "
            "class must be stamped on every request-scoped record (null = "
            "classless; see glom_tpu/serve/qos.py)"
        )
    if (
        kind == "forecast"
        and isinstance(v, int)
        and v >= 9
        and "forecast_abs_err" not in rec
    ):
        # v9's forecast-quality contract (the trace-presence pattern):
        # every forecast record must carry its predicted-vs-realized
        # error KEY — null while no prediction has matured (degenerate
        # fits pin honestly), but never silently absent, so an emitter
        # that stopped scoring itself is a lint failure the moment it
        # writes, not a quiet gap in the gate.
        errs.append(
            f"forecast.{rec.get('metric')} record (v{v}) carries no "
            "forecast_abs_err key — predicted-vs-realized error must be "
            "stamped on every window (null = not matured; absent = "
            "unscored; see telemetry/forecast.py)"
        )
    if (
        kind == "decision"
        and isinstance(v, int)
        and v >= 10
        and "evidence" not in rec
    ):
        # v10's decision-provenance contract (the same presence pattern):
        # a decision without its inputs on the record can never be
        # audited — `telemetry audit` replays the evidence through the
        # pure policy function and demands the stamped action back.
        errs.append(
            f"decision.{rec.get('action')} record (v{v}) carries no "
            "evidence key — the input bundle must be stamped on every "
            "decision (see telemetry/audit.py)"
        )
    try:
        json.dumps(rec)
    except (TypeError, ValueError) as e:
        errs.append(f"record is not JSON-serializable: {e}")
    return errs


def assert_valid(rec: dict) -> dict:
    errs = validate_record(rec)
    if errs:
        raise SchemaError("; ".join(errs))
    return rec


def iter_json_lines(lines: Iterable[str]) -> Iterable[Tuple[int, dict]]:
    """(lineno, record) for every line that parses as a JSON object —
    shell noise, timestamps, and tracebacks interleaved in hw-queue logs
    are skipped, not errors."""
    for i, line in enumerate(lines, 1):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict):
            yield i, rec


def lint_stream(
    lines: Iterable[str],
    *,
    require_stamp: bool = True,
    require_records: bool = True,
) -> List[str]:
    """Validate every JSON record in a log stream. require_stamp=True (the
    CI mode) also fails records that never got a schema_version — the
    whole point is that no sink writes unstamped rows anymore.
    require_records=True additionally fails a stream with NO JSON records
    at all (an empty bench log is the round-5 'empty evidence trajectory'
    regression); the queue's mixed-log sweep passes False, since probe /
    tpu_validate logs legitimately contain no JSON."""
    errors = []
    n = 0
    for lineno, rec in iter_json_lines(lines):
        n += 1
        if "schema_version" not in rec and not require_stamp:
            continue
        for e in validate_record(rec):
            errors.append(f"line {lineno}: {e}")
    if n == 0 and require_records:
        errors.append("no JSON records found")
    return errors


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m glom_tpu.telemetry.schema",
        description="Lint JSONL telemetry/bench logs against the event schema",
    )
    ap.add_argument("paths", nargs="+")
    ap.add_argument(
        "--allow-unstamped", action="store_true",
        help="skip records without schema_version instead of failing them; "
        "also tolerates files with no JSON records at all (the hw-queue "
        "sweep over mixed shell logs)",
    )
    args = ap.parse_args(argv)
    rc = 0
    for path in args.paths:
        with open(path) as fh:
            errs = lint_stream(
                fh,
                require_stamp=not args.allow_unstamped,
                require_records=not args.allow_unstamped,
            )
        if errs:
            rc = 1
            for e in errs:
                print(f"{path}: {e}", file=sys.stderr)
        else:
            print(f"{path}: OK")
    return rc


if __name__ == "__main__":
    sys.exit(main())
