"""Perfetto export: span/flight JSONL -> a browsable timeline.

The flight recorder answers "what were the last N events"; spans answer
"where did host time go" — but both as JSONL you read with grep. Perfetto
(ui.perfetto.dev) reads the Chrome JSON trace-event format natively, and
every stamped record this framework writes already carries enough to place
it on a timeline, so the conversion is mechanical:

  * "span" records WITH a start time (t_start from span(writer=...)) become
    complete events (ph "X": name, ts, dur) on a per-depth track — the real
    nested timeline;
  * rollup "span" records (SpanAggregator drains carry only total dur_s /
    count) become counter samples (ph "C") of seconds-per-drain per phase —
    the per-phase load curve over the run;
  * watchdog records become instant events (ph "i") named by state — an
    outage is a visible gash in the timeline; "fault" records (injected
    failures, glom_tpu/resilience) draw the same full-height line, so a
    chaos run shows each injection next to the recovery that answered it;
  * everything else (train_step, bench, anomaly, error, note, serve,
    recovery) becomes an instant event named by kind, args = the record.

Timestamps: records carry heterogeneous clocks (epoch `t_start` /
`wall_time_s`, run-relative `wall_time` / `t`). Each record uses its best
clock, and the whole trace is normalized to start at 0 — Perfetto needs
ORDER and DURATION, not absolute epochs. Records with no clock at all
(flight dumps from writerless sinks) fall back to their flight_seq /
line order at 1ms spacing, preserving sequence.

Pure stdlib, like the linter and the compare gate: this must run against a
crashed run's dumps in a jax-broken environment.

    python -m glom_tpu.telemetry perfetto FILE... [-o OUT.json]
"""

from __future__ import annotations

import json
import sys
from typing import Iterable, List, Optional

from glom_tpu.telemetry import schema

_PID = 1
# Track (tid) layout: real spans nest by depth on low tids; one-off
# instants and counters get stable named tracks via process_labels.
_TID_SPANS = 1
_TID_EVENTS = 90
_TID_ROLLUPS = 91


def _timestamp_s(rec: dict, fallback: float) -> float:
    """Best available clock for one record, in (heterogeneous) seconds.
    Epoch clocks dwarf run-relative ones; normalization happens per clock
    family in to_trace_events, so mixed streams still order sensibly."""
    for key in ("t_start", "wall_time_s", "wall_time", "t"):
        v = rec.get(key)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return float(v)
    return fallback


def to_trace_events(records: Iterable[dict]) -> List[dict]:
    """Chrome trace-event dicts (ts/dur in microseconds) from stamped
    telemetry records, chronologically normalized to start at ~0."""
    raw: List[dict] = []
    for i, rec in enumerate(records):
        kind = rec.get("kind", schema.infer_kind(rec))
        fallback = i * 1e-3  # 1ms spacing keeps clockless records ordered
        ts = _timestamp_s(rec, fallback)
        if kind == "span" and "t_start" in rec:
            raw.append(
                {
                    "name": rec.get("name", "span"),
                    "ph": "X",
                    "pid": _PID,
                    "tid": _TID_SPANS + int(rec.get("depth", 0)),
                    "ts": ts,
                    "dur": float(rec.get("dur_s", 0.0)) * 1e6,
                    "args": rec,
                }
            )
        elif kind == "span":
            # Rollup form: a counter sample of seconds spent in the phase
            # since the last drain (the per-phase load curve).
            raw.append(
                {
                    "name": f"phase:{rec.get('name', 'span')}",
                    "ph": "C",
                    "pid": _PID,
                    "tid": _TID_ROLLUPS,
                    "ts": ts,
                    "args": {"dur_s": float(rec.get("dur_s", 0.0))},
                }
            )
        elif kind == "watchdog":
            raw.append(
                {
                    "name": f"backend:{rec.get('backend_state', '?')}",
                    "ph": "i",
                    "s": "g",  # global scope: draw the full-height line
                    "pid": _PID,
                    "tid": _TID_EVENTS,
                    "ts": ts,
                    "args": rec,
                }
            )
        elif kind == "fault":
            # An injected fault is a full-height line like a watchdog
            # transition: a chaos run's timeline shows each injection as a
            # gash the recovery events then answer.
            raw.append(
                {
                    "name": f"fault:{rec.get('fault', '?')}",
                    "ph": "i",
                    "s": "g",
                    "pid": _PID,
                    "tid": _TID_EVENTS,
                    "ts": ts,
                    "args": rec,
                }
            )
        else:
            label = {
                "train_step": f"step {rec.get('step', '?')}",
                "bench": str(rec.get("metric", "bench")),
                "anomaly": f"anomaly: {rec.get('reason', '?')}",
                "error": f"error: {rec.get('error', '?')}",
                "serve": f"serve:{rec.get('event', '?')}",
                "recovery": f"recovery:{rec.get('action', '?')}",
                "barrier": f"barrier:{rec.get('phase', '?')}",
            }.get(kind, kind)
            raw.append(
                {
                    "name": label,
                    "ph": "i",
                    "s": "t",
                    "pid": _PID,
                    "tid": _TID_EVENTS,
                    "ts": ts,
                    "args": rec,
                }
            )
    if not raw:
        return []
    # Normalize per clock family: epoch-clock events (> ~1e9 s) and
    # run-relative ones each shift to their own zero, so a stream mixing
    # both still renders compactly instead of 50 years wide.
    epochs = [e["ts"] for e in raw if e["ts"] > 1e9]
    relatives = [e["ts"] for e in raw if e["ts"] <= 1e9]
    e0 = min(epochs) if epochs else 0.0
    r0 = min(relatives) if relatives else 0.0
    for e in raw:
        base = e0 if e["ts"] > 1e9 else r0
        e["ts"] = round((e["ts"] - base) * 1e6, 3)
        if "dur" in e:
            e["dur"] = round(e["dur"], 3)
    raw.sort(key=lambda e: e["ts"])
    return raw


def convert_lines(lines: Iterable[str]) -> dict:
    """One JSONL stream -> the Chrome/Perfetto trace object."""
    records = [rec for _, rec in schema.iter_json_lines(lines)]
    return {
        "traceEvents": to_trace_events(records),
        "displayTimeUnit": "ms",
        "metadata": {"source": "glom_tpu.telemetry.perfetto"},
    }


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m glom_tpu.telemetry perfetto",
        description="Convert span/flight/telemetry JSONL to a Perfetto-"
        "loadable JSON trace (open at ui.perfetto.dev)",
    )
    ap.add_argument("paths", nargs="+", help="JSONL logs / flight dumps")
    ap.add_argument(
        "-o", "--out", default=None,
        help="output path (default: <first input>.perfetto.json); all "
        "inputs merge into one trace",
    )
    args = ap.parse_args(argv)

    records = []
    for path in args.paths:
        with open(path) as fh:
            records.extend(rec for _, rec in schema.iter_json_lines(fh))
    if not records:
        print(f"no JSON records in {args.paths}", file=sys.stderr)
        return 1
    trace = {
        "traceEvents": to_trace_events(records),
        "displayTimeUnit": "ms",
        "metadata": {"source": "glom_tpu.telemetry.perfetto",
                     "inputs": args.paths},
    }
    out = args.out if args.out else args.paths[0] + ".perfetto.json"
    with open(out, "w") as fh:
        json.dump(trace, fh)
    print(f"{out}: {len(trace['traceEvents'])} events")
    return 0


if __name__ == "__main__":
    sys.exit(main())
