"""Perfetto export: span/flight JSONL -> a browsable timeline.

The flight recorder answers "what were the last N events"; spans answer
"where did host time go" — but both as JSONL you read with grep. Perfetto
(ui.perfetto.dev) reads the Chrome JSON trace-event format natively, and
every stamped record this framework writes already carries enough to place
it on a timeline, so the conversion is mechanical:

  * "span" records WITH a start time (t_start from span(writer=...)) become
    complete events (ph "X": name, ts, dur) on a per-depth track — the real
    nested timeline;
  * rollup "span" records (SpanAggregator drains carry only total dur_s /
    count) become counter samples (ph "C") of seconds-per-drain per phase —
    the per-phase load curve over the run;
  * watchdog records become instant events (ph "i") named by state — an
    outage is a visible gash in the timeline; "fault" records (injected
    failures, glom_tpu/resilience) draw the same full-height line, so a
    chaos run shows each injection next to the recovery that answered it;
  * everything else (train_step, bench, anomaly, error, note, serve,
    recovery) becomes an instant event named by kind, args = the record.

Timestamps: records carry heterogeneous clocks (epoch `t_start` /
`wall_time_s`, run-relative `wall_time` / `t`). Each record uses its best
clock, and the whole trace is normalized to start at 0 — Perfetto needs
ORDER and DURATION, not absolute epochs. Records with no clock at all
(flight dumps from writerless sinks) fall back to their flight_seq /
line order at 1ms spacing, preserving sequence.

Pure stdlib, like the linter and the compare gate: this must run against a
crashed run's dumps in a jax-broken environment.

    python -m glom_tpu.telemetry perfetto FILE... [-o OUT.json]
"""

from __future__ import annotations

import json
import sys
from typing import Iterable, List, Optional

from glom_tpu.telemetry import schema

_PID = 1
# Track (tid) layout: real spans nest by depth on low tids; one-off
# instants and counters get stable named tracks via process_labels;
# barrier events (pod coordination, resilience/coordinator.py) get one
# track PER HOST so a round's propose->commit->saved->complete chain
# reads as flow arrows crossing the hosts instead of a pile of instants.
_TID_SPANS = 1
_TID_EVENTS = 90
_TID_ROLLUPS = 91
# Capacity-observatory tracks (ISSUE 13): per-(site, axis) collective
# wall-time counters, per-engine headroom counters, and the dispatch
# phase split rendered as NESTED slices (one parent slice per dispatch,
# its five phases as children) so one trace reads
# queue->pack->h2d->device->resolve end to end.
_TID_COLLECTIVES = 92
_TID_CAPACITY = 93
_TID_FLEET = 94
_TID_DISPATCH = 95
_TID_PHASES = 96
# Workload-observatory tracks (ISSUE 17): the offered arrival rate
# (a trailing-window counter over "workload" records and live "admit"
# events) and the scored forecast series render as counters beside
# fleet:n_engines — load, the fleet's answer, and the forecast that
# should have anticipated it, on adjacent tracks.
_TID_FORECAST = 97
_TID_WORKLOAD = 98
_TID_BARRIER_BASE = 100
# Decision-observatory tracks (ISSUE 18): one track PER FLEET of
# "decision" instants (schema v10, serve/elastic.py), flow-arrowed to
# the scale/spare events each decision_id actuated — a decision reads
# as an arrow from the instant the policy believed its evidence to the
# spawn/drain/promotion that answered it, beside fleet:n_engines and
# the arrival-rate tracks. Allocated past the barrier range so a pod
# chaos run's host tracks never collide with the fleet tracks.
_TID_DECISION_BASE = 1000
_ARRIVAL_WINDOW_S = 1.0  # the arrival-rate counter's trailing window

# The elastic-serving transition vocabulary (serve/elastic.SCALE_EVENTS —
# mirrored literally: this module stays pure-stdlib importable and the
# serve package pulls jax).
_SCALE_EVENTS = (
    "scale_out_decision",
    "scale_out",
    "admission_open",
    "spawn_rollback",
    "scale_in_decision",
    "drain_begin",
    "drain_flush",
    "drain_migrate",
    "drain_release",
    "spare_spawn",
    "spare_promote",
    "spare_demote",
)


CLOCK_KEYS = ("t_start", "wall_time_s", "wall_time", "t")
# Above this, a clock value is an epoch (time.time()) reading; below, a
# run-relative one. One definition — the pod aggregator
# (telemetry/aggregate.py) reuses both constants for its cross-host
# clock-family reconciliation.
EPOCH_CUTOFF_S = 1e9


def timestamp_s(rec: dict, fallback: float) -> float:
    """Best available clock for one record, in (heterogeneous) seconds.
    Epoch clocks dwarf run-relative ones; normalization happens per clock
    family in to_trace_events, so mixed streams still order sensibly."""
    for key in CLOCK_KEYS:
        v = rec.get(key)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return float(v)
    return fallback


_timestamp_s = timestamp_s  # original private name, kept for callers


# One vocabulary for "which traces does this record belong to": the flow
# links must never diverge from the trees the trace CLI reconstructs.
from glom_tpu.telemetry.tracectx import _trace_ids_of  # noqa: E402


def to_trace_events(records: Iterable[dict]) -> List[dict]:
    """Chrome trace-event dicts (ts/dur in microseconds) from stamped
    telemetry records, chronologically normalized to start at ~0.

    Two flow-event families link related instants with arrows:

      * request traces — serve records carrying v6 trace context chain
        per trace_id (ph "s" at the first sighting, "t" per hop, "f" at
        the resolve/response leaf), so selecting one dispatch in the UI
        lights up the whole request across engines and hops;
      * barrier rounds — "barrier" records land on per-host tracks
        (thread_name metadata names them) and chain per round id, so a
        pod save barrier's propose->commit->saved->complete reads as
        arrows crossing the host tracks.
    """
    raw: List[dict] = []
    flow_seen: dict = {}  # barrier/decision flow id -> "open"
    trace_flows: dict = {}  # trace_id -> [(ts, is_leaf), ...]
    barrier_tracks: dict = {}  # tid -> track label
    decision_tracks: dict = {}  # fleet -> tid
    arrival_window: List[float] = []  # trailing arrival ts (seconds)
    class_arrivals: dict = {}  # slo_class -> trailing arrival ts (v11)

    def decision_flow(rec: dict, ts: float, tid: int) -> None:
        # Chain every record carrying a decision_id on one flow id per
        # (fleet, decision): "s" at the first sighting (the decision
        # instant, when the stream carries it), "t" per actuation — the
        # barrier-flow pattern, since the chain's length isn't known
        # until the stream ends.
        did = rec.get("decision_id")
        if not isinstance(did, int) or isinstance(did, bool):
            return
        fleet = rec.get("fleet")
        fleet = fleet if isinstance(fleet, str) and fleet else "fleet0"
        fid = f"decision:{fleet}:{did}"
        raw.append(
            {
                "name": fid,
                "cat": "decision",
                "ph": "s" if fid not in flow_seen else "t",
                "id": fid,
                "pid": _PID,
                "tid": tid,
                "ts": ts,
            }
        )
        flow_seen[fid] = "open"
    for i, rec in enumerate(records):
        kind = rec.get("kind", schema.infer_kind(rec))
        fallback = i * 1e-3  # 1ms spacing keeps clockless records ordered
        ts = _timestamp_s(rec, fallback)
        if kind == "span" and "t_start" in rec:
            raw.append(
                {
                    "name": rec.get("name", "span"),
                    "ph": "X",
                    "pid": _PID,
                    "tid": _TID_SPANS + int(rec.get("depth", 0)),
                    "ts": ts,
                    "dur": float(rec.get("dur_s", 0.0)) * 1e6,
                    "args": rec,
                }
            )
        elif kind == "span":
            # Rollup form: a counter sample of seconds spent in the phase
            # since the last drain (the per-phase load curve).
            raw.append(
                {
                    "name": f"phase:{rec.get('name', 'span')}",
                    "ph": "C",
                    "pid": _PID,
                    "tid": _TID_ROLLUPS,
                    "ts": ts,
                    "args": {"dur_s": float(rec.get("dur_s", 0.0))},
                }
            )
        elif kind == "watchdog":
            raw.append(
                {
                    "name": f"backend:{rec.get('backend_state', '?')}",
                    "ph": "i",
                    "s": "g",  # global scope: draw the full-height line
                    "pid": _PID,
                    "tid": _TID_EVENTS,
                    "ts": ts,
                    "args": rec,
                }
            )
        elif kind == "fault":
            # An injected fault is a full-height line like a watchdog
            # transition: a chaos run's timeline shows each injection as a
            # gash the recovery events then answer.
            raw.append(
                {
                    "name": f"fault:{rec.get('fault', '?')}",
                    "ph": "i",
                    "s": "g",
                    "pid": _PID,
                    "tid": _TID_EVENTS,
                    "ts": ts,
                    "args": rec,
                }
            )
        elif kind == "barrier":
            # One track per host: a pod round's phases land side by side
            # instead of interleaved on the shared events track, and the
            # per-round flow arrows below make the chain's ORDER visible.
            host = rec.get("host")
            if isinstance(host, int) and not isinstance(host, bool):
                tid = _TID_BARRIER_BASE + host
                barrier_tracks[tid] = f"barrier host {host}"
            else:
                tid = _TID_EVENTS
            raw.append(
                {
                    "name": f"barrier:{rec.get('phase', '?')}",
                    "ph": "i",
                    "s": "t",
                    "pid": _PID,
                    "tid": tid,
                    "ts": ts,
                    "args": rec,
                }
            )
            rnd = rec.get("round")
            if isinstance(rnd, str):
                fid = f"barrier:{rnd}"
                raw.append(
                    {
                        "name": fid,
                        "cat": "barrier",
                        "ph": "s" if fid not in flow_seen else "t",
                        "id": fid,
                        "pid": _PID,
                        "tid": tid,
                        "ts": ts,
                    }
                )
                flow_seen[fid] = "open"
        elif kind == "collective_time":
            # One counter track per (site, axis): the per-collective
            # wall-time trend over the run — a congested link shows as
            # one site's counter climbing while its siblings hold.
            axis = rec.get("axis")
            name = f"collective:{rec.get('site', '?')}" + (
                f"@{axis}" if isinstance(axis, str) else ""
            )
            raw.append(
                {
                    "name": name,
                    "ph": "C",
                    "pid": _PID,
                    "tid": _TID_COLLECTIVES,
                    "ts": ts,
                    "args": {"wall_ms": float(rec.get("wall_ms", 0.0))},
                }
            )
        elif kind == "capacity":
            raw.append(
                {
                    "name": f"headroom:{rec.get('engine', '?')}",
                    "ph": "C",
                    "pid": _PID,
                    "tid": _TID_CAPACITY,
                    "ts": ts,
                    "args": {
                        "headroom": float(rec.get("headroom", 0.0))
                    },
                }
            )
        elif kind == "serve" and rec.get("event") in _SCALE_EVENTS:
            # Elastic fleet transitions (schema v8, serve/elastic.py):
            # each decision/transition is a full-height GLOBAL instant —
            # a scale-out reads as a line the latency recovery then
            # answers — and any record carrying n_engines samples the
            # fleet-size counter track (capacity following load, drawn).
            raw.append(
                {
                    "name": f"elastic:{rec.get('event')}",
                    "ph": "i",
                    "s": "g",
                    "pid": _PID,
                    "tid": _TID_EVENTS,
                    "ts": ts,
                    "args": rec,
                }
            )
            n = rec.get("n_engines")
            if isinstance(n, (int, float)) and not isinstance(n, bool):
                raw.append(
                    {
                        "name": "fleet:n_engines",
                        "ph": "C",
                        "pid": _PID,
                        "tid": _TID_FLEET,
                        "ts": ts,
                        "args": {"n_engines": float(n)},
                    }
                )
            decision_flow(rec, ts, _TID_EVENTS)
        elif kind == "decision":
            # One instants track PER FLEET (schema v10): the decision,
            # with its full evidence bundle in args, starts the flow its
            # actuation events extend.
            fleet = rec.get("fleet")
            fleet = (
                fleet if isinstance(fleet, str) and fleet else "fleet0"
            )
            tid = decision_tracks.setdefault(
                fleet, _TID_DECISION_BASE + len(decision_tracks)
            )
            raw.append(
                {
                    "name": f"decision:{rec.get('action', '?')}",
                    "ph": "i",
                    "s": "t",
                    "pid": _PID,
                    "tid": tid,
                    "ts": ts,
                    "args": rec,
                }
            )
            decision_flow(rec, ts, tid)
        elif kind == "forecast":
            # Forecast evidence (schema v9, telemetry/forecast.py): each
            # window samples a counter track per metric beside the fleet
            # and arrival tracks — predicted vs observed load, and the
            # scored error once the horizon matures. Null errors (the
            # window not yet matured) are honest gaps, never zeros.
            args = {}
            for key in (
                "predicted",
                "observed_rate_rps",
                "realized",
                "forecast_abs_err",
                "lead_time_ms",
            ):
                val = rec.get(key)
                if isinstance(val, (int, float)) and not isinstance(
                    val, bool
                ):
                    args[key] = float(val)
            if args:
                raw.append(
                    {
                        "name": f"forecast:{rec.get('metric', '?')}",
                        "ph": "C",
                        "pid": _PID,
                        "tid": _TID_FORECAST,
                        "ts": ts,
                        "args": args,
                    }
                )
        elif kind == "workload" or (
            kind == "serve" and rec.get("event") == "admit"
        ):
            # Offered load (schema v9, serve/workload.py): every workload
            # artifact row — and every live "admit" event — advances a
            # trailing-window arrival-rate counter. Per-arrival instants
            # would drown the events track at serving volume; the rate
            # curve is the readable form.
            arrival_window.append(ts)
            cutoff = ts - _ARRIVAL_WINDOW_S
            while arrival_window and arrival_window[0] < cutoff:
                arrival_window.pop(0)
            raw.append(
                {
                    "name": "workload:arrival_rps",
                    "ph": "C",
                    "pid": _PID,
                    "tid": _TID_WORKLOAD,
                    "ts": ts,
                    "args": {
                        "arrival_rps": round(
                            len(arrival_window) / _ARRIVAL_WINDOW_S, 3
                        )
                    },
                }
            )
            # Per-SLO-class arrival rate (schema v11, serve/qos.py): a
            # classed record ALSO advances its tenant's own counter on
            # the same track — the flash-crowd mix reads as stacked
            # curves. Classless streams (slo_class null/absent) never
            # emit these, keeping their traces byte-identical.
            cls = rec.get("slo_class")
            if isinstance(cls, str) and cls:
                win = class_arrivals.setdefault(cls, [])
                win.append(ts)
                while win and win[0] < cutoff:
                    win.pop(0)
                raw.append(
                    {
                        "name": f"workload:arrival_rps[{cls}]",
                        "ph": "C",
                        "pid": _PID,
                        "tid": _TID_WORKLOAD,
                        "ts": ts,
                        "args": {
                            "arrival_rps": round(
                                len(win) / _ARRIVAL_WINDOW_S, 3
                            )
                        },
                    }
                )
        else:
            label = {
                "train_step": f"step {rec.get('step', '?')}",
                "bench": str(rec.get("metric", "bench")),
                "anomaly": f"anomaly: {rec.get('reason', '?')}",
                "error": f"error: {rec.get('error', '?')}",
                "serve": f"serve:{rec.get('event', '?')}",
                "recovery": f"recovery:{rec.get('action', '?')}",
            }.get(kind, kind)
            if (
                kind == "serve"
                and rec.get("event") == "dispatch"
                and isinstance(rec.get("latency_ms"), (int, float))
                and isinstance(rec.get("device_ms"), (int, float))
            ):
                # The dispatch phase split as NESTED slices: the record's
                # clock reads at stamp time (after the dispatch), so the
                # parent slice starts latency_ms earlier and the five
                # phases lay out consecutively under it — one trace shows
                # where each dispatch's wall went, next to the request
                # flow arrows.
                lat_s = float(rec["latency_ms"]) / 1e3
                t_start = ts - lat_s
                raw.append(
                    {
                        "name": f"dispatch:{rec.get('engine', '?')}",
                        "ph": "X",
                        "pid": _PID,
                        "tid": _TID_DISPATCH,
                        "ts": t_start,
                        "dur": lat_s * 1e6,
                        "args": rec,
                    }
                )
                cursor = t_start
                for phase in (
                    "queue_wait_ms", "pack_ms", "h2d_ms", "device_ms",
                    "resolve_ms",
                ):
                    v = rec.get(phase)
                    if not isinstance(v, (int, float)):
                        continue
                    raw.append(
                        {
                            "name": phase[: -len("_ms")],
                            "ph": "X",
                            "pid": _PID,
                            "tid": _TID_PHASES,
                            "ts": cursor,
                            "dur": float(v) * 1e3,  # ms -> us
                            "args": {phase: v},
                        }
                    )
                    cursor += float(v) / 1e3
            raw.append(
                {
                    "name": label,
                    "ph": "i",
                    "s": "t",
                    "pid": _PID,
                    "tid": _TID_EVENTS,
                    "ts": ts,
                    "args": rec,
                }
            )
            if kind in ("serve", "recovery", "span"):
                # Collect this record into each request trace it belongs
                # to (schema v6 trace context); phases are assigned after
                # the walk, in TIMESTAMP order — the batcher emits a
                # hop's resolve leaf BEFORE the hop's dispatch record, so
                # assigning phases in stream order would start the flow
                # at the leaf (never closing it) or close it early and
                # drop the final hop.
                leaf = rec.get("event") in ("resolve", "response")
                for trace_id in _trace_ids_of(rec):
                    trace_flows.setdefault(trace_id, []).append((ts, leaf))
    # Flow-link each trace's records in CAUSAL order — hop records
    # (dispatch/continuation/...) by timestamp, then the leaves
    # (resolve/response): one "s" at the first hop, "t" per further hop,
    # one "f" at the first leaf. Neither stream order nor pure ts order
    # is causal here: the batcher stamps a hop's resolve leaf BEFORE the
    # hop's own dispatch record (and the dispatch record's clock reads
    # LATER), so either walk would start the flow at the leaf, or close
    # it early and skip the final hop. Records after the finish are not
    # flow-linked (a second leaf, e.g. the CLI response after the
    # batcher's resolve, would close an already-terminated flow, which
    # the importer drops); flow ts is clamped monotone so the closing
    # arrow never points backward across the ms-scale stamp skew.
    for trace_id, cands in trace_flows.items():
        cands.sort(key=lambda c: (c[1], c[0]))
        prev_ts = None
        for i, (cts, leaf) in enumerate(cands):
            ph = "s" if i == 0 else ("f" if leaf else "t")
            if prev_ts is not None:
                cts = max(cts, prev_ts)
            prev_ts = cts
            raw.append(
                {
                    "name": f"trace:{trace_id[:8]}",
                    "cat": "trace",
                    "ph": ph,
                    **({"bp": "e"} if ph == "f" else {}),
                    "id": f"trace:{trace_id}",
                    "pid": _PID,
                    "tid": _TID_EVENTS,
                    "ts": cts,
                }
            )
            if ph == "f":
                break
    if not raw:
        return []
    # Normalize per clock family: epoch-clock events (> EPOCH_CUTOFF_S)
    # and run-relative ones each shift to their own zero, so a stream
    # mixing both still renders compactly instead of 50 years wide. Flow
    # events copied their anchor instant's ts, so they stay in family.
    epochs = [e["ts"] for e in raw if e["ts"] > EPOCH_CUTOFF_S]
    relatives = [e["ts"] for e in raw if e["ts"] <= EPOCH_CUTOFF_S]
    e0 = min(epochs) if epochs else 0.0
    r0 = min(relatives) if relatives else 0.0
    for e in raw:
        base = e0 if e["ts"] > EPOCH_CUTOFF_S else r0
        e["ts"] = round((e["ts"] - base) * 1e6, 3)
        if "dur" in e:
            e["dur"] = round(e["dur"], 3)
    raw.sort(key=lambda e: e["ts"])
    # Name the workload-observatory tracks when they carry samples.
    named_tids = {e["tid"] for e in raw}
    for tid, label in (
        (_TID_FORECAST, "forecast"),
        (_TID_WORKLOAD, "workload arrivals"),
    ):
        if tid in named_tids:
            raw.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": _PID,
                    "tid": tid,
                    "args": {"name": label},
                }
            )
    # Name the per-fleet decision tracks (metadata events; ts-less).
    for fleet, tid in sorted(decision_tracks.items()):
        raw.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": _PID,
                "tid": tid,
                "args": {"name": f"decisions {fleet}"},
            }
        )
    # Name the per-host barrier tracks (metadata events; ts-less).
    for tid, label in sorted(barrier_tracks.items()):
        raw.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": _PID,
                "tid": tid,
                "args": {"name": label},
            }
        )
    return raw


def convert_lines(lines: Iterable[str]) -> dict:
    """One JSONL stream -> the Chrome/Perfetto trace object."""
    records = [rec for _, rec in schema.iter_json_lines(lines)]
    return {
        "traceEvents": to_trace_events(records),
        "displayTimeUnit": "ms",
        "metadata": {"source": "glom_tpu.telemetry.perfetto"},
    }


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m glom_tpu.telemetry perfetto",
        description="Convert span/flight/telemetry JSONL to a Perfetto-"
        "loadable JSON trace (open at ui.perfetto.dev)",
    )
    ap.add_argument("paths", nargs="+", help="JSONL logs / flight dumps")
    ap.add_argument(
        "-o", "--out", default=None,
        help="output path (default: <first input>.perfetto.json); all "
        "inputs merge into one trace",
    )
    args = ap.parse_args(argv)

    records = []
    for path in args.paths:
        with open(path) as fh:
            records.extend(rec for _, rec in schema.iter_json_lines(fh))
    if not records:
        print(f"no JSON records in {args.paths}", file=sys.stderr)
        return 1
    trace = {
        "traceEvents": to_trace_events(records),
        "displayTimeUnit": "ms",
        "metadata": {"source": "glom_tpu.telemetry.perfetto",
                     "inputs": args.paths},
    }
    out = args.out if args.out else args.paths[0] + ".perfetto.json"
    with open(out, "w") as fh:
        json.dump(trace, fh)
    print(f"{out}: {len(trace['traceEvents'])} events")
    return 0


if __name__ == "__main__":
    sys.exit(main())
