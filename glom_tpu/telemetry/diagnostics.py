"""In-graph training diagnostics: scalars computed INSIDE the jitted step.

The design constraint is cost: telemetry that adds a host round-trip or a
separate compiled sweep per step gets turned off the moment throughput
matters, and then the next outage is blind again (rounds 4-5). Everything
here is fused into the step the trainer already runs:

  * scalar taps — grad/update/param global norms: three tree-wide
    reductions XLA fuses with the update math (the grad-norm one is the
    same sweep the logging step already paid);
  * the NaN/Inf guard — ONE extra scalar op: a non-finite gradient anywhere
    poisons the grad norm, so `isfinite(loss + grad_norm)` covers the whole
    tree without a second sweep. Policy "skip" drops the update in-graph
    (jnp.where keeps the old params/opt state — the step counter still
    advances so schedules/logs stay aligned); "warn" applies it and flags
    the record. fit_loop turns the flag into a structured anomaly event;
  * per-level consensus-agreement (level "full") — mean cosine between each
    patch vector and its image's mean vector per level, from the forward's
    final state: the "islands of agreement" formation signal (GLOM §9) as
    one [L]-vector per step.

Gating is `TrainConfig.telemetry_level`, resolved ONCE by
resolve_telemetry_level (the same single-source discipline as
resolve_zero_stage) and stamped into every record.
"""

from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp

TELEMETRY_LEVELS = ("off", "scalars", "full")
NONFINITE_POLICIES = ("skip", "warn")


def resolve_telemetry_level(tcfg, *, supports_full: bool = True) -> str:
    """Effective telemetry level for a trainer path — THE single resolution
    source (both trainers call this once and stamp the output, so a record
    can never claim diagnostics that didn't run). supports_full=False (the
    manual shard_map path: the per-shard loss body has no aux channel for
    the final state) degrades "full" to "scalars" loudly."""
    level = tcfg.telemetry_level
    if level not in TELEMETRY_LEVELS:
        raise ValueError(
            f"telemetry_level={level!r}: one of {TELEMETRY_LEVELS}"
        )
    if tcfg.nonfinite_policy not in NONFINITE_POLICIES:
        raise ValueError(
            f"nonfinite_policy={tcfg.nonfinite_policy!r}: one of "
            f"{NONFINITE_POLICIES}"
        )
    if level == "full" and not supports_full:
        warnings.warn(
            "telemetry_level='full' is unavailable on the manual shard_map "
            "path (no aux channel through the per-shard loss body); "
            "running with 'scalars' — the stamped level is the resolved one",
            stacklevel=3,
        )
        return "scalars"
    return level


def nonfinite_flag(loss: jnp.ndarray, grad_norm: jnp.ndarray) -> jnp.ndarray:
    """True when this step's loss or ANY gradient element is non-finite.
    The grad norm is the whole-tree witness: one NaN/Inf anywhere makes the
    sum of squares non-finite, so no per-leaf isfinite sweep is needed."""
    return jnp.logical_not(
        jnp.isfinite(loss.astype(jnp.float32) + grad_norm.astype(jnp.float32))
    )


def guard_update(nonfinite: jnp.ndarray, new_tree, old_tree):
    """Skip-step policy, in-graph: where the step was non-finite, keep the
    old value on every leaf (params AND optimizer state — a poisoned Adam
    moment would re-emit the NaN on the next healthy step)."""
    return jax.tree_util.tree_map(
        lambda new, old: jnp.where(nonfinite, old, new), new_tree, old_tree
    )


def level_agreement(final: jnp.ndarray) -> jnp.ndarray:
    """Per-level consensus-agreement from a final state [b, n, L, d]:
    mean over (b, n) of the cosine between each patch's level vector and
    that image's mean vector at the same level. -> [L] float32, ~1.0 when
    a level has collapsed to one island, ~0 when patches disagree."""
    x = final.astype(jnp.float32)
    eps = 1e-8
    xhat = x / (jnp.linalg.norm(x, axis=-1, keepdims=True) + eps)
    mean = jnp.mean(xhat, axis=1, keepdims=True)  # [b, 1, L, d]
    mhat = mean / (jnp.linalg.norm(mean, axis=-1, keepdims=True) + eps)
    return jnp.mean(jnp.sum(xhat * mhat, axis=-1), axis=(0, 1))  # [L]


def quantization_error(grads, dq_grads) -> jnp.ndarray:
    """Relative L2 error of one quantize-dequantize wire hop over the whole
    gradient tree — the in-graph probe that keeps the EQuARX emulation's
    accuracy cost on the record (PAPERS.md: quantized-collective rollouts
    need per-step error telemetry before they can be trusted)."""
    err_sq = sum(
        jnp.sum(jnp.square(g.astype(jnp.float32) - q.astype(jnp.float32)))
        for g, q in zip(
            jax.tree_util.tree_leaves(grads),
            jax.tree_util.tree_leaves(dq_grads),
        )
    )
    ref_sq = sum(
        jnp.sum(jnp.square(g.astype(jnp.float32)))
        for g in jax.tree_util.tree_leaves(grads)
    )
    return jnp.sqrt(err_sq) / (jnp.sqrt(ref_sq) + 1e-12)


def scalar_taps(
    *,
    loss: jnp.ndarray,
    grad_norm: jnp.ndarray,
    updates,
    params,
) -> dict:
    """The "scalars" bundle: update/param norms + the non-finite flag
    (grad_norm rides in from the caller — it is shared with the metrics
    the step already computes)."""
    import optax

    return {
        "grad_norm": grad_norm,
        "update_norm": optax.global_norm(updates),
        "param_norm": optax.global_norm(params),
        "nonfinite": nonfinite_flag(loss, grad_norm),
    }


def split_level_agreement(metrics: dict) -> dict:
    """Host-side: explode a metrics dict's [L] `level_agreement` vector
    into per-level scalar keys (consensus_agreement_l0..l{L-1}) so every
    sink — JSONL, TensorBoard, the driver's tail parse — sees flat
    scalars. No-op when the key is absent."""
    if "level_agreement" not in metrics:
        return metrics
    metrics = dict(metrics)
    vec = metrics.pop("level_agreement")
    import numpy as np

    vec = np.asarray(vec)
    for i, v in enumerate(vec.tolist()):
        metrics[f"consensus_agreement_l{i}"] = v
    return metrics
