"""Bench-trajectory regression gate: `python -m glom_tpu.telemetry compare`.

Rounds 4-5 polluted the bench trajectory with `value: 0.0` UNMEASURED rows
— any naive base-vs-new diff read them as a 100% regression (or, worse, a
recovery *from* zero as an infinite speedup). This gate compares two bench
logs the way the trajectory should be read:

  * records match by their full `metric` label (the label names the regime
    — config, chip, path — so cross-regime rows never compare);
  * repeated measurements of one metric collapse to the BEST value on each
    side (min-of-noise on both sides, the same convention the benches'
    min-over-repeats timing uses), so run-to-run jitter cannot
    manufacture a regression by itself;
  * direction comes from the unit: rates ("/s", "x") regress DOWN, costs
    ("ms", "percent", "bytes", seconds) regress UP;
  * UNMEASURED rows — kind "error", `value: null`, or a non-numeric value
    — are MISSING, never zero: reported, excluded from the verdict;
  * the verdict is noise-aware: only a relative change beyond --threshold
    (default 5%, ~2x the chained-timing error bound in utils/timing.py)
    in the regressing direction fails the gate.

Exit code: 1 when any regression beyond threshold survives, else 0 —
run_hw_queue.sh wires it after the bench steps so a slow row cannot land
silently. Pure stdlib, like the linter.
"""

from __future__ import annotations

import json
import sys
from typing import Dict, List, Optional, Tuple

from glom_tpu.telemetry import schema

# Unit substrings that mark a LOWER-is-better (cost) metric; anything else
# — including the north-star "column-iters/s/chip" and speedup ratios "x"
# — is a rate, where lower is the regression. "iters" covers the serving
# early-exit rows ("iters/request": column updates spent per request); the
# rate check runs FIRST, so "column-iters/s/chip" still reads as a rate.
_COST_UNIT_TOKENS = ("ms", "percent", "bytes", "second", "iters")
# Failure-ish count names regress UP (more retries/failures/sheds is
# worse); everything else counted (dispatches, rejoins, alive) is a
# rate, where LOWER is the regression — a dead engine's dispatches
# dropping to zero must gate, not vanish.
_COST_METRIC_TOKENS = (
    "overhead", "time", "latency", "retries", "failures", "gave_up",
    "fast_failed", "shed", "evictions", "rejects", "expirations",
    # Ladder churn regresses UP too: restores track degrades 1:1, so a
    # run that never degraded improves on BOTH, and one that bounced
    # more regresses on both — rate-classifying restores would gate the
    # calm run for restoring less.
    "degrades", "restores", "deaths", "failovers",
    # Pad waste is a COST (ISSUE 11): a serve change that pads more —
    # higher pad_fraction_mean, more pad bytes, or warm levels0 bytes
    # creeping back onto the host->device path — regresses UP.
    "pad", "h2d",
    # Delta-cache depth is a COST (ISSUE 12): longer chains mean more
    # pages per stream and deeper reconstruction; compactions deferred
    # under pins are pressure evidence. bytes_per_stream rides the
    # "bytes" unit token.
    "chain", "compact_deferred",
    # Capacity-observatory pressure rows (ISSUE 13): occupancy creeping
    # up regresses even when latency holds (headroom is the matching
    # BENEFIT token below; collective_time.* wall_ms and the
    # serve_latency.* phase rows ride the "ms" unit token).
    "utilization", "fill", "wait",
    # Elastic-serving damage rows (ISSUE 15): a drain that INVALIDATES
    # sessions (no sibling page budget) lost warmth a migration would
    # have kept; spawn rollbacks are failed scale-outs. spawn_ms and
    # migrated_bytes ride the "ms"/"bytes" unit tokens.
    "invalidated", "spawn_failures",
    # Banded-consensus + pool-aliasing rows (ISSUE 16): the duplicated
    # k/v working set regresses UP (peak_window_bytes rides the "bytes"
    # unit token too — the name token keeps intent explicit), and
    # alias fallbacks are pinned writes that fell back to full-pool
    # copy-on-write — more of them is more bytes moved.
    # serve_ragged_max_signature_pages has NEITHER token: it rate-
    # classifies, so the admission ceiling SHRINKING is the regression.
    "peak_window", "alias_fallback",
    # Workload-observatory rows (ISSUE 17): forecast error growing is a
    # worse forecast, and a longer spawn lead time means the
    # anticipatory policy must act earlier — both regress UP
    # (lead_time_ms also rides the "ms" unit token; the name token
    # covers the flattened forecast.*.lead_time rows).
    "forecast_abs_err", "lead_time",
    # Decision-observatory rows (ISSUE 18): REGRET is failure evidence
    # inside a decision's cover window, decisions_late counts scale-outs
    # taken only after the SLO already broke, and spawn_lead_violations
    # counts spawns slower than the lead their decision believed — every
    # one regresses UP ("violation" also covers the flattened
    # serve_elastic.spawn_lead_violations row).
    "regret", "decisions_late", "violation",
    # Per-class QoS rows (ISSUE 19): a tenant's failed/degraded/shed
    # counts regress UP wherever they surface ("shed" already rides the
    # list; "failed" covers serve_class.*.n_failed, "degraded" the
    # per-class degrade counters — a change that degrades premium more
    # is a regression even when totals hold).
    "failed", "degraded",
)
# Metric-name tokens that mark a HIGHER-is-better row regardless of the
# cost heuristics: headroom is capacity LEFT — a serving change that
# erodes it regresses DOWN, exactly opposite to the occupancy costs.
# served_fraction is the starvation-floor contract made a gate: the
# batch tenant's served share dropping IS the regression (ISSUE 19).
_BENEFIT_METRIC_TOKENS = ("headroom", "served_fraction")


def lower_is_better(metric: str, unit: str) -> bool:
    unit = unit.lower()
    if any(tok in metric.lower() for tok in _BENEFIT_METRIC_TOKENS):
        return False
    if "/s" in unit or unit == "x":
        return False
    if any(tok in unit for tok in _COST_UNIT_TOKENS) or unit == "s":
        return True
    return any(tok in metric.lower() for tok in _COST_METRIC_TOKENS)


def _is_measured(rec: dict) -> bool:
    v = rec.get("value")
    return (
        rec.get("kind") != "error"
        and isinstance(v, (int, float))
        and not isinstance(v, bool)
    )


def flatten_engine_metrics(rec: dict) -> List[dict]:
    """Synthetic bench-shaped rows from one serve summary's per-engine
    nest, so multi-engine rollups GATE instead of vanishing: the summary
    nests dispatches / rejoins / ladder / retry counters under
    `engines[name]` (flat on a single-engine summary — those fields ride
    the record itself and were never per-engine), and the compare gate
    only ingests `metric` rows. Numeric leaves (bools as 0/1 — an engine
    going alive=1 -> 0 IS the regression kill-serve hunts) flatten to
    `serve_engine.<name>.<dotted.path> (<config>)`, unit "count"; the
    direction comes from _COST_METRIC_TOKENS (retries/failures regress
    UP, dispatches/alive regress DOWN)."""
    engines = rec.get("engines")
    if not isinstance(engines, dict):
        return []
    cfg = rec.get("config")
    suffix = f" ({cfg})" if isinstance(cfg, str) and cfg else ""
    rows: List[dict] = []

    def walk(prefix: str, obj: dict, out: Dict[str, float]) -> None:
        for k, v in obj.items():
            if isinstance(v, dict):
                walk(f"{prefix}{k}.", v, out)
            elif isinstance(v, (int, float)):
                # bool is an int subclass: alive flattens as 0/1.
                out[f"{prefix}{k}"] = float(v)

    for name in sorted(engines):
        st = engines[name]
        if not isinstance(st, dict):
            continue
        flat: Dict[str, float] = {}
        walk("", st, flat)
        for key, value in sorted(flat.items()):
            rows.append(
                {
                    "metric": f"serve_engine.{name}.{key}{suffix}",
                    "value": value,
                    "unit": "count",
                    "kind": "bench",
                }
            )
    # Pad-tax rollup rows (ISSUE 11): the summary's aggregated pad waste
    # and warm-path upload bytes gate as COSTS — a serving change that
    # re-grows the pad fraction or puts levels0 back on the PCIe path
    # regresses, whatever it did to latency. Units make the direction
    # ("fraction"/"bytes" carry the pad/h2d cost tokens in the metric).
    for key, unit in (
        ("pad_fraction_mean", "fraction"),
        ("pad_bytes_wasted", "bytes"),
        ("levels0_h2d_bytes", "bytes"),
    ):
        v = rec.get(key)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            rows.append(
                {
                    "metric": f"serve_pad.{key}{suffix}",
                    "value": float(v),
                    "unit": unit,
                    "kind": "bench",
                }
            )
    # The cache-delta nest (ISSUE 12): bytes_per_stream and chain length
    # gate as COSTS — a storage change that re-grows per-stream pages or
    # deepens chains regresses even when latency holds. Counters
    # (n_delta_writes, n_base_shares, ...) flatten too; direction comes
    # from _COST_METRIC_TOKENS ("chain"/"compact_deferred" up, shares as
    # a rate down).
    delta = (rec.get("column_cache") or {}).get("delta")
    if isinstance(delta, dict):
        for key in sorted(delta):
            v = delta[key]
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                continue
            unit = "bytes" if "bytes" in key else "count"
            rows.append(
                {
                    "metric": f"serve_cache_delta.{key}{suffix}",
                    "value": float(v),
                    "unit": unit,
                    "kind": "bench",
                }
            )
    # The latency decomposition rollup (ISSUE 13): the summary's mean
    # per-dispatch phase split gates as serve_latency.* COSTS ("ms" unit)
    # — a change that moves time into queue_wait or h2d regresses even
    # when total latency holds inside noise.
    phases = rec.get("latency_phases")
    if isinstance(phases, dict):
        for key in sorted(phases):
            v = phases[key]
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                rows.append(
                    {
                        "metric": f"serve_latency.{key}{suffix}",
                        "value": float(v),
                        "unit": "ms",
                        "kind": "bench",
                    }
                )
    # The capacity nest (ISSUE 13): headroom gates as a BENEFIT (the
    # _BENEFIT_METRIC_TOKENS row — less capacity left is the
    # regression), utilization as a cost, service rate by its "/s" unit.
    capacity = rec.get("capacity")
    if isinstance(capacity, dict):
        for name in sorted(capacity):
            st = capacity[name]
            if not isinstance(st, dict):
                continue
            for key, unit in (
                ("headroom", "fraction"),
                ("utilization", "fraction"),
                ("service_rate_rps", "req/s"),
            ):
                v = st.get(key)
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    rows.append(
                        {
                            "metric": (
                                f"serve_capacity.{name}.{key}{suffix}"
                            ),
                            "value": float(v),
                            "unit": unit,
                            "kind": "bench",
                        }
                    )
    # The elastic nest (ISSUE 15): the autoscaler's rollup flattens as
    # serve_elastic.* rows — spawn latency ("ms") and migration bytes
    # ("bytes") gate as COSTS by unit; spawn failures and invalidated
    # sessions by the failure-ish metric tokens; scale counts ride as
    # plain counts (how often the loop acts is workload, not quality).
    elastic = rec.get("elastic")
    if isinstance(elastic, dict):
        for key in sorted(elastic):
            v = elastic[key]
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                continue  # the timeline list is perfetto's, not a row
            unit = (
                "ms" if "_ms" in key
                else "bytes" if "bytes" in key
                else "count"
            )
            rows.append(
                {
                    "metric": f"serve_elastic.{key}{suffix}",
                    "value": float(v),
                    "unit": unit,
                    "kind": "bench",
                }
            )
    # The per-class QoS nest (ISSUE 19): each SLO class's counters gate
    # as serve_class.<class>.* rows — premium sheds/fails/degrades are
    # COSTS (the failure-ish metric tokens), each class's
    # served_fraction a BENEFIT (the starvation floor made a gate: the
    # batch tenant's served share dropping below the floor regresses
    # even while fleet totals hold).
    classes = rec.get("classes")
    if isinstance(classes, dict):
        for cls in sorted(classes):
            st = classes[cls]
            if not isinstance(st, dict):
                continue
            for key in sorted(st):
                v = st[key]
                if not isinstance(v, (int, float)) or isinstance(v, bool):
                    continue
                unit = "fraction" if "fraction" in key else "count"
                rows.append(
                    {
                        "metric": f"serve_class.{cls}.{key}{suffix}",
                        "value": float(v),
                        "unit": unit,
                        "kind": "bench",
                    }
                )
    # Per-lane admission rejections from the class scheduler's record: a
    # full premium lane is shed-at-the-door evidence ("rejects" token —
    # regresses UP). Scheduler pick counters are workload, not quality —
    # they never gate.
    sched = rec.get("class_scheduler")
    if isinstance(sched, dict) and isinstance(sched.get("lane_full"), dict):
        lane_full = sched["lane_full"]
        for cls in sorted(lane_full):
            v = lane_full[cls]
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                rows.append(
                    {
                        "metric": (
                            f"serve_class.{cls}.lane_full_rejects{suffix}"
                        ),
                        "value": float(v),
                        "unit": "count",
                        "kind": "bench",
                    }
                )
    return rows


def load_bench_records(lines) -> Tuple[Dict[str, dict], Dict[str, dict]]:
    """(measured, unmeasured) bench rows keyed by metric label. Repeated
    measured rows keep EVERY value (collapsed to best at compare time);
    shell noise and non-bench kinds are skipped like the linter skips
    them. Legacy `value: 0.0` rows carrying an `error` field are the
    round-5 dead zeros — classified unmeasured, never ingested. Serve
    SUMMARY records contribute their per-engine nest as synthetic
    `serve_engine.*` rows (flatten_engine_metrics), so a fan-out
    regression confined to one engine still gates."""
    measured: Dict[str, dict] = {}
    unmeasured: Dict[str, dict] = {}

    def ingest(rec: dict) -> None:
        metric = rec.get("metric")
        if not isinstance(metric, str):
            return
        kind = rec.get("kind", schema.infer_kind(rec))
        if kind not in ("bench", "error"):
            return
        dead_zero = rec.get("value") in (0, 0.0) and "error" in rec
        if _is_measured(rec) and not dead_zero:
            slot = measured.setdefault(metric, {"rec": rec, "values": []})
            slot["values"].append(float(rec["value"]))
        else:
            unmeasured[metric] = rec

    for _, rec in schema.iter_json_lines(lines):
        if rec.get("kind") == "serve" and rec.get("event") == "summary":
            for row in flatten_engine_metrics(rec):
                ingest(row)
            continue
        if rec.get("kind") == "collective_time" and isinstance(
            rec.get("site"), str
        ):
            # Per-collective wall-time rows (ISSUE 13): wall_ms gates as
            # a cost by its "ms" unit — a schedule change that slows one
            # site regresses even when totals hide it. The path (trainer
            # route or engine name) keys the regime like a config label.
            v = rec.get("wall_ms")
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                ingest(
                    {
                        "metric": (
                            f"collective_time.{rec.get('path', '?')}."
                            f"{rec['site']} wall_ms"
                        ),
                        "value": float(v),
                        "unit": "ms",
                        "kind": "bench",
                    }
                )
            continue
        if rec.get("kind") == "capacity" and isinstance(
            rec.get("engine"), str
        ):
            h = rec.get("headroom")
            if isinstance(h, (int, float)) and not isinstance(h, bool):
                ingest(
                    {
                        "metric": f"capacity.{rec['engine']}.headroom",
                        "value": float(h),
                        "unit": "fraction",
                        "kind": "bench",
                    }
                )
            continue
        if rec.get("kind") == "forecast" and isinstance(
            rec.get("metric"), str
        ):
            # Forecast-quality rows (ISSUE 17): the matured
            # predicted-vs-realized error and the spawn lead time gate
            # as COSTS (forecast_abs_err/lead_time name tokens) — a
            # change that makes the forecast worse, or the fleet slower
            # to spawn, regresses even though both live on "forecast"
            # records, not bench rows. Unmatured windows (null error)
            # are honest gaps, not zeros — skipped, never ingested.
            series = rec["metric"]
            err = rec.get("forecast_abs_err")
            if isinstance(err, (int, float)) and not isinstance(err, bool):
                ingest(
                    {
                        "metric": f"forecast.{series}.forecast_abs_err",
                        "value": float(err),
                        "unit": "count",
                        "kind": "bench",
                    }
                )
            lead = rec.get("lead_time_ms")
            if isinstance(lead, (int, float)) and not isinstance(
                lead, bool
            ):
                ingest(
                    {
                        "metric": f"forecast.{series}.lead_time_ms",
                        "value": float(lead),
                        "unit": "ms",
                        "kind": "bench",
                    }
                )
            continue
        if rec.get("kind") == "decision":
            # Forecast-AT-DECISION rows (ISSUE 18, the PR 17 forecast-row
            # shape): the error the policy BELIEVED when it acted gates
            # like the live forecast error — a change that makes the
            # fleet act on worse-scored predictions regresses UP even if
            # every window's live score held. Unmatured evidence (null
            # error) is an honest gap, skipped.
            evidence = rec.get("evidence")
            fc = (
                evidence.get("forecast")
                if isinstance(evidence, dict) else None
            )
            fleet = rec.get("fleet", "fleet0")
            if isinstance(fc, dict):
                err = fc.get("forecast_abs_err")
                if isinstance(err, (int, float)) and not isinstance(
                    err, bool
                ):
                    ingest(
                        {
                            "metric": (
                                f"decision.{fleet}.forecast_abs_err"
                            ),
                            "value": float(err),
                            "unit": "count",
                            "kind": "bench",
                        }
                    )
            if isinstance(evidence, dict):
                lead = evidence.get("lead_time_ms")
                if isinstance(lead, (int, float)) and not isinstance(
                    lead, bool
                ):
                    ingest(
                        {
                            "metric": f"decision.{fleet}.lead_time_ms",
                            "value": float(lead),
                            "unit": "ms",
                            "kind": "bench",
                        }
                    )
            continue
        ingest(rec)
    return measured, unmeasured


def _best(values: List[float], lower_better: bool) -> float:
    return min(values) if lower_better else max(values)


def compare_records(
    base_measured: Dict[str, dict],
    base_unmeasured: Dict[str, dict],
    new_measured: Dict[str, dict],
    new_unmeasured: Dict[str, dict],
    *,
    threshold: float = 0.05,
) -> List[dict]:
    """One result dict per metric seen on either side, worst first."""
    results = []
    for metric in sorted(set(base_measured) | set(base_unmeasured)):
        base = base_measured.get(metric)
        if base is None:
            # Unmeasured in BASE: nothing to regress against.
            status = (
                "unmeasured-both" if metric not in new_measured else "recovered"
            )
            rec = new_measured.get(metric)
            new_v = None
            if rec is not None:
                lb = lower_is_better(metric, rec["rec"].get("unit", ""))
                new_v = _best(rec["values"], lb)
            results.append(
                {"metric": metric, "status": status, "new": new_v}
            )
            continue
        unit = base["rec"].get("unit", "")
        lb = lower_is_better(metric, unit)
        base_v = _best(base["values"], lb)
        new = new_measured.get(metric)
        if new is None:
            results.append(
                {
                    "metric": metric,
                    "status": (
                        "unmeasured-in-new"
                        if metric in new_unmeasured
                        else "missing-in-new"
                    ),
                    "base": base_v,
                    "error": new_unmeasured.get(metric, {}).get("error"),
                }
            )
            continue
        new_v = _best(new["values"], lb)
        if base_v == 0:
            rel = 0.0 if new_v == 0 else float("inf")
        else:
            rel = (new_v - base_v) / abs(base_v)
        regressed = rel > threshold if lb else rel < -threshold
        improved = rel < -threshold if lb else rel > threshold
        results.append(
            {
                "metric": metric,
                "status": (
                    "regression"
                    if regressed
                    else "improvement" if improved else "ok"
                ),
                "base": base_v,
                "new": new_v,
                "rel_change": round(rel, 4) if rel != float("inf") else 1e9,
                "unit": unit,
                "lower_is_better": lb,
            }
        )
    for metric in sorted(set(new_measured) - set(base_measured) - set(base_unmeasured)):
        rec = new_measured[metric]
        lb = lower_is_better(metric, rec["rec"].get("unit", ""))
        results.append(
            {
                "metric": metric,
                "status": "new-metric",
                "new": _best(rec["values"], lb),
            }
        )
    # A brand-new metric that ALSO failed to measure (first run of a new
    # bench OOMing, say) must still appear in the report — omitting it
    # would hide that a measurement was attempted at all.
    for metric in sorted(
        set(new_unmeasured)
        - set(base_measured) - set(base_unmeasured) - set(new_measured)
    ):
        results.append(
            {
                "metric": metric,
                "status": "unmeasured-new-only",
                "error": new_unmeasured[metric].get("error"),
            }
        )
    order = {"regression": 0, "missing-in-new": 1, "unmeasured-in-new": 2}
    results.sort(key=lambda r: (order.get(r["status"], 3), r["metric"]))
    return results


class SchemaArtifactError(ValueError):
    pass


def artifact_lines(path: str) -> List[str]:
    """The bench JSONL lines inside one driver round artifact
    (BENCH_r0x.json: a single JSON object whose "tail" field carries the
    bench's final stdout lines). Legacy rounds' value-0.0 dead zeros are
    classified unmeasured by load_bench_records like any other stream —
    the artifact is just a different container for the same rows."""
    with open(path) as fh:
        obj = json.load(fh)
    if not isinstance(obj, dict):
        raise SchemaArtifactError(f"{path}: not a driver artifact object")
    tail = obj.get("tail") or ""
    lines = [l for l in tail.splitlines() if l.strip()]
    parsed = obj.get("parsed")
    if not lines and isinstance(parsed, dict):
        lines = [json.dumps(parsed)]
    return lines


def compare_files(
    base_path: str,
    new_path: str,
    *,
    threshold: float = 0.05,
    artifacts: bool = False,
):
    if artifacts:
        bm, bu = load_bench_records(artifact_lines(base_path))
        nm, nu = load_bench_records(artifact_lines(new_path))
    else:
        with open(base_path) as fh:
            bm, bu = load_bench_records(fh)
        with open(new_path) as fh:
            nm, nu = load_bench_records(fh)
    return compare_records(bm, bu, nm, nu, threshold=threshold)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m glom_tpu.telemetry compare",
        description="Noise-aware bench-trajectory regression gate "
        "(UNMEASURED rows are missing, never zero)",
    )
    ap.add_argument("base", help="baseline bench JSONL/log")
    ap.add_argument("new", help="candidate bench JSONL/log")
    ap.add_argument(
        "--threshold", type=float, default=0.05, metavar="FRAC",
        help="relative change beyond which a move in the regressing "
        "direction fails the gate (default 0.05)",
    )
    ap.add_argument(
        "--fail-on-missing", action="store_true",
        help="also exit nonzero when a baseline metric is absent from NEW "
        "entirely (UNMEASURED rows still only warn — they are missing by "
        "design, not silently dropped)",
    )
    ap.add_argument(
        "--bench-artifact", action="store_true",
        help="BASE/NEW are driver round artifacts (BENCH_r0x.json: one "
        "JSON object whose 'tail' carries the bench rows) instead of raw "
        "JSONL — the round-over-round trajectory gate",
    )
    args = ap.parse_args(argv)
    results = compare_files(
        args.base, args.new,
        threshold=args.threshold, artifacts=args.bench_artifact,
    )

    counts: Dict[str, int] = {}
    for r in results:
        counts[r["status"]] = counts.get(r["status"], 0) + 1
        tag = r["status"].upper().replace("-", "_")
        if r["status"] in ("regression", "improvement", "ok"):
            arrow = f"{r['base']:g} -> {r['new']:g} ({100 * r['rel_change']:+.1f}%)"
            print(f"{tag:<16} {r['metric']}: {arrow}", file=sys.stderr)
        else:
            detail = r.get("error") or ""
            print(f"{tag:<16} {r['metric']} {detail}".rstrip(), file=sys.stderr)

    summary = schema.stamp(
        {
            "summary": True,
            "comparison": {"base": args.base, "new": args.new},
            "threshold": args.threshold,
            "metrics_compared": counts.get("regression", 0)
            + counts.get("improvement", 0)
            + counts.get("ok", 0),
            **{f"n_{k.replace('-', '_')}": v for k, v in sorted(counts.items())},
        },
        kind="summary",
    )
    print(json.dumps(summary))
    failed = counts.get("regression", 0) > 0 or (
        args.fail_on_missing and counts.get("missing-in-new", 0) > 0
    )
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
