"""Telemetry: in-graph diagnostics, collective counters, structured sinks,
and the backend-liveness watchdog (docs/OBSERVABILITY.md).

The subsystem exists because rounds 4-5 produced zero driver-recorded
numbers while the TPU tunnel was wedged — the run itself must emit
schema-stable evidence (step timings, health scalars, collective volumes,
backend state) without a human tailing logs. Import surface:

    schema       — versioned JSONL event contract + lint CLI
    diagnostics  — in-graph scalars, NaN/Inf guard, consensus agreement
    counters     — measured collective wire bytes (manual shard_map path)
    sinks        — step-time histograms, stamped bench emitter
    watchdog     — backend-liveness heartbeat + state machine
    compare      — bench-trajectory regression gate (compare BASE NEW)
    perfetto     — span/flight JSONL -> Perfetto JSON trace (perfetto FILE)

Re-exports are LAZY (PEP 562, same pattern as glom_tpu/__init__):
diagnostics imports jax, and the lint entry point
(`python -m glom_tpu.telemetry FILE`) must work in a jax-broken or
jax-less environment — the exact wedged-image scenario schema.py's
pure-stdlib contract exists for.
"""

_EXPORTS = {
    "CollectiveCounters": "counters",
    "comm_drift": "counters",
    "record_collective": "counters",
    "recording": "counters",
    "TELEMETRY_LEVELS": "diagnostics",
    "resolve_telemetry_level": "diagnostics",
    "SCHEMA_VERSION": "schema",
    "stamp": "schema",
    "validate_record": "schema",
    "StepTimeStats": "sinks",
    "emit": "sinks",
    "BackendWatchdog": "watchdog",
    "backend_record": "watchdog",
    "get_global_watchdog": "watchdog",
    "set_global_watchdog": "watchdog",
}
_SUBMODULES = (
    "compare", "counters", "diagnostics", "perfetto", "schema", "sinks",
    "watchdog",
)

__all__ = sorted([*_EXPORTS, *_SUBMODULES])


def __getattr__(name):
    import importlib

    if name in _SUBMODULES:
        return importlib.import_module(f"glom_tpu.telemetry.{name}")
    if name in _EXPORTS:
        module = importlib.import_module(
            f"glom_tpu.telemetry.{_EXPORTS[name]}"
        )
        return getattr(module, name)
    raise AttributeError(f"module 'glom_tpu.telemetry' has no attribute {name!r}")
