"""Short-horizon load forecasting, scored against what then happened.

The autoscaler (serve/elastic.py) reacts AFTER a breach; ROADMAP item 4
says that at fleet scale the spawn latency IS the outage — acting at
`now + lead_time` needs (a) a load forecast over the capacity window and
(b) a spawn-lead-time model from the stamped spawn_ms evidence. This
module is the EVIDENCE half: it fits both and stamps schema-v9
"forecast" records whose predicted-vs-realized error
(`forecast_abs_err`) is carried on EVERY record — null while nothing has
matured (degenerate fits pin honestly, like the α-β comm model), never
absent (the schema linter rejects an unscored emitter). PR 18 plugs the
numbers into ElasticPolicy; nothing here changes a scaling decision.

Pure stdlib — importable from conftest-less subprocesses and the hw
queue without touching jax or numpy. The clock never appears: callers
pass `t` explicitly, so tests drive a fake clock and replayed artifacts
re-score deterministically.
"""

from __future__ import annotations

import math
import threading
from collections import deque
from typing import Deque, List, Optional, Tuple

from glom_tpu.telemetry import schema


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    return sorted_vals[min(len(sorted_vals) - 1, int(q * len(sorted_vals)))]


class LoadForecaster:
    """Windowed trend (+ optional seasonality) over one metric series.

    observe(t, value) feeds one measured sample (arrival rate, service
    rate — any rps-ish series); forecast(t) fits the trailing window_s of
    samples and predicts the value at t + horizon_s. Every prediction is
    queued until the series passes its target time, then SCORED against
    the realized (interpolated) value — the resulting absolute error
    rides the next records as `forecast_abs_err` (and the running mean as
    `forecast_mae`), so `telemetry compare`/`watch` gate forecast quality
    like any other cost.

    Seasonality (season_s) folds samples into season_buckets phase bins;
    the seasonal deviation (bin mean - global mean) joins the trend
    extrapolation only once the series spans >= 2 full seasons —
    before that the component pins to None with the reason stamped
    (never a half-fit pretending to be a fit).

    Degenerate windows — fewer than min_samples samples, or zero time
    span — emit `predicted: null` with a `reason`, still carrying the
    forecast_abs_err key (the v9 presence contract).
    """

    def __init__(
        self,
        metric: str,
        *,
        window_s: float = 10.0,
        horizon_s: float = 2.0,
        season_s: Optional[float] = None,
        season_buckets: int = 8,
        min_samples: int = 3,
    ):
        if window_s <= 0 or horizon_s <= 0:
            raise ValueError(
                f"window_s {window_s} and horizon_s {horizon_s} must be > 0"
            )
        if season_s is not None and season_s <= 0:
            raise ValueError(f"season_s {season_s} must be > 0 or None")
        if season_buckets < 2:
            raise ValueError(f"season_buckets {season_buckets} must be >= 2")
        if min_samples < 2:
            raise ValueError(f"min_samples {min_samples} must be >= 2")
        self.metric = metric
        self.window_s = float(window_s)
        self.horizon_s = float(horizon_s)
        self.season_s = season_s
        self.season_buckets = season_buckets
        self.min_samples = min_samples
        self._samples: Deque[Tuple[float, float]] = deque()  # (t, value)
        # Seasonal phase bins accumulate over the WHOLE run (seasonality
        # is the long-period structure the trailing window cannot see).
        self._season_sum = [0.0] * season_buckets
        self._season_n = [0] * season_buckets
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None
        # Predictions waiting to mature: (t_target, predicted).
        self._pending: Deque[Tuple[float, float]] = deque()
        self._last_abs_err: Optional[float] = None
        self._last_realized: Optional[float] = None
        self._err_sum = 0.0
        self._n_scored = 0

    # -- ingest ------------------------------------------------------------

    def observe(self, t: float, value: float) -> None:
        """One measured sample of the series at time t (monotone t —
        replayed artifacts and live clocks both qualify)."""
        t, value = float(t), float(value)
        self._samples.append((t, value))
        if self._t_first is None:
            self._t_first = t
        self._t_last = t
        if self.season_s is not None:
            b = int((t % self.season_s) / self.season_s * self.season_buckets)
            b = min(b, self.season_buckets - 1)
            self._season_sum[b] += value
            self._season_n[b] += 1
        self._mature(t)
        self._prune(t)

    def _prune(self, now: float) -> None:
        horizon = now - self.window_s
        while self._samples and self._samples[0][0] < horizon:
            self._samples.popleft()

    def _mature(self, now: float) -> None:
        """Score every pending prediction whose target time has passed,
        against the realized value interpolated at the target."""
        while self._pending and self._pending[0][0] <= now:
            t_target, predicted = self._pending.popleft()
            realized = self._value_at(t_target)
            if realized is None:
                continue  # the series went dark over the target: unscorable
            self._last_realized = realized
            self._last_abs_err = abs(predicted - realized)
            self._err_sum += self._last_abs_err
            self._n_scored += 1

    def _value_at(self, t: float) -> Optional[float]:
        """Linear interpolation of the sample series at t (nearest sample
        when t falls outside the retained span)."""
        if not self._samples:
            return None
        before = after = None
        for ts, v in self._samples:
            if ts <= t:
                before = (ts, v)
            if ts >= t and after is None:
                after = (ts, v)
        if before is None:
            return after[1]
        if after is None:
            return before[1]
        if after[0] == before[0]:
            return before[1]
        frac = (t - before[0]) / (after[0] - before[0])
        return before[1] + frac * (after[1] - before[1])

    # -- the fit -----------------------------------------------------------

    def _trend(self) -> Optional[Tuple[float, float]]:
        """(slope per second, value at the window's last sample) from a
        least-squares line over the retained window; None when the window
        is degenerate (too few samples, zero time span)."""
        pts = list(self._samples)
        if len(pts) < self.min_samples:
            return None
        t0 = pts[0][0]
        xs = [t - t0 for t, _ in pts]
        ys = [v for _, v in pts]
        n = len(pts)
        if xs[-1] - xs[0] <= 0:
            return None
        mx = sum(xs) / n
        my = sum(ys) / n
        sxx = sum((x - mx) ** 2 for x in xs)
        if sxx <= 0:
            return None
        slope = sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / sxx
        return slope, my + slope * (xs[-1] - mx)

    def _seasonal(self, t_target: float) -> Tuple[Optional[float], Optional[str]]:
        """(deviation at t_target's phase, degenerate reason). The
        component needs >= 2 full observed seasons — one season cannot
        distinguish seasonality from trend."""
        if self.season_s is None:
            return None, None
        if (
            self._t_first is None
            or self._t_last is None
            or self._t_last - self._t_first < 2 * self.season_s
        ):
            return None, "season-immature"
        filled = [
            (s / n) for s, n in zip(self._season_sum, self._season_n) if n
        ]
        if len(filled) < 2:
            return None, "season-immature"
        grand = sum(filled) / len(filled)
        b = int(
            (t_target % self.season_s) / self.season_s * self.season_buckets
        )
        b = min(b, self.season_buckets - 1)
        if not self._season_n[b]:
            return None, "season-phase-unseen"
        return self._season_sum[b] / self._season_n[b] - grand, None

    def forecast(self, t: float) -> dict:
        """One stamped "forecast" record predicting the series at
        t + horizon_s. Degenerate fits stamp predicted null + the reason;
        the forecast_abs_err key is ALWAYS present (the v9 contract)."""
        t = float(t)
        self._mature(t)
        self._prune(t)
        t_target = t + self.horizon_s
        fit = self._trend()
        reason = None
        predicted = trend_per_s = seasonal = None
        if fit is None:
            reason = (
                "insufficient-samples"
                if len(self._samples) < self.min_samples
                else "zero-time-span"
            )
        else:
            trend_per_s, last = fit
            t_last = self._samples[-1][0]
            predicted = last + trend_per_s * (t_target - t_last)
            seasonal, season_reason = self._seasonal(t_target)
            if seasonal is not None:
                predicted += seasonal
            elif season_reason is not None:
                reason = season_reason  # trend-only fit, honestly labelled
            self._pending.append((t_target, predicted))
        rec = {
            "metric": self.metric,
            "horizon_s": self.horizon_s,
            "t": round(t, 3),
            "predicted": (
                round(predicted, 4) if predicted is not None else None
            ),
            "realized": (
                round(self._last_realized, 4)
                if self._last_realized is not None else None
            ),
            # The contract key: null until a prediction matures, never
            # absent (schema.validate_record enforces presence at v9).
            "forecast_abs_err": (
                round(self._last_abs_err, 4)
                if self._last_abs_err is not None else None
            ),
            "forecast_mae": (
                round(self._err_sum / self._n_scored, 4)
                if self._n_scored else None
            ),
            "n_scored": self._n_scored,
            "trend_per_s": (
                round(trend_per_s, 6) if trend_per_s is not None else None
            ),
            "seasonal": (
                round(seasonal, 4) if seasonal is not None else None
            ),
            "n_samples": len(self._samples),
            "window_s": self.window_s,
        }
        if reason is not None:
            rec["reason"] = reason
        return schema.stamp(rec, kind="forecast")


class SpawnLeadTimeModel:
    """How long a scale-out takes, from the stamped spawn_ms evidence.

    Each observed spawn latency first SCORES the model's prior estimate
    (|previous lead_time_ms - realized spawn_ms| — the same predicted-vs-
    realized discipline as the load forecast), then joins the sample set.
    lead_time_ms() is the `quantile` nearest-rank percentile — the lead
    the anticipatory policy (PR 18) must act ahead by so `quantile` of
    spawns complete in time. No evidence pins to None, never a guess.
    """

    def __init__(self, *, quantile: float = 0.9, max_samples: int = 256):
        if not 0.0 < quantile <= 1.0:
            raise ValueError(f"quantile {quantile} outside (0, 1]")
        if max_samples < 1:
            raise ValueError(f"max_samples {max_samples} must be >= 1")
        self.quantile = quantile
        self._samples: Deque[float] = deque(maxlen=max_samples)
        self._last_abs_err: Optional[float] = None
        self._err_sum = 0.0
        self._n_scored = 0

    def observe(self, spawn_ms: float) -> None:
        prior = self.lead_time_ms()
        if prior is not None:
            self._last_abs_err = abs(prior - float(spawn_ms))
            self._err_sum += self._last_abs_err
            self._n_scored += 1
        self._samples.append(float(spawn_ms))

    def lead_time_ms(self) -> Optional[float]:
        if not self._samples:
            return None
        return round(_percentile(sorted(self._samples), self.quantile), 3)

    def record(self) -> dict:
        """One stamped "forecast" record of the current lead-time model
        (metric "spawn_lead_time"); degenerate (no spawns yet) pins
        lead_time_ms null with the reason stamped."""
        lead = self.lead_time_ms()
        rec = {
            "metric": "spawn_lead_time",
            # The lead time IS the horizon this model predicts over.
            "horizon_s": round(lead / 1e3, 4) if lead is not None else 0.0,
            "lead_time_ms": lead,
            "quantile": self.quantile,
            "forecast_abs_err": (
                round(self._last_abs_err, 4)
                if self._last_abs_err is not None else None
            ),
            "forecast_mae": (
                round(self._err_sum / self._n_scored, 4)
                if self._n_scored else None
            ),
            "n_scored": self._n_scored,
            "n_samples": len(self._samples),
        }
        if lead is None:
            rec["reason"] = "no-spawn-evidence"
        return schema.stamp(rec, kind="forecast")


class ForecastEmitter:
    """Live glue: a batcher event tap that closes a forecast window every
    interval_s of tap activity and emits ONE scored arrival-rate forecast
    record per window (plus a spawn-lead-time record per scale-out).

    Rides DynamicBatcher.add_event_tap next to the autoscaler's SLO
    monitor; arrivals come from the per-request "admit" events
    (batcher.enable_admission_events() arms them — the same stream the
    WorkloadRecorder captures), spawn evidence from the autoscaler's
    "scale_out" records. Thread-safe: taps fire from worker AND submit
    threads. emit(record) is the caller's sink (MetricsWriter.write,
    telemetry.sinks.emit, a list.append in tests). Windows only close on
    tap activity — an idle stream forecasts nothing, which is the honest
    reading (no traffic, no load to predict)."""

    def __init__(
        self,
        emit,
        *,
        interval_s: float = 0.5,
        window_s: float = 5.0,
        horizon_s: float = 1.0,
        season_s: Optional[float] = None,
        clock=None,
    ):
        if interval_s <= 0:
            raise ValueError(f"interval_s {interval_s} must be > 0")
        import time

        self._emit = emit
        self.interval_s = float(interval_s)
        self._clock = clock if clock is not None else time.monotonic
        self.forecaster = LoadForecaster(
            "arrival_rate_rps",
            window_s=window_s,
            horizon_s=horizon_s,
            season_s=season_s,
        )
        self.lead_model = SpawnLeadTimeModel()
        self._lock = threading.Lock()
        self._t0: Optional[float] = None
        self._window_start: Optional[float] = None
        self._window_arrivals = 0
        # Per-SLO-class arrivals inside the open window (v11): the admit
        # events' slo_class stamps, counted only when classed — a
        # classless stream keeps its forecast records byte-identical.
        self._window_by_class: dict = {}
        self.n_windows = 0
        self._last_forecast: Optional[dict] = None

    def tap(self, rec: dict) -> None:
        out: List[dict] = []
        with self._lock:
            now = self._clock()
            if self._t0 is None:
                self._t0 = self._window_start = now
            if rec.get("kind") == "serve":
                event = rec.get("event")
                if event == "admit":
                    self._window_arrivals += 1
                    cls = rec.get("slo_class")
                    if isinstance(cls, str) and cls:
                        self._window_by_class[cls] = (
                            self._window_by_class.get(cls, 0) + 1
                        )
                elif event in ("scale_out", "spare_spawn") and isinstance(
                    rec.get("spawn_ms"), (int, float)
                ):
                    # Warm-pool spare pre-spawns are REAL spawn evidence
                    # (same factory, same warmup) — they bootstrap the
                    # lead-time model before the first live scale-out,
                    # which is exactly when the anticipatory policy
                    # needs a lead to act ahead of.
                    self.lead_model.observe(float(rec["spawn_ms"]))
                    out.append(self.lead_model.record())
            if now - self._window_start >= self.interval_s:
                out.append(self._close_window(now))
        for r in out:
            self._emit(r)

    def latest_forecast(self) -> Optional[dict]:
        """The most recent closed-window arrival-rate forecast record
        (a copy), or None before any window has closed. The autoscaler
        reads this each tick to stamp the forecast it believed into the
        decision's evidence bundle."""
        with self._lock:
            return dict(self._last_forecast) if self._last_forecast else None

    def _close_window(self, now: float) -> dict:
        """Observe the realized window rate, score, and forecast — caller
        holds the lock."""
        span = max(now - self._window_start, 1e-9)
        rate = self._window_arrivals / span
        by_class = self._window_by_class
        t_rel = now - self._t0
        self.forecaster.observe(t_rel, rate)
        self._window_arrivals = 0
        self._window_by_class = {}
        self._window_start = now
        self.n_windows += 1
        rec = self.forecaster.forecast(t_rel)
        rec["observed_rate_rps"] = round(rate, 4)
        if by_class:
            # Tenant mix of the closed window (v11): per-class arrival
            # counts, stamped only when any admit carried a class.
            rec["by_class"] = {
                cls: by_class[cls] for cls in sorted(by_class)
            }
        self._last_forecast = rec
        return rec

    def close(self) -> None:
        """Flush the final partial window (end-of-run): the run's last
        traffic still scores the forecast before the stream ends."""
        out = []
        with self._lock:
            if self._window_start is not None and (
                self._window_arrivals or self.forecaster._pending
            ):
                out.append(self._close_window(self._clock()))
            out.append(self.lead_model.record())
        for r in out:
            self._emit(r)
