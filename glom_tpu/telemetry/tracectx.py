"""Request-scoped distributed tracing: every request is one causal tree.

PRs 7-9 made a request's life distributed: admission -> bucket ->
dispatch -> continuation hops -> failover requeue -> cache writeback,
possibly across engines and (via failover) across dispatch records that
never knew each other. Each hop already stamps a schema record, but no
stamped event could be joined back to the REQUEST that caused it — a
slow p99 was visible, its cause was not. This module is the Dapper-style
fix: `DynamicBatcher.submit` mints a `trace_id` (the request) and a root
`span_id` (the submit), every downstream record carries
`trace_id`/`span_id`/`parent_span` (batch-level records carry the
parallel `trace_ids`/`parent_spans` lists — one dispatch serves many
traces), and this module reconstructs the tree:

    python -m glom_tpu.telemetry trace FILE... --trace-id X

prints the causal tree for one request and checks CONSERVATION — the
paper's cost unit is per-request EXECUTED WORK, so the summed per-hop
executed iterations and dispatch wall spans of the tree must exactly
equal the totals the ticket resolved with (the stamped "resolve" leaf).
A tree that doesn't conserve means a hop's evidence is missing or
double-counted — exit 1, like the schema linter.

Propagation inside the serving process is a thread-local DISPATCH SCOPE:
the batcher worker opens `dispatch_scope(...)` around one dispatch, and
every serve/recovery/span sink that emits from under it (retry events,
cache evictions, lazy warmup compiles, host spans) inherits the trace
fields without signature changes — `current_fields()` merges at the
stamp sites (serve/events.stamp_serve, resilience/faults.emit_recovery,
tracing/spans.span).

Pure stdlib, like the rest of the telemetry surface: the trace CLI must
run against a crashed run's dumps in a jax-broken environment.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Iterable, List, Optional

# The trace-context vocabulary (schema v6). Per-request records carry the
# singular keys; batch-level records (a dispatch serves many traces) carry
# the parallel plural lists, row-aligned with the batch. The set of serve
# events REQUIRED to carry them is schema.TRACE_REQUIRED_EVENTS — the
# schema registry owns the contract, this module owns the mechanics.
TRACE_FIELDS = ("trace_id", "span_id", "parent_span")
TRACE_BATCH_FIELDS = ("trace_ids", "span_id", "parent_spans")

# The serve latency decomposition (schema v7, docs/OBSERVABILITY.md
# "Capacity observatory"): every dispatch record splits latency_ms into
# these phase fields, IN THIS ORDER — the batcher defines latency_ms as
# their left-to-right float sum, so the conservation check below is
# bit-exact, not approximate. Null values mean ServeConfig.phase_split
# was off (the keys are still present, like the trace-context contract).
PHASE_KEYS = (
    "queue_wait_ms", "pack_ms", "h2d_ms", "device_ms", "resolve_ms"
)


def new_id(nbytes: int = 8) -> str:
    """A fresh random hex id (16 hex chars by default — trace and span
    ids share the format; collision across one deployment's traces is
    negligible at 64 bits)."""
    return os.urandom(nbytes).hex()


new_trace_id = new_id
new_span_id = new_id


# -- thread-local dispatch scope --------------------------------------------

_local = threading.local()


class dispatch_scope:
    """Context manager marking THIS thread as executing one dispatch.

    Every record stamped from inside (retry recovery events, cache
    evictions, a lazy mid-traffic warmup compile, host spans with a
    writer) inherits the scope's trace fields via `current_fields()` —
    the in-process analog of trace-context propagation, with no
    signature changes through the engine/retry/cache layers."""

    def __init__(self, span_id, trace_ids, parent_spans=None):
        self._fields = {"span_id": span_id, "trace_ids": trace_ids}
        if parent_spans is not None:
            self._fields["parent_spans"] = parent_spans

    def __enter__(self):
        stack = getattr(_local, "scopes", None)
        if stack is None:
            stack = _local.scopes = []
        stack.append(self._fields)
        return self

    def __exit__(self, *exc):
        _local.scopes.pop()


def current_fields() -> dict:
    """The innermost open dispatch scope's trace fields on this thread
    ({} outside any scope). Stamp sites merge these with setdefault, so
    explicitly-carried fields always win."""
    stack = getattr(_local, "scopes", None)
    if not stack:
        return {}
    return dict(stack[-1])


# -- tree reconstruction ----------------------------------------------------


def _trace_ids_of(rec: dict) -> List[str]:
    """Every trace id one record belongs to (singular or batch form)."""
    out = []
    t = rec.get("trace_id")
    if isinstance(t, str):
        out.append(t)
    ts = rec.get("trace_ids")
    if isinstance(ts, (list, tuple)):
        out.extend(x for x in ts if isinstance(x, str))
    return out


def records_for(records: Iterable[dict], trace_id: str) -> List[dict]:
    """The subset of `records` belonging to one trace, in stream order."""
    return [r for r in records if trace_id in _trace_ids_of(r)]


def _parent_for(rec: dict, trace_id: str) -> Optional[str]:
    """This record's parent span AS SEEN BY one trace: the singular
    `parent_span`, or the row-aligned entry of `parent_spans`."""
    p = rec.get("parent_span")
    if isinstance(p, str):
        return p
    parents = rec.get("parent_spans")
    traces = rec.get("trace_ids")
    if isinstance(parents, (list, tuple)) and isinstance(traces, (list, tuple)):
        for t, pp in zip(traces, parents):
            if t == trace_id and isinstance(pp, str):
                return pp
    return None


def list_traces(records: Iterable[dict]) -> Dict[str, dict]:
    """trace_id -> {n_records, n_hops, resolved, iters_total} for every
    trace seen in the stream (the `trace` subcommand's no-id listing)."""
    out: Dict[str, dict] = {}
    for rec in records:
        for t in _trace_ids_of(rec):
            slot = out.setdefault(
                t,
                {"n_records": 0, "n_hops": 0, "resolved": False,
                 "iters_total": None},
            )
            slot["n_records"] += 1
            if rec.get("event") == "dispatch":
                slot["n_hops"] += 1
            if rec.get("event") == "resolve":
                slot["resolved"] = True
                slot["iters_total"] = rec.get("iters_total")
    return out


def build_tree(records: Iterable[dict], trace_id: str) -> dict:
    """One trace's causal tree.

    Nodes are SPANS: records sharing a span_id (a dispatch plus the retry
    / cache / warmup events stamped under its scope) collapse into one
    node carrying them all; edges follow each record's parent span as
    seen by this trace. Parents that no record owns roll up to the
    synthesized root (the submit span the batcher minted — submit itself
    emits no record on the happy path). Returns
    {"trace_id", "root": node} with node = {"span_id", "records",
    "children": [node...]}."""
    mine = records_for(records, trace_id)
    nodes: Dict[str, dict] = {}
    order: List[str] = []
    parent_of: Dict[str, Optional[str]] = {}
    for rec in mine:
        span = rec.get("span_id")
        if not isinstance(span, str):
            # A trace-stamped record with no span of its own (e.g. a
            # legacy sink): attach it to the root.
            span = f"<anonymous:{len(nodes)}>"
        node = nodes.get(span)
        if node is None:
            node = nodes[span] = {
                "span_id": span, "records": [], "children": [],
            }
            order.append(span)
        node["records"].append(rec)
        if span not in parent_of:
            parent_of[span] = _parent_for(rec, trace_id)
    root = {"span_id": None, "records": [], "children": []}
    for span in order:
        parent = parent_of.get(span)
        if parent is not None and parent in nodes:
            nodes[parent]["children"].append(nodes[span])
        else:
            if root["span_id"] is None and parent is not None:
                root["span_id"] = parent  # the minted submit span
            root["children"].append(nodes[span])
    return {"trace_id": trace_id, "root": root}


def conservation(records: Iterable[dict], trace_id: str) -> dict:
    """The trace-parity check: per-request executed work must CONSERVE
    across hops. Sums `iters_run` and `latency_ms` over the trace's
    dispatch hops and compares them against the stamped "resolve" leaf's
    `iters_total` / `dispatch_ms_total` (what the ticket resolved with).
    ok=True requires a resolve record and EXACT equality — a missing hop
    or a double-counted one cannot conserve."""
    mine = records_for(records, trace_id)
    hops = [r for r in mine if r.get("event") == "dispatch"]
    resolves = [r for r in mine if r.get("event") == "resolve"]
    hop_iters = sum(
        r["iters_run"] for r in hops
        if isinstance(r.get("iters_run"), (int, float))
    )
    hop_ms = sum(
        r["latency_ms"] for r in hops
        if isinstance(r.get("latency_ms"), (int, float))
    )
    out = {
        "trace_id": trace_id,
        "n_hops": len(hops),
        "hop_iters": hop_iters,
        "hop_dispatch_ms": hop_ms,
        "resolved": bool(resolves),
        "ok": False,
    }
    if not resolves:
        out["why"] = "no resolve record (request never resolved, or its leaf is missing from the stream)"
        return out
    leaf = resolves[-1]
    out["iters_total"] = leaf.get("iters_total")
    out["dispatch_ms_total"] = leaf.get("dispatch_ms_total")
    iters_ok = leaf.get("iters_total") == hop_iters
    # Wall spans: the resolve leaf accumulated the SAME rounded per-hop
    # latency_ms values the dispatch records carry, in the same order —
    # equality here is exact, not approximate.
    ms_ok = leaf.get("dispatch_ms_total") == hop_ms
    # The v7 phase extension: each hop's phase fields must sum (left to
    # right, PHASE_KEYS order — the exact float addition the batcher
    # performed to DEFINE latency_ms) back to that hop's latency_ms, and
    # the per-phase accumulations across hops must equal the resolve
    # leaf's phase_ms_total bit for bit. Hops stamped with null phases
    # (phase_split off) are exempt — the keys' PRESENCE is the schema's
    # job, conservation only binds measured values.
    phase_ok = True
    phase_why = None
    phase_totals: Dict[str, float] = {}
    any_phases = False
    for r in hops:
        vals = [r.get(k) for k in PHASE_KEYS]
        if not all(isinstance(v, (int, float)) for v in vals):
            continue
        any_phases = True
        s = 0.0
        for k, v in zip(PHASE_KEYS, vals):
            s = s + v
            phase_totals[k] = phase_totals.get(k, 0.0) + v
        if s != r.get("latency_ms"):
            phase_ok = False
            phase_why = (
                f"hop phase split does not conserve: phases sum {s}, "
                f"dispatch record says latency_ms={r.get('latency_ms')}"
            )
            break
    leaf_phases = leaf.get("phase_ms_total")
    if phase_ok and any_phases and isinstance(leaf_phases, dict):
        for k in PHASE_KEYS:
            if leaf_phases.get(k) != phase_totals.get(k, 0.0):
                phase_ok = False
                phase_why = (
                    f"phase {k} does not conserve across hops: hops sum "
                    f"{phase_totals.get(k, 0.0)}, resolve leaf says "
                    f"{leaf_phases.get(k)}"
                )
                break
    if any_phases:
        out["phase_ms_total"] = phase_totals
    out["ok"] = iters_ok and ms_ok and phase_ok
    if not iters_ok:
        out["why"] = (
            f"iters do not conserve: hops sum {hop_iters}, resolve leaf "
            f"says {leaf.get('iters_total')}"
        )
    elif not ms_ok:
        out["why"] = (
            f"wall spans do not conserve: hops sum {hop_ms}, resolve "
            f"leaf says {leaf.get('dispatch_ms_total')}"
        )
    elif not phase_ok:
        out["why"] = phase_why
    return out


def _node_label(node: dict) -> str:
    recs = node["records"]
    if not recs:
        return "(submit)"
    head = recs[0]
    event = head.get("event") or head.get("kind") or "?"
    bits = [str(event)]
    if head.get("engine"):
        bits.append(str(head["engine"]))
    if isinstance(head.get("iters_run"), (int, float)):
        bits.append(f"iters={head['iters_run']}")
    if isinstance(head.get("iters_total"), (int, float)):
        bits.append(f"iters_total={head['iters_total']}")
    if isinstance(head.get("latency_ms"), (int, float)):
        bits.append(f"{head['latency_ms']}ms")
    if len(recs) > 1:
        bits.append(f"+{len(recs) - 1} attached")
    return " ".join(bits)


def render_tree(tree: dict) -> List[str]:
    """Human-readable indented lines for one trace tree."""
    lines = [f"trace {tree['trace_id']}"]

    def walk(node, depth):
        lines.append("  " * depth + "- " + _node_label(node))
        for child in node["children"]:
            walk(child, depth + 1)

    for child in tree["root"]["children"]:
        walk(child, 1)
    return lines


# -- CLI --------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import json
    import sys

    from glom_tpu.telemetry import schema

    ap = argparse.ArgumentParser(
        prog="python -m glom_tpu.telemetry trace",
        description="Reconstruct one request's causal tree from stamped "
        "JSONL and verify per-hop executed-work conservation "
        "(docs/OBSERVABILITY.md, Request tracing)",
    )
    ap.add_argument("paths", nargs="+", help="JSONL logs / flight dumps")
    ap.add_argument(
        "--trace-id", default=None,
        help="the trace to reconstruct; omit to list every trace seen",
    )
    args = ap.parse_args(argv)
    records: List[dict] = []
    for path in args.paths:
        with open(path) as fh:
            records.extend(rec for _, rec in schema.iter_json_lines(fh))
    if args.trace_id is None:
        traces = list_traces(records)
        if not traces:
            print("no trace-stamped records found", file=sys.stderr)
            return 1
        for t, info in sorted(traces.items()):
            status = "resolved" if info["resolved"] else "OPEN"
            print(
                f"{t}  {info['n_hops']} hops  {info['n_records']} records"
                f"  {status}"
                + (
                    f"  iters_total={info['iters_total']}"
                    if info["iters_total"] is not None
                    else ""
                )
            )
        return 0
    tree = build_tree(records, args.trace_id)
    if not tree["root"]["children"]:
        print(f"no records for trace {args.trace_id}", file=sys.stderr)
        return 1
    for line in render_tree(tree):
        print(line)
    check = conservation(records, args.trace_id)
    print(json.dumps(schema.stamp(dict(check, summary=True), kind="summary")))
    if not check["ok"]:
        print(
            f"CONSERVATION FAILED: {check.get('why', '?')}", file=sys.stderr
        )
        return 1
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
