"""Backend-liveness watchdog: the shell `watcher.log` promoted to a module.

Round 5's TPU tunnel flapped down for ~60 seconds mid-session and nothing
structured recorded it — the only evidence was a hand-tailed shell log, so
the driver's "backend-init-unavailable" records carried no outage timeline.
This heartbeat wraps `probe_device_count` (the throwaway-subprocess probe
that survives a WEDGED plugin — in-process `jax.devices()` hangs forever in
that state) and stamps every state transition as a schema-versioned
"watchdog" event into the same JSONL stream the trainer/bench records ride.

States: unknown -> up/down on the first probe; up <-> down on changes; and
`flapping` when >= flap_threshold transitions land inside flap_window_s
(the round-5 signature: a backend that answers, dies, answers again — worse
than plainly down, because half your queue steps dispatch into the gap).

A process-global watchdog (set_global_watchdog) lets every sink stamp the
current backend state without threading a handle through every call:
`backend_record()` is what trainers/benches merge into their records.

Between transitions, a healthy backend confirms itself with a low-cadence
heartbeat event (heartbeat_s, default 10 min): a run that later hangs
SILENTLY leaves a ring whose last heartbeat dates the silence, instead of
a stale buffer with no way to tell a quiet hour from a dead one.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, List, Optional

from glom_tpu.telemetry import schema

STATES = schema.WATCHDOG_STATES  # ("unknown", "up", "down", "flapping")


def _default_probe(timeout: float) -> Optional[int]:
    # Deferred import: utils.metrics is the probe's home and imports this
    # package for record stamping — a top-level import would cycle.
    from glom_tpu.utils.metrics import probe_device_count

    return probe_device_count(timeout=timeout)


class BackendWatchdog:
    """Heartbeat over the backend-init probe with transition stamping.

    `probe(timeout) -> Optional[int]` returns the visible device count or
    None (init failed/hung). `writer` (anything with .write(dict), e.g.
    MetricsWriter) receives one stamped "watchdog" event per transition;
    the full timeline is also kept in memory for end-of-run records.
    start() runs probes from a daemon thread every interval_s; probe_once()
    is the synchronous form the benches use as their fail-fast gate.
    """

    def __init__(
        self,
        *,
        interval_s: float = 60.0,
        probe: Optional[Callable[[float], Optional[int]]] = None,
        probe_timeout: float = 120.0,
        writer=None,
        flap_window_s: float = 600.0,
        flap_threshold: int = 3,
        heartbeat_s: float = 600.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if flap_threshold < 2:
            raise ValueError("flap_threshold must be >= 2 (a single "
                             "transition is just up or down)")
        self.interval_s = interval_s
        self._probe = probe if probe is not None else _default_probe
        self.probe_timeout = probe_timeout
        self.writer = writer
        self.flap_window_s = flap_window_s
        self.flap_threshold = flap_threshold
        # Low-cadence "up"-confirmation events (0 disables): transitions
        # only fire on CHANGE, so a run that silently hangs leaves a stale
        # flight-recorder ring with no way to date the silence. A
        # heartbeat event at most every heartbeat_s keeps the ring
        # timestamped — the gap after the LAST heartbeat bounds when the
        # hang began (ROADMAP backlog item).
        self.heartbeat_s = heartbeat_s
        self._last_heartbeat: Optional[float] = None
        self._clock = clock
        self._t0 = clock()
        self._lock = threading.Lock()
        self._state = "unknown"
        self._devices: Optional[int] = None
        self._transitions = 0
        self._transition_times: deque = deque()
        self._timeline: List[dict] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._probes = 0
        self._probe_fault: Optional[Callable[[Optional[int]], Optional[int]]] = None

    # -- fault-injection seam ---------------------------------------------

    def set_probe_fault(
        self, fault: Optional[Callable[[Optional[int]], Optional[int]]]
    ) -> None:
        """Chaos seam (glom_tpu/resilience/faults.py): `fault` receives the
        REAL probe's result and returns the possibly-corrupted one (None =
        backend looks down). The state machine, transition stamping, and
        every downstream consumer see only the faulted value — exactly the
        view a genuinely flapping backend would present — while the
        injector stamps its own schema "fault" event per injection, so a
        chaos run can reconcile observed transitions against injected
        flaps. Pass None to remove."""
        with self._lock:
            self._probe_fault = fault

    # -- state machine ----------------------------------------------------

    def probe_once(self) -> str:
        """Run one probe, update the state machine, stamp any transition."""
        n = self._probe(self.probe_timeout)
        with self._lock:
            fault = self._probe_fault
        if fault is not None:
            # Outside the lock: the injector stamps "fault" events, and a
            # writer that re-enters record() must not deadlock.
            n = fault(n)
        with self._lock:
            self._probes += 1
            self._devices = n
            raw = "up" if n is not None and n >= 1 else "down"
            prev = self._state
            prev_raw = "up" if prev in ("up", "flapping") else prev
            now = self._clock() - self._t0
            if raw != prev_raw:
                self._transitions += 1
                self._transition_times.append(now)
                while (
                    self._transition_times
                    and now - self._transition_times[0] > self.flap_window_s
                ):
                    self._transition_times.popleft()
                flapping = (
                    prev != "unknown"
                    and len(self._transition_times) >= self.flap_threshold
                )
                new = "flapping" if flapping and raw == "up" else raw
                self._record_transition(prev, new, now)
                self._state = new
            elif self._state == "flapping" and not self._transition_times:
                # Flap window drained with no new transitions: settled.
                self._record_transition("flapping", "up", now)
                self._state = "up"
            else:
                # Re-confirmations age the flap window.
                while (
                    self._transition_times
                    and now - self._transition_times[0] > self.flap_window_s
                ):
                    self._transition_times.popleft()
                # Quiet re-confirmation of a healthy backend: emit the
                # low-cadence heartbeat so a later total hang is datable
                # from the ring (transitions reset the cadence — a fresh
                # transition event IS a timestamp).
                if (
                    self.heartbeat_s > 0
                    and self._state == "up"
                    and (
                        self._last_heartbeat is None
                        or now - self._last_heartbeat >= self.heartbeat_s
                    )
                ):
                    self._record_heartbeat(now)
            return self._state

    def _record_transition(self, prev: str, new: str, t: float) -> None:
        event = schema.stamp(
            {
                "t": round(t, 3),
                "wall_time_s": round(time.time(), 3),
                "event": "backend_transition",
                "prev_state": prev,
                "backend_state": new,
                "backend_devices": self._devices,
                "transitions": self._transitions,
            },
            kind="watchdog",
        )
        self._timeline.append(event)
        self._last_heartbeat = t  # any stamped event restarts the cadence
        self._write_event(event)

    def _record_heartbeat(self, t: float) -> None:
        """The "up"-confirmation event: NOT a transition (the timeline and
        transition counter stay clean), just a timestamped pulse into the
        writer / flight ring. Only ever fired for state "up" — a repeated
        "down" heartbeat would re-trigger the flight recorder's
        backend-down dump on every probe."""
        self._last_heartbeat = t
        event = schema.stamp(
            {
                "t": round(t, 3),
                "wall_time_s": round(time.time(), 3),
                "event": "heartbeat",
                "backend_state": self._state,
                "backend_devices": self._devices,
                "probes": self._probes,
            },
            kind="watchdog",
        )
        self._write_event(event)

    def _write_event(self, event: dict) -> None:
        # No writer: the global flight recorder gets the event directly,
        # so a down transition still triggers the postmortem dump.
        from glom_tpu.tracing.flight import write_or_observe

        write_or_observe(self.writer, event)

    # -- heartbeat thread -------------------------------------------------

    def start(self) -> "BackendWatchdog":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.probe_once()
                except Exception:
                    pass  # the watchdog must never take the run down
                self._stop.wait(self.interval_s)

        self._thread = threading.Thread(
            target=loop, name="glom-backend-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- reads ------------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def timeline(self) -> List[dict]:
        with self._lock:
            return list(self._timeline)

    def record(self) -> dict:
        """The fields every metrics/bench record stamps."""
        with self._lock:
            return {
                "backend_state": self._state,
                "backend_devices": self._devices,
                "backend_transitions": self._transitions,
            }


# -- process-global registration ------------------------------------------

_GLOBAL: Optional[BackendWatchdog] = None


def set_global_watchdog(wd: Optional[BackendWatchdog]) -> None:
    global _GLOBAL
    _GLOBAL = wd


def get_global_watchdog() -> Optional[BackendWatchdog]:
    return _GLOBAL


def _inprocess_backend_live() -> bool:
    """Has THIS process already initialized a jax backend successfully?
    (Private-API peek with a safe fallback: a live in-process backend is
    the one case where 'up' is certain without spawning a probe.)"""
    try:
        from jax._src import xla_bridge

        return bool(getattr(xla_bridge, "_backends", None))
    except Exception:
        return False


def backend_record() -> dict:
    """Watchdog fields for a metrics record: the global watchdog's state
    when one is registered; otherwise 'up' iff a backend is already live
    in-process (a trainer mid-step IS the liveness proof), else 'unknown'
    — never a guess."""
    wd = get_global_watchdog()
    if wd is not None:
        return wd.record()
    return {"backend_state": "up" if _inprocess_backend_live() else "unknown"}
