"""Profiling / tracing (SURVEY.md §5: absent in the reference).

The phase structure inside the scan body is already annotated with
jax.named_scope (bottom_up / top_down / consensus / mean_update in
models/core.py), so XProf/TensorBoard traces group by phase out of the box.
This module adds the capture plumbing and an MFU report built on the
analytic FLOP model (utils/metrics.py).
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

import jax

from glom_tpu.utils.config import GlomConfig
from glom_tpu.utils.metrics import flops_per_column_iter, mfu


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/glom_tpu_trace"):
    """Capture a profiler trace of the enclosed block.

    View with: tensorboard --logdir <log_dir>  (or xprof).
    """
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


def start_server(port: int = 9999):
    """On-demand profiling: connect TensorBoard's profile tab to this port
    while training runs (the 'attach to a live job' workflow)."""
    return jax.profiler.start_server(port)


def annotate(name: str):
    """Trace annotation decorator for host-side phases (data loading, eval)."""

    def deco(fn):
        return jax.profiler.annotate_function(fn, name=name)

    return deco


def perf_report(
    cfg: GlomConfig,
    *,
    column_iters_per_sec: float,
    chip: str = "v5e",
    num_chips: int = 1,
    backward: bool = False,
) -> dict:
    """Assemble the north-star metrics dict from a measured rate."""
    return {
        "column_iters_per_sec_per_chip": column_iters_per_sec / num_chips,
        "flops_per_column_iter": flops_per_column_iter(cfg),
        "mfu": mfu(
            cfg, column_iters_per_sec / num_chips, chip=chip, backward=backward
        ),
        "chip": chip,
        "num_chips": num_chips,
    }


class StepTimer:
    """Rolling wall-clock step timer that syncs on a supplied scalar, for
    platforms where block_until_ready is unreliable (see bench.py)."""

    def __init__(self):
        self._t0: Optional[float] = None
        self.history: list[float] = []

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self, sync_scalar=None) -> float:
        if sync_scalar is not None:
            float(sync_scalar)  # host fetch = real synchronization
        dt = time.perf_counter() - self._t0
        self.history.append(dt)
        return dt

    @property
    def best(self) -> float:
        return min(self.history)
