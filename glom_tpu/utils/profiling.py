"""Back-compat shim: the profiling stub grew into `glom_tpu/tracing/`.

Everything this module used to define lives there now — spans, the
step-windowed TraceCapture, HBM accounting, and the flight recorder are
the new surface (docs/OBSERVABILITY.md). The original names keep working
from here:

    trace / start_server / annotate  -> glom_tpu.tracing.capture
    perf_report / StepTimer          -> glom_tpu.tracing.report
"""

from glom_tpu.tracing.capture import annotate, start_server, trace
from glom_tpu.tracing.report import StepTimer, perf_report

__all__ = ["StepTimer", "annotate", "perf_report", "start_server", "trace"]
