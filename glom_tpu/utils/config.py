"""Configuration dataclasses.

The reference's entire "config system" is the six `Glom.__init__` kwargs
(glom_pytorch/glom_pytorch.py:76-83) plus two forward kwargs. Those six are
preserved verbatim in `GlomConfig`; everything else (training, mesh, backend)
layers around them without touching the model contract.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple, Union


@dataclasses.dataclass(frozen=True)
class GlomConfig:
    """Model hyperparameters — field-for-field the reference constructor."""

    dim: int = 512
    levels: int = 6
    image_size: int = 224
    patch_size: int = 14
    consensus_self: bool = False
    local_consensus_radius: int = 0
    # Extensions beyond the reference kwargs (defaults match its hardcoded values):
    mult: int = 4  # FFW expansion, reference hardcodes 4
    channels: int = 3  # reference hardcodes RGB

    def __post_init__(self):
        if self.image_size % self.patch_size != 0:
            raise ValueError(
                f"image_size {self.image_size} not divisible by patch_size {self.patch_size}"
            )
        if self.levels < 2:
            raise ValueError("levels must be >= 2 (top-down net needs levels-1 groups)")

    @property
    def num_patches_side(self) -> int:
        return self.image_size // self.patch_size

    @property
    def num_patches(self) -> int:
        return self.num_patches_side ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * self.channels

    @property
    def default_iters(self) -> int:
        # "twice the levels, for information to propagate up and back down"
        # (reference :105)
        return 2 * self.levels


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Parallelism layout. Axis sizes of 1 disable an axis.

    data:  batch sharding (DP) — gradient allreduce over ICI
    seq:   patch-axis sharding (SP) — ring / halo consensus
    model: dim sharding (TP) of the FFW weights

    num_slices > 1 marks a multi-slice (DCN-connected) topology: the data
    axis is laid out slice-major, so its outermost num_slices-way split
    rides DCN while everything inside a slice (the inner data split, seq,
    model) rides ICI. Axis names and logical shape are unchanged — XLA
    decomposes the data-axis allreduce hierarchically from the device
    placement (mesh_utils.create_hybrid_device_mesh).
    """

    data: int = 1
    seq: int = 1
    model: int = 1
    num_slices: int = 1

    def __post_init__(self):
        if self.num_slices > 1 and self.data % self.num_slices != 0:
            raise ValueError(
                f"data axis {self.data} not divisible by num_slices "
                f"{self.num_slices} (the DCN split is the outer data axis)"
            )

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return ("data", "seq", "model")

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.data, self.seq, self.model)

    @property
    def num_devices(self) -> int:
        return self.data * self.seq * self.model


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Batched-inference serving policy (glom_tpu/serve, docs/SERVING.md).

    The engine compiles ONE program per batch bucket ahead of traffic
    (warmup) and the batcher pads every dispatched batch up to the
    smallest admitting bucket — requests never trigger a mid-traffic
    recompile, the serving-side analog of the trainer's static-shape
    discipline."""

    # Ascending batch-size buckets the engine precompiles; a dispatch of n
    # requests pads to the smallest bucket >= n. The largest bucket is the
    # dispatch ceiling.
    buckets: Tuple[int, ...] = (1, 2, 4, 8)
    # Admission policy: dispatch when max_batch requests are waiting, or
    # when the OLDEST waiting request has aged max_delay_ms — whichever
    # comes first (latency floor vs throughput ceiling).
    max_batch: int = 8
    max_delay_ms: float = 5.0
    # Bounded request queue: submissions beyond this depth are SHED
    # immediately (backpressure — a full queue means the engine is already
    # saturated; queueing deeper only grows tail latency).
    queue_depth: int = 64
    # Forward iteration budget: an int pins the count, None uses the model
    # default (2L), "auto" enables consensus early exit (serve/early_exit:
    # up to max_auto_iters updates, stopping when no level's agreement
    # moves more than exit_threshold between iterations).
    iters: Union[int, str, None] = None  # int | "auto" | None
    exit_threshold: float = 1e-3
    min_iters: int = 1
    max_auto_iters: Optional[int] = None  # None -> model default (2L)
    # Two-tier early exit (serve/early_exit.glom_forward_tiered,
    # docs/SERVING.md "Continuation queue"): the bucket exits once this
    # FRACTION of its valid rows has individually converged (per-row
    # witness; ceil(quorum * n_valid) rows). 1.0 = every valid row must
    # converge before the bucket exits (the strictest quorum — batch-level
    # behavior). Unconverged stragglers at bucket exit re-bucket into the
    # batcher's continuation queue — carried as warm column state with the
    # REMAINING iteration budget — up to max_continuations hops; 0 hops
    # disables re-bucketing (stragglers resolve with the state they have,
    # exactly the pre-two-tier contract).
    exit_quorum: float = 1.0
    max_continuations: int = 0
    # Serve mesh (parallel/serve_mesh.py): axis sizes > 1 route every
    # bucket signature through the manual shard_map forward over
    # (data, seq) — batch rows sharded over 'data', the patch axis over
    # 'seq' — with the early-exit witness collectives legal inside the
    # while_loop body. Every bucket must be divisible by mesh_data (the
    # engine validates; a non-divisible bucket would silently pad-shard).
    mesh_data: int = 1
    mesh_seq: int = 1
    compute_dtype: str = "float32"  # "bfloat16" for MXU-native serving
    use_pallas: bool = False
    # Donate the input buffer to each compiled call so XLA reuses it for
    # outputs (None = auto: on TPU only — CPU ignores donation noisily).
    donate: Optional[bool] = None
    # Transient-dispatch retry (glom_tpu/resilience/retry.py): a failed
    # dispatch retries up to dispatch_retries times with exponential
    # backoff from retry_backoff_ms — UNLESS the watchdog says the backend
    # is down, which fails fast (never retry into a dead backend). 0
    # disables. Caller bugs (ValueError/TypeError) never retry.
    dispatch_retries: int = 2
    retry_backoff_ms: float = 25.0
    # Degradation ladder (glom_tpu/resilience/ladder.py, opt-in via
    # DynamicBatcher(ladder=...) — serve/cli.py --ladder wires it): under
    # queue pressure or a flapping backend, step down normal ->
    # capped-iters -> capped-buckets -> shed instead of jumping straight
    # to shed. degraded_iters None -> half the model budget (floor 1);
    # degraded_max_batch None -> half max_batch (floor 1).
    ladder: bool = False
    degraded_iters: Optional[int] = None
    degraded_max_batch: Optional[int] = None
    ladder_high_water: float = 0.75  # queue fill that steps DOWN a rung
    ladder_low_water: float = 0.25   # queue fill that steps back UP
    # Streaming warm-start column cache (glom_tpu/serve/column_cache.py,
    # docs/SERVING.md "Streaming"): requests carrying a session_id write
    # their converged [n, L, d] columns back under the session key and the
    # NEXT frame of the stream dispatches warm from that state (the
    # engine's warm levels0 signature), exiting iters="auto" in a fraction
    # of the cold budget. column_cache_bytes is the HARD residency budget
    # (LRU eviction, priced per entry by column_state_bytes — the
    # live-bytes model); 0 disables streaming entirely. column_cache_ttl_s
    # expires a quiet stream's entry at lookup (None = no expiry); entries
    # are additionally invalidated the moment a dispatch on their source
    # engine fails, so stale or dead-engine state never warm-starts.
    column_cache_bytes: int = 0
    column_cache_ttl_s: Optional[float] = None
    # Paged column memory (glom_tpu/serve/paged_columns.py, docs/SERVING.md
    # "Paged column memory"): page_pool_pages > 0 preallocates ONE
    # device-resident HBM buffer of [page_pool_pages, page_tokens, L, d]
    # per engine — the column-state page pool. Cached session columns then
    # live in pool pages instead of host arrays: warm dispatches assemble
    # levels0 IN-GRAPH via a page-index take (zero host<->device levels0
    # transfer on the warm path) and write-back on resolve copies the
    # converged columns device-to-device into owned pages. 0 keeps the
    # PR 8 host-array cache (every warm dispatch re-uploads its columns).
    # page_tokens is the page granularity in patch tokens; 0 resolves to
    # the largest divisor of num_patches <= 64 (resolve_page_tokens — a
    # page must tile the full-resolution row so the bucket route's
    # [bucket, n] layout maps onto whole pages).
    page_pool_pages: int = 0
    page_tokens: int = 0
    # In-place pool aliasing (docs/SERVING.md "Pool aliasing"): True
    # promotes pool write-backs from copy-on-write buffer swaps (every
    # write re-materializes the WHOLE pool buffer) to DONATED in-graph
    # scatter updates — the write-back aliases the pool's own pages, so
    # pool bytes moved per write drop from pool_bytes to the written
    # pages only. The dispatch/write-back serialization seam: dispatches
    # hold a READ PIN on the buffer snapshot (acquire_read/release_read)
    # and every aliased write advances the pool EPOCH; a write that finds
    # pins outstanding falls back to CoW LOUDLY (alias_fallback event +
    # counter) so an in-flight dispatch never reads a donated buffer.
    # False (default) keeps the CoW discipline byte-for-byte.
    pool_aliasing: bool = False
    # Ragged admission (docs/SERVING.md "Ragged admission"): requests with
    # DIFFERING patch counts (mixed resolutions/aspect ratios) share one
    # dispatch sized by total PAGES instead of padding every row to the
    # worst-row bucket shape. ragged_pages is the ascending page-count
    # ladder the ragged signatures precompile (the page-axis analog of
    # `buckets`); empty resolves to buckets x pages-per-full-row. Requires
    # local_consensus_radius == 0 (the ragged window has no 2D coordinate
    # grid to build a radius mask from — the engine validates loudly).
    ragged: bool = False
    ragged_pages: Tuple[int, ...] = ()
    # Ragged consensus gather (serve/early_exit.py, docs/SERVING.md
    # "Block-banded ragged consensus"):
    #   "windowed"      — the row-windowed per-token gather (the PR 11
    #                     form): W k/v column states duplicated per TOKEN
    #                     per iteration;
    #   "banded"        — the page-blocked band: pages are the blocks,
    #                     each token attends within its row's page band
    #                     computed from the flat [T, L, d] state — the
    #                     duplicated working set shrinks page_tokens-fold
    #                     and the output is BITWISE the windowed route at
    #                     threshold 0 (the house parity rule; locked by
    #                     tests and the --banded-ab gate);
    #   "banded-pallas" — the streaming Pallas kernel
    #                     (kernels/banded_consensus.py) reading k/v pages
    #                     in place — kernel-parity TOLERANCE, like the
    #                     fused dense route; falls back to "banded" off
    #                     TPU.
    ragged_attention: str = "windowed"
    # Delta streaming (glom_tpu/serve/paged_columns.py, docs/SERVING.md
    # "Delta streaming"): instead of rewriting a session's whole [n, L, d]
    # column state every frame, each session keeps a paged BASE plus a
    # chain of frame-to-frame DELTAS — only pages whose column residual
    # exceeds delta_page_atol are stored (0.0 = exact: a page is "changed"
    # when any BIT differs). Reconstruction is base+Σdeltas resolved to an
    # effective page map and assembled in-graph by the same page-index
    # take the paged warm path already uses (zero levels0 H2D). The chain
    # compacts base <- base+Σdeltas device-to-device at delta_chain_cap.
    # delta_base_share aliases content-identical bases across sessions
    # (hash at write-back, refcounted pool pages — two cameras on one
    # scene pay for one base). delta_incremental routes warm frames
    # through glom_forward_incremental: the early-exit witness is seeded
    # from the INPUT delta's page support, so rows whose frame did not
    # change start pre-converged (min_iters floor still applies) and a
    # small perturbation converges in ~1-2 iters. Requires a page pool;
    # exclusive with ragged admission (bucket route only for now). Any
    # delta_page_atol > 0 mode stamps the tolerance on every record the
    # compare gate reads — threshold 0 stays BITWISE.
    delta_streaming: bool = False
    delta_page_atol: float = 0.0
    delta_chain_cap: int = 4
    delta_base_share: bool = True
    delta_incremental: bool = True
    # Sharded paged route (parallel/serve_mesh.py): how a paged warm
    # dispatch materializes pool pages across the 'data' shards.
    #   "pool"   — all_gather the WHOLE pool per dispatch (the PR 11
    #              provisioning bound);
    #   "needed" — exchange ONLY the pages the dispatch references via a
    #              registered psum_scatter (dp x rows x pages-per-row
    #              page payloads — the pad-free wire);
    #   "auto"   — pick whichever moves fewer bytes at the signature's
    #              static shapes (the compile trace records the choice).
    page_gather: str = "auto"
    # Engine REJOIN after recovery (docs/RESILIENCE.md): a fan-out engine
    # marked dead re-enters service only after rejoin_threshold
    # CONSECUTIVE successful probation health dispatches (stamped
    # engine_rejoin event); 0 keeps death terminal until restart (the
    # pre-PR 8 contract). rejoin_interval_ms paces the probation probes.
    rejoin_threshold: int = 0
    rejoin_interval_ms: float = 200.0
    # Request-scoped tracing (telemetry/tracectx.py, docs/OBSERVABILITY.md
    # "Request tracing"): submit() mints a trace_id/span_id per request
    # and every downstream serve record (dispatch, continuation, shed,
    # failover, retry, cache, resolve) carries the context, so
    # `python -m glom_tpu.telemetry trace` reconstructs the causal tree.
    # Default ON — the measured overhead bar is <2% at full stamping
    # (`bench_serve.py --trace-ab`). False stamps the context keys as
    # null (explicitly untraced — the schema still lints).
    trace_requests: bool = True
    # Serve latency decomposition (docs/OBSERVABILITY.md, "Capacity
    # observatory"): every dispatch record splits latency_ms into
    # queue_wait / pack / h2d / device / resolve phase fields that sum to
    # it BIT-EXACTLY (and accumulate into the per-request resolve leaf),
    # so `telemetry trace` shows where each request's time went across
    # hops. Default ON — the bar is <2% (`bench_serve.py --phase-ab`);
    # False stamps the phase keys as null and reverts latency_ms to the
    # bare engine dispatch wall (the pre-v7 reading).
    phase_split: bool = True
    # Per-collective wall-time on the serve mesh (telemetry/comm_time.py,
    # resolved by counters.resolve_collective_timing — the
    # telemetry_level discipline): "off" (default), "sampled" (every
    # collective_timing_interval-th dispatch re-dispatches each witness /
    # gather site as its own timed sub-graph), "full" (every execution
    # bracketed by dataflow-ordered io_callbacks, inserted at the AOT
    # compile). Single-device engines have no collectives: any mode
    # resolves to "off" there, stamped.
    collective_timing: str = "off"
    collective_timing_interval: int = 16
    # SLO-driven elastic serving (glom_tpu/serve/elastic.py,
    # docs/SERVING.md "Elastic serving"): elastic=True runs an Autoscaler
    # control loop next to the batcher that reads the live capacity
    # records (headroom) plus in-process SLO breaches and CHANGES the
    # fleet — scale-out spawns a fully-warmed engine replica at runtime
    # (admission opens only after precompile), scale-in gracefully drains
    # the least-loaded engine (stop admitting -> flush -> migrate cache
    # sessions -> release devices). False (the default) keeps the static
    # --engines N fleet byte-for-byte. The policy is windowed low/high
    # water with min-dwell hysteresis and a post-action cooldown, clamped
    # to [min_engines, max_engines]:
    #   * worst eligible headroom < elastic_low_water continuously for
    #     elastic_dwell_s (or any armed upper-bound SLO breach —
    #     elastic_p99_ms / elastic_shed_rate, None = not armed) scales
    #     OUT; a breach also VETOES scale-in (breach precedence);
    #   * worst eligible headroom > elastic_high_water continuously for
    #     elastic_dwell_s scales IN (drain the max-headroom engine).
    # elastic_interval_s paces the control ticks; elastic_window_s is
    # the signal window the policy and its SLO monitor share.
    elastic: bool = False
    min_engines: int = 1
    max_engines: int = 4
    elastic_low_water: float = 0.15
    elastic_high_water: float = 0.6
    elastic_dwell_s: float = 2.0
    elastic_cooldown_s: float = 5.0
    elastic_window_s: float = 10.0
    elastic_interval_s: float = 0.5
    elastic_p99_ms: Optional[float] = None
    elastic_shed_rate: Optional[float] = None
    # Drained-husk retention (schema v9, docs/OBSERVABILITY.md "Workload
    # observatory"): a scale-in leaves the drained engine in the summary
    # as an evidence husk. None (both defaults) retains every husk
    # forever — the pre-v9 shape. husk_max keeps at most N husks (oldest
    # retire first); husk_max_age_s retires a husk once it has been
    # drained that long. Retirement folds the husk's counters into the
    # summary's husks_retired nest and stamps one engine_husk_retired
    # event, so conservation still reconciles after the trim.
    husk_max: Optional[int] = None
    husk_max_age_s: Optional[float] = None
    # Anticipatory autoscaling (schema v10, docs/SERVING.md "Anticipatory
    # autoscaling"): elastic_anticipatory=True lets the policy act on the
    # forecast load at `now + spawn_lead_time` instead of the already-
    # breached present — a positive predicted deficit over the fleet's
    # usable capacity (measured service rate x elastic_target_utilization)
    # arms scale-out and vetoes scale-in. The anticipatory signal only
    # fires once BOTH models have matured (a scored forecast_abs_err and
    # spawn-lead evidence); until then the policy is the reactive PR 14
    # semantics bit-for-bit. Every decision stamps its evidence bundle
    # (`python -m glom_tpu.telemetry audit` replays it).
    elastic_anticipatory: bool = False
    elastic_target_utilization: float = 0.8
    # Warm-pool spares: N pre-spawned, fully-warmed engine replicas held
    # OUTSIDE admission (never registered with the batcher, so a spare is
    # not a husk and serves no traffic). Scale-out promotes a spare at
    # ~0 spawn cost; scale-in demotes the drained engine back into the
    # pool instead of releasing its devices. Spare spawn latencies feed
    # the spawn-lead-time model before the first live scale-out.
    warm_pool: int = 0
    # Multi-tenant QoS (glom_tpu/serve/qos.py, docs/SERVING.md "SLO
    # classes"): named SLO classes — e.g. ("premium:weight=8,p99_ms=150",
    # "standard:weight=3", "batch:weight=1,shed_rate=0.5") — turn the
    # batcher's shared FIFO into a deficit-weighted-fair class scheduler
    # with PER-CLASS bounded lanes (batch backpressure can never fill
    # premium's lane), class-aware ladder gates (the first class in the
    # shed order degrades and sheds a rung early), class-scoped SLO rules
    # ("p99_ms[premium]=X"), and per-class decision evidence the audit
    # weighs. None (the default) keeps the classless batcher and policy
    # byte-for-byte. slo_default_class labels unclassed submits (default:
    # "standard" when declared, else the highest-weight class);
    # slo_shed_order overrides the ascending-weight default; the
    # starvation floor is each lower class's guaranteed pick share under
    # strict-priority contention.
    slo_classes: Optional[Tuple[str, ...]] = None
    slo_default_class: Optional[str] = None
    slo_shed_order: Optional[Tuple[str, ...]] = None
    slo_starvation_floor: float = 0.05

    def __post_init__(self):
        if not self.buckets:
            raise ValueError("buckets must be non-empty")
        if list(self.buckets) != sorted(set(self.buckets)):
            raise ValueError(f"buckets {self.buckets} must be strictly ascending")
        if any(b < 1 for b in self.buckets):
            raise ValueError(f"buckets {self.buckets} must be >= 1")
        if self.max_batch > max(self.buckets):
            raise ValueError(
                f"max_batch {self.max_batch} exceeds the largest bucket "
                f"{max(self.buckets)} (the dispatch ceiling)"
            )
        if self.max_batch < 1:
            raise ValueError(f"max_batch {self.max_batch} must be >= 1")
        if self.queue_depth < 1:
            raise ValueError(f"queue_depth {self.queue_depth} must be >= 1")
        if self.max_delay_ms < 0:
            raise ValueError(f"max_delay_ms {self.max_delay_ms} must be >= 0")
        if self.iters is not None and self.iters != "auto":
            if not isinstance(self.iters, int) or self.iters < 1:
                raise ValueError(
                    f"iters={self.iters!r}: an int >= 1, 'auto', or None"
                )
        if self.exit_threshold < 0:
            raise ValueError(f"exit_threshold {self.exit_threshold} must be >= 0")
        if self.min_iters < 1:
            raise ValueError(f"min_iters {self.min_iters} must be >= 1")
        if not 0.0 < self.exit_quorum <= 1.0:
            raise ValueError(
                f"exit_quorum {self.exit_quorum} outside (0, 1] (1.0 = all "
                "valid rows must converge before the bucket exits)"
            )
        if self.max_continuations < 0:
            raise ValueError(
                f"max_continuations {self.max_continuations} must be >= 0"
            )
        if self.mesh_data < 1 or self.mesh_seq < 1:
            raise ValueError(
                f"mesh_data={self.mesh_data} mesh_seq={self.mesh_seq}: "
                "serve mesh axes must be >= 1"
            )
        if self.mesh_data > 1 and any(
            b % self.mesh_data for b in self.buckets
        ):
            raise ValueError(
                f"every bucket {self.buckets} must be divisible by "
                f"mesh_data={self.mesh_data} (batch rows shard over 'data')"
            )
        if self.dispatch_retries < 0:
            raise ValueError(
                f"dispatch_retries {self.dispatch_retries} must be >= 0"
            )
        if self.retry_backoff_ms < 0:
            raise ValueError(
                f"retry_backoff_ms {self.retry_backoff_ms} must be >= 0"
            )
        if self.degraded_iters is not None and self.degraded_iters < 1:
            raise ValueError(
                f"degraded_iters {self.degraded_iters} must be >= 1 or None"
            )
        if self.degraded_max_batch is not None and self.degraded_max_batch < 1:
            raise ValueError(
                f"degraded_max_batch {self.degraded_max_batch} must be >= 1 "
                "or None"
            )
        if not 0.0 <= self.ladder_low_water < self.ladder_high_water <= 1.0:
            raise ValueError(
                f"need 0 <= ladder_low_water ({self.ladder_low_water}) < "
                f"ladder_high_water ({self.ladder_high_water}) <= 1"
            )
        if self.column_cache_bytes < 0:
            raise ValueError(
                f"column_cache_bytes {self.column_cache_bytes} must be >= 0 "
                "(0 disables the streaming column cache)"
            )
        if self.column_cache_ttl_s is not None and self.column_cache_ttl_s <= 0:
            raise ValueError(
                f"column_cache_ttl_s {self.column_cache_ttl_s} must be > 0 "
                "or None"
            )
        if self.page_pool_pages < 0:
            raise ValueError(
                f"page_pool_pages {self.page_pool_pages} must be >= 0 "
                "(0 disables the device-resident column page pool)"
            )
        if self.page_tokens < 0:
            raise ValueError(
                f"page_tokens {self.page_tokens} must be >= 0 (0 resolves "
                "from the model's patch count)"
            )
        # Ragged admission COMPOSES with the continuation queue (ISSUE
        # 16 lifted the PR 11 exclusivity): straggler rows carry their
        # flat page-aligned state through the host levels0 form
        # (glom_forward_ragged's continuation carry) and re-enter as
        # ragged rows with their remaining budget. Only the fixed route
        # stays incompatible — a fixed iteration count has no stragglers.
        if self.ragged and self.max_continuations > 0 and self.iters != "auto":
            raise ValueError(
                "ragged continuations need iters='auto': a fixed route "
                "has no convergence witness to leave stragglers behind"
            )
        if self.ragged_attention not in ("windowed", "banded", "banded-pallas"):
            raise ValueError(
                f"ragged_attention {self.ragged_attention!r}: 'windowed', "
                "'banded', or 'banded-pallas'"
            )
        if self.pool_aliasing and self.page_pool_pages <= 0:
            raise ValueError(
                "pool_aliasing needs a device page pool "
                "(page_pool_pages > 0): there is no buffer to alias"
            )
        if self.ragged_pages:
            if list(self.ragged_pages) != sorted(set(self.ragged_pages)):
                raise ValueError(
                    f"ragged_pages {self.ragged_pages} must be strictly "
                    "ascending"
                )
            if any(p < 1 for p in self.ragged_pages):
                raise ValueError(
                    f"ragged_pages {self.ragged_pages} must be >= 1"
                )
        if self.delta_streaming:
            if self.page_pool_pages <= 0:
                raise ValueError(
                    "delta_streaming needs a device page pool "
                    "(page_pool_pages > 0): delta entries are pool pages"
                )
            if self.ragged:
                raise ValueError(
                    "delta_streaming rides the bucket route only (ragged "
                    "delta chains are a documented follow-on)"
                )
        if self.delta_page_atol < 0:
            raise ValueError(
                f"delta_page_atol {self.delta_page_atol} must be >= 0 "
                "(0.0 = exact: any changed bit stores the page)"
            )
        if self.delta_chain_cap < 1:
            raise ValueError(
                f"delta_chain_cap {self.delta_chain_cap} must be >= 1"
            )
        if self.page_gather not in ("auto", "pool", "needed"):
            raise ValueError(
                f"page_gather {self.page_gather!r}: 'auto', 'pool', or "
                "'needed'"
            )
        if self.rejoin_threshold < 0:
            raise ValueError(
                f"rejoin_threshold {self.rejoin_threshold} must be >= 0 "
                "(0 keeps engine death terminal)"
            )
        if self.rejoin_interval_ms <= 0:
            raise ValueError(
                f"rejoin_interval_ms {self.rejoin_interval_ms} must be > 0"
            )
        if self.collective_timing not in ("off", "sampled", "full"):
            raise ValueError(
                f"collective_timing {self.collective_timing!r}: one of "
                "('off', 'sampled', 'full')"
            )
        if self.collective_timing_interval < 1:
            raise ValueError(
                f"collective_timing_interval "
                f"{self.collective_timing_interval} must be >= 1"
            )
        if self.min_engines < 1:
            raise ValueError(f"min_engines {self.min_engines} must be >= 1")
        if self.max_engines < self.min_engines:
            raise ValueError(
                f"max_engines {self.max_engines} must be >= min_engines "
                f"{self.min_engines}"
            )
        if not 0.0 <= self.elastic_low_water < self.elastic_high_water <= 1.0:
            raise ValueError(
                f"need 0 <= elastic_low_water ({self.elastic_low_water}) < "
                f"elastic_high_water ({self.elastic_high_water}) <= 1"
            )
        if self.elastic_dwell_s < 0 or self.elastic_cooldown_s < 0:
            raise ValueError(
                f"elastic_dwell_s {self.elastic_dwell_s} and "
                f"elastic_cooldown_s {self.elastic_cooldown_s} must be >= 0"
            )
        if self.elastic_window_s <= 0 or self.elastic_interval_s <= 0:
            raise ValueError(
                f"elastic_window_s {self.elastic_window_s} and "
                f"elastic_interval_s {self.elastic_interval_s} must be > 0"
            )
        if self.elastic_p99_ms is not None and self.elastic_p99_ms <= 0:
            raise ValueError(
                f"elastic_p99_ms {self.elastic_p99_ms} must be > 0 or None"
            )
        if self.elastic_shed_rate is not None and not (
            0.0 <= self.elastic_shed_rate <= 1.0
        ):
            raise ValueError(
                f"elastic_shed_rate {self.elastic_shed_rate} must be in "
                "[0, 1] or None"
            )
        if self.husk_max is not None and self.husk_max < 0:
            raise ValueError(
                f"husk_max {self.husk_max} must be >= 0 or None"
            )
        if self.husk_max_age_s is not None and self.husk_max_age_s < 0:
            raise ValueError(
                f"husk_max_age_s {self.husk_max_age_s} must be >= 0 or None"
            )
        if not 0.0 < self.elastic_target_utilization <= 1.0:
            raise ValueError(
                f"elastic_target_utilization "
                f"{self.elastic_target_utilization} must be in (0, 1]"
            )
        if self.warm_pool < 0:
            raise ValueError(f"warm_pool {self.warm_pool} must be >= 0")
        if not 0.0 <= self.slo_starvation_floor < 1.0:
            raise ValueError(
                f"slo_starvation_floor {self.slo_starvation_floor} must "
                "be in [0, 1)"
            )
        if self.slo_classes is not None or self.slo_shed_order is not None:
            # The one class-table resolution (glom_tpu/serve/qos.py,
            # stdlib-only — no jax rides this import): a typo'd class
            # spec, duplicate name, unknown default/shed-order entry, or
            # unsatisfiable starvation floor fails HERE, at config
            # construction, not mid-traffic. A shed order without
            # declared classes is equally a config bug.
            if not self.slo_classes:
                raise ValueError(
                    "slo_shed_order needs slo_classes: there are no "
                    "declared classes to order"
                )
            from glom_tpu.serve.qos import resolve_slo_classes

            resolve_slo_classes(self)


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Self-supervised denoising trainer (the reference's README recipe)."""

    batch_size: int = 8
    learning_rate: float = 1e-4
    weight_decay: float = 0.0
    # Learning-rate schedule: "constant" | "cosine" | "warmup_cosine".
    # Cosine decays to lr_final_fraction * learning_rate; schedule_steps
    # is the TOTAL schedule length — for warmup_cosine that INCLUDES the
    # warmup_steps of linear warmup (cosine decay then spans
    # schedule_steps - warmup_steps; optax semantics). Anything beyond
    # these composes via passing an optax optimizer to the Trainer.
    lr_schedule: str = "constant"
    schedule_steps: int = 10_000
    warmup_steps: int = 0
    lr_final_fraction: float = 0.0
    # Gradient accumulation: split each batch into grad_accum microbatches,
    # scan value_and_grad over them accumulating gradients, ONE optimizer
    # update — trains an effective batch grad_accum x larger than what
    # fits in HBM at once (batch_size must divide evenly).
    # None (the default) = AUTO-ROUTE: resolve_training_route may split the
    # batch when exact accumulation recovers the fused-loop VJP. An explicit
    # value — INCLUDING 1 — is pinned and never overridden, so a user who
    # wants the single-pass full-batch step (memory/latency A/B) sets
    # grad_accum=1 (docs/PARALLELISM.md, "Opting out of auto grad-accum").
    grad_accum: Optional[int] = None
    noise_std: float = 1.0
    # Which stacked iteration's top level feeds the reconstruction head.
    # Reference README uses index 7 for L=6/T=12 (mid-iteration top level).
    recon_iter_index: Optional[int] = None  # None -> T // 2 + 1 (7 at T=12)
    iters: Optional[int] = None  # None -> model default (2L)
    remat: bool = False  # jax.checkpoint over the scan body ("ckpt over iters")
    compute_dtype: str = "float32"  # "bfloat16" for MXU-optimal training
    use_pallas: bool = False  # fused TPU kernels on the forward hot path
    # ZeRO-style cross-replica sharded weight update (Xu et al. 2020,
    # arXiv:2004.13336 — the GSPMD "automatic cross-replica sharding of
    # weight update"). Stages:
    #   0 — replicated optimizer state, monolithic gradient allreduce
    #       (the classic DP step);
    #   1 — optimizer state sharded over the 'data' mesh axis; gradients
    #       move as reduce-scatter, each replica updates only its owned
    #       shard, updated params all-gather back;
    #   2 — additionally the gradient-accumulation buffer is sharded:
    #       each microbatch's gradients reduce-scatter immediately, so
    #       only the 1/dp shard is ever accumulated (differs from stage 1
    #       only when grad_accum > 1).
    # Resolution (dp==1 -> 0) is resolve_zero_stage in train/trainer.py —
    # the single source both trainers stamp into every metrics record.
    zero_stage: int = 0
    # EQuARX-style int8 block-scaled quantized all-reduce (arXiv:2506.17615)
    # — EXPERIMENTAL, and on this codebase an EMULATION: gradients are
    # block-quantized to int8 and dequantized before the reduction
    # collective, modeling one wire-quantization hop (the real thing
    # quantizes inside XLA's collective; that needs a compiler hook).
    # Changes numerics (~1e-2 relative on gradients); never on by default.
    quantized_reduce: bool = False
    # Telemetry depth (glom_tpu/telemetry, docs/OBSERVABILITY.md):
    #   "off"     — no in-graph diagnostics beyond the loss (the sustained-
    #               throughput default; static analytics still stamped);
    #   "scalars" — per-step grad/update/param norms + a NaN/Inf guard,
    #               computed INSIDE the jitted step (one fused reduction),
    #               plus measured collective counters on the manual path;
    #   "full"    — scalars + per-level consensus-agreement stats (GSPMD /
    #               single-device paths; the manual shard_map path degrades
    #               to "scalars" loudly — the resolved level is stamped).
    # Resolution is telemetry.diagnostics.resolve_telemetry_level — the
    # single source both trainers stamp into every metrics record.
    telemetry_level: str = "off"
    # What the NaN/Inf guard does when a step produces a non-finite loss or
    # gradient (active only when telemetry_level != "off"):
    #   "skip" — the update is dropped in-graph (params/opt state keep
    #            their previous values; the step counter still advances)
    #            and the record carries skipped_nonfinite=1;
    #   "warn" — the update is applied as-is, the record just flags it.
    # Either way fit_loop emits a structured "anomaly" event at the next
    # logging step.
    nonfinite_policy: str = "skip"
    # Unroll the T-iteration scan into straight-line code. Removes the
    # residual-stack dynamic-slice bookkeeping scan autodiff pays per
    # iteration (~3-5% step time at the flagship config on v5e, measured
    # back-to-back). Costs compile time proportional to T; leave off for
    # large T, under remat (which exists to NOT keep per-iteration
    # residuals), and in GSPMD regions where compile time is precious.
    scan_unroll: bool = False
    # Per-collective wall-time on the manual path (docs/OBSERVABILITY.md
    # "Capacity observatory"; resolved by
    # counters.resolve_collective_timing — the telemetry_level
    # discipline): "off" (default), "sampled" (every
    # collective_timing_interval-th fit-loop logging boundary, each
    # registered zero1-schedule site is re-dispatched as its own timed
    # sub-graph and stamped as a "collective_time" record with the α-β
    # comm_time_model drift), "full" (degrades to "sampled" loudly here —
    # the jit-on-first-call trainer has no AOT seam for the io_callback
    # brackets). Only the manual zero>=1 route has registered sites; the
    # GSPMD step resolves to "off", stamped.
    collective_timing: str = "off"
    collective_timing_interval: int = 10
    seed: int = 0
