"""Metrics / observability (SURVEY.md §5: absent in reference — built here).

JSONL metrics writer + the analytic FLOP model used for MFU. The FLOP model
follows SURVEY.md §3.2's hot-loop profile:

  per column-update iteration, per image:
    bottom-up MLP : 2 matmuls over L groups   = 2 * n * L * d * (d*mult) * 2
    top-down  MLP : same over L-1 groups
    consensus     : 2 einsums, O(L * n^2 * d) = 2 * L * n * n * d * 2

A "column-iter" (the north-star unit) = one t-step update of all n*L level
vectors of one image.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path
from typing import Optional

from glom_tpu.utils.config import GlomConfig


def flops_per_column_iter(cfg: GlomConfig) -> float:
    """FLOPs for one column-update iteration of ONE image (forward only)."""
    n, L, d, m = cfg.num_patches, cfg.levels, cfg.dim, cfg.mult
    ffw = lambda groups: 2 * 2 * n * groups * d * (d * m)  # two matmuls, MACs*2
    bottom_up = ffw(L)
    top_down = ffw(L - 1)
    consensus = 2 * 2 * L * n * n * d  # qk^T and attn@v
    return float(bottom_up + top_down + consensus)


def tokens_flops(cfg: GlomConfig) -> float:
    """Patch embedding FLOPs per image (outside the loop)."""
    return float(2 * cfg.num_patches * cfg.patch_dim * cfg.dim)


# Peak bf16 TFLOP/s per chip. v5e ("TPU v5 lite"): 197 bf16 TFLOP/s.
PEAK_FLOPS = {
    "v6e": 918e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "cpu": 1e12,  # nominal, so MFU math never divides by zero off-TPU
}


def apply_env_platform() -> None:
    """Mirror JAX_PLATFORMS into jax.config in THIS process (no-op when
    unset or a backend is already live).

    MUST be called before first backend use by every caller that trusts
    probe_device_count's result: the probe subprocess honors the env var
    at the config level (this image's sitecustomize hook overrides the
    env var alone), so a caller that skips this would initialize a
    different — possibly wedged — backend than the one the probe just
    validated."""
    import jax

    p = os.environ.get("JAX_PLATFORMS")
    if p:
        try:
            jax.config.update("jax_platforms", p)
        except RuntimeError:
            pass  # a backend is already live in this process


def probe_device_count(timeout: float = 120.0) -> Optional[int]:
    """Visible-device count via a THROWAWAY subprocess, or None when backend
    init fails or hangs.

    Never touches a backend in the calling process: a wedged TPU plugin makes
    `jax.devices()` hang indefinitely (observed round 4: both driver artifacts
    died in parent-process backend init before any framework code ran), and a
    hang cannot be caught in-process. The subprocess inherits the caller's
    env, and additionally applies JAX_PLATFORMS at the CONFIG level (this
    image's sitecustomize hook pre-registers the TPU plugin and overrides
    the env var, so env alone would still wedge the probe — same discovery
    as tests/conftest.py and the dryrun re-exec bootstrap). So
    virtual-CPU-mesh setups (JAX_PLATFORMS=cpu +
    --xla_force_host_platform_device_count=N) probe exactly what the caller
    intends, instantly."""
    import subprocess
    import sys

    code = (
        "import os, jax\n"
        "p = os.environ.get('JAX_PLATFORMS')\n"
        "if p:\n"
        "    jax.config.update('jax_platforms', p)\n"
        "print('DEVCOUNT=%d' % len(jax.devices()))"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except (subprocess.TimeoutExpired, OSError):
        return None
    if proc.returncode != 0:
        return None
    for line in proc.stdout.splitlines():
        if line.startswith("DEVCOUNT="):
            return int(line.split("=", 1)[1])
    return None


def detect_chip(device=None) -> str:
    """Map jax device_kind to a PEAK_FLOPS key ('v5e' fallback with the
    benefit of the doubt going to the lowest-peak TPU)."""
    import jax

    device = device or jax.devices()[0]
    if device.platform != "tpu":
        return "cpu"
    kind = device.device_kind.lower()
    if "v6" in kind:
        return "v6e"
    if "v5" in kind:
        # "TPU v5 lite" = v5e; "TPU v5p"/"TPU v5" = v5p
        return "v5e" if "lite" in kind or "v5e" in kind else "v5p"
    if "v4" in kind:
        return "v4"
    return "v5e"


def mfu(
    cfg: GlomConfig,
    column_iters_per_sec: float,
    *,
    chip: str = "v5e",
    backward: bool = False,
) -> float:
    """Model FLOP utilization from measured column-iters/sec/chip."""
    f = flops_per_column_iter(cfg)
    if backward:
        f *= 3.0  # fwd + ~2x bwd
    return column_iters_per_sec * f / PEAK_FLOPS[chip]


def _spec_divisor(spec, axis_sizes: dict) -> int:
    """How many ways a PartitionSpec splits a leaf: the product of the mesh
    axis sizes it names (axis entries may be a name or a tuple of names)."""
    div = 1
    for entry in tuple(spec):
        if entry is None:
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        for name in names:
            div *= int(axis_sizes.get(name, 1))
    return div


def tree_bytes_per_replica(tree, spec_tree, axis_sizes: dict) -> int:
    """Live bytes of a pytree PER REPLICA under a PartitionSpec tree: each
    leaf's global bytes divided by the ways its spec splits it. Pure
    analytics — works from abstract shapes, no device needed (the
    "recorded even when no chip is available" contract)."""
    import jax
    import numpy as np
    from jax.sharding import PartitionSpec

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    specs = (
        [None] * len(leaves)  # spec_tree=None: fully replicated
        if spec_tree is None
        else treedef.flatten_up_to(spec_tree)
    )
    total = 0
    for leaf, spec in zip(leaves, specs):
        nbytes = int(np.prod(np.shape(leaf))) * np.dtype(leaf.dtype).itemsize
        if isinstance(spec, PartitionSpec):
            nbytes //= _spec_divisor(spec, axis_sizes)
        total += nbytes
    return total


def live_bytes_model(
    params,
    opt_state,
    *,
    axis_sizes: dict,
    param_specs,
    opt_specs,
    grad_specs,
) -> dict:
    """Per-replica live-bytes for the three train-state tenants the ZeRO
    stages trade between: params (always gathered for the forward), the
    gradient buffer (full at stage<=1, 1/dp shard at stage 2), and the
    optimizer moments (1/dp shard at stage>=1). Spec trees are the SAME
    objects the trainers shard with, so the report can never drift from
    the layout actually trained."""
    return {
        "params_bytes_per_replica": tree_bytes_per_replica(
            params, param_specs, axis_sizes
        ),
        "grads_bytes_per_replica": tree_bytes_per_replica(
            params, grad_specs, axis_sizes
        ),
        "opt_bytes_per_replica": tree_bytes_per_replica(
            opt_state, opt_specs, axis_sizes
        ),
    }


def comm_volume_model(
    grad_bytes: int,
    param_bytes: int,
    dp: int,
    zero_stage: int,
    *,
    quantized: bool = False,
    grad_accum: int = 1,
) -> dict:
    """Per-replica per-step collective wire bytes of the gradient/update
    path (ring-algorithm costs; SP/TP collectives are priced separately in
    docs/PARALLELISM.md since they depend on activation shapes):

      stage 0 — one allreduce of the full gradient: 2*(dp-1)/dp * G
      stage 1 — reduce-scatter G + all-gather P: (dp-1)/dp * (G + P)
      stage 2 — the reduce-scatter happens once PER MICROBATCH (that is
                what keeps the accumulator sharded): (dp-1)/dp *
                (accum * G + P)

    Quantized reduce carries the gradient payload as int8 + block scales
    (~G/4 + G/512); the param all-gather stays f32 (EQuARX quantizes the
    reduce, not the weights)."""
    from glom_tpu.parallel.quantized import DEFAULT_BLOCK

    if dp <= 1:
        return {
            "comm_reduce_bytes_per_step": 0,
            "comm_gather_bytes_per_step": 0,
            "comm_bytes_per_step": 0,
        }
    frac = (dp - 1) / dp
    wire_grad = grad_bytes
    if quantized:
        elems = grad_bytes // 4
        wire_grad = elems + (-(-elems // DEFAULT_BLOCK)) * 4
    if zero_stage == 0:
        reduce_bytes = int(2 * frac * wire_grad)
        gather_bytes = 0
    else:
        n_scatters = grad_accum if zero_stage >= 2 else 1
        reduce_bytes = int(frac * wire_grad * n_scatters)
        gather_bytes = int(frac * param_bytes)
    return {
        "comm_reduce_bytes_per_step": reduce_bytes,
        "comm_gather_bytes_per_step": gather_bytes,
        "comm_bytes_per_step": reduce_bytes + gather_bytes,
    }


class MetricsWriter:
    """Append-only JSONL metrics log, one dict per line, with wall time.

    Every record is stamped with the versioned event schema
    (glom_tpu/telemetry/schema.py: schema_version + kind, inferred when
    the caller didn't stamp) — trainer metrics, watchdog transitions, and
    bench rows all validate against the same contract, which is what lets
    `python -m glom_tpu.telemetry.schema` lint any artifact of record.

    `tensorboard_dir` additionally mirrors numeric scalars to TensorBoard
    via clu.metric_writers (XProf/TensorBoard is the stack's native UI);
    records carrying a `step` key are written at that step, others at an
    internal counter. The JSONL file stays the artifact of record — it is
    what the benches and tests read back."""

    def __init__(
        self,
        path: Optional[str] = None,
        echo: bool = True,
        tensorboard_dir: Optional[str] = None,
    ):
        import threading

        self.path = Path(path) if path else None
        self.echo = echo
        self._t0 = time.time()
        self._seq = 0
        # The watchdog heartbeat thread writes transition events into the
        # same stream as the training loop's records — serialize writes
        # so no JSONL row can interleave mid-line.
        self._lock = threading.Lock()
        if self.path:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            self._fh = self.path.open("a")
        else:
            self._fh = None
        self._tb = None
        if tensorboard_dir:
            try:
                from clu import metric_writers  # deferred: heavy import
            except ImportError as e:
                raise ImportError(
                    "tensorboard_dir requires the optional `clu` package "
                    "(pip install clu); JSONL metrics work without it"
                ) from e
            self._tb = metric_writers.SummaryWriter(tensorboard_dir)

    def write(self, metrics: dict):
        from glom_tpu.telemetry import schema
        from glom_tpu.tracing.flight import observe_event

        rec = schema.stamp({"wall_time": round(time.time() - self._t0, 3), **metrics})
        # Every record of record also lands in the crash flight recorder's
        # ring buffer (no-op until one is registered globally).
        observe_event(rec)
        line = json.dumps(rec)
        with self._lock:
            if self._fh:
                self._fh.write(line + "\n")
                self._fh.flush()
            if self.echo:
                sys.stdout.write(line + "\n")
                sys.stdout.flush()
        if self._tb is not None:
            scalars = {
                k: float(v)
                for k, v in rec.items()
                if isinstance(v, (int, float))
                and not isinstance(v, bool)
                and k != "schema_version"  # constant stamp, not a signal
            }
            with self._lock:
                step = int(scalars.pop("step", self._seq))
                self._seq = step + 1
                if scalars:
                    self._tb.write_scalars(step, scalars)

    def close(self):
        if self._fh:
            self._fh.close()
        if self._tb is not None:
            self._tb.close()
