"""Checkpoint / resume (SURVEY.md §5: absent in the reference — users were
left with torch.save; the README never even shows it).

Orbax-backed checkpointing of the full training state: params, optimizer
state, step counter, the host rng key, and optionally the carried `levels`
of a temporal run. Async by default (the save overlaps the next training
steps); `wait()` or close() drains. Restore is sharding-aware: pass the
abstract state (jax.eval_shape of your init) plus shardings and Orbax
device_puts shards directly on restore — the multi-host resume path.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Optional

import jax
import numpy as np

try:  # orbax is in the image; guard anyway so import of glom_tpu never dies
    import orbax.checkpoint as ocp

    HAVE_ORBAX = True
except ImportError:  # pragma: no cover
    HAVE_ORBAX = False


class _SpanSink:
    """Writer shim for the checkpoint spans: forwards to the manager's
    metrics_writer when one is attached, else straight to the global
    flight recorder — the same no-writer fallback every other sink takes."""

    def __init__(self, mgr: "CheckpointManager"):
        self._mgr = mgr

    def write(self, rec: dict) -> None:
        from glom_tpu.tracing.flight import write_or_observe

        write_or_observe(self._mgr.metrics_writer, rec)


class CheckpointManager:
    """Thin wrapper over orbax.CheckpointManager for TrainState pytrees.

    save()/wait() are span-covered (tracing.spans.spanned:
    host_checkpoint_save / host_checkpoint_wait): with async saves the
    save() span bounds the blocking serialize-and-enqueue slice and the
    wait() span the drain — the last unattributed host-time sinks the
    ROADMAP named. Pass `metrics_writer` to land the span events in the
    run's metrics stream (train/cli.py does)."""

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
        async_save: bool = True,
        metrics_writer=None,
    ):
        if not HAVE_ORBAX:
            raise RuntimeError("orbax-checkpoint is not available")
        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=async_save,
        )
        self._mgr = ocp.CheckpointManager(self.directory, options=options)
        self.metrics_writer = metrics_writer
        from glom_tpu.tracing.spans import spanned

        sink = _SpanSink(self)
        self.save = spanned("host_checkpoint_save", writer=sink)(self.save)
        self.wait = spanned("host_checkpoint_wait", writer=sink)(self.wait)

    def save(self, step: int, state: Any, *, levels: Optional[Any] = None) -> bool:
        """Save state (+ optional carried temporal `levels`) at `step`."""
        items = {"state": ocp.args.StandardSave(state)}
        if levels is not None:
            items["levels"] = ocp.args.StandardSave(levels)
        return self._mgr.save(step, args=ocp.args.Composite(**items))

    def restore(
        self,
        step: Optional[int] = None,
        *,
        abstract_state: Any,
        abstract_levels: Optional[Any] = None,
    ):
        """Restore the latest (or a specific) step.

        abstract_state: jax.eval_shape-style pytree of ShapeDtypeStruct,
        optionally with .sharding set — restored arrays land directly in
        that sharding (no host bounce), which is what makes multi-host
        resume work.
        Returns (step, state) or (step, (state, levels)).
        """
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found in {self.directory}")
        items = {"state": ocp.args.StandardRestore(abstract_state)}
        if abstract_levels is not None:
            items["levels"] = ocp.args.StandardRestore(abstract_levels)
        restored = self._mgr.restore(step, args=ocp.args.Composite(**items))
        if abstract_levels is not None:
            return step, (restored["state"], restored["levels"])
        return step, restored["state"]

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return self._mgr.all_steps()

    def wait(self):
        """Block until any in-flight async save lands."""
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.wait_until_finished()
        self._mgr.close()


def abstract_like(tree: Any) -> Any:
    """Shape/dtype skeleton of a pytree (for restore targets)."""
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype), tree
    )
