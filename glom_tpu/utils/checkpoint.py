"""Checkpoint / resume (SURVEY.md §5: absent in the reference — users were
left with torch.save; the README never even shows it).

Orbax-backed checkpointing of the full training state: params, optimizer
state, step counter, the host rng key, and optionally the carried `levels`
of a temporal run. Async by default (the save overlaps the next training
steps); `wait()` or close() drains. Restore is sharding-aware: pass the
abstract state (jax.eval_shape of your init) plus shardings and Orbax
device_puts shards directly on restore — the multi-host resume path.

Crash-safety (docs/RESILIENCE.md): Orbax's commit marker makes each step
ATOMIC against a mid-write kill, but not VERIFIED — a step that corrupts
after commit (truncated array file, torn copy, bad disk) still lists as
latest and crashes the restore that production recovery depends on. Every
save therefore also lands a checksum manifest (`manifest_<step>.json`
next to the step dir: per-file size + sha256, itself written temp-file →
fsync → atomic rename), and the read side — `latest_step`, `valid_steps`,
`restore(step=None)` — only ever hands out steps that VERIFY: a torn or
checksum-failed step is skipped with a stamped "recovery" event
(action "skip-torn-checkpoint") and the previous valid step restores
instead. A step with no manifest at all (written by an older build, or by
a process killed between Orbax's commit and the manifest write) is
accepted on Orbax's commit marker alone — strictly better-than-before,
never worse.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import time
import threading
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import jax
import numpy as np

try:  # orbax is in the image; guard anyway so import of glom_tpu never dies
    import orbax.checkpoint as ocp

    HAVE_ORBAX = True
except ImportError:  # pragma: no cover
    HAVE_ORBAX = False


class CheckpointCorruptError(RuntimeError):
    """An EXPLICITLY requested step failed manifest verification. The
    step=None path never raises this — it skips to the previous valid
    step — but a caller who names a step gets the loud failure."""


def _fsync_dir(path: Path) -> None:
    """fsync the directory entry so the rename itself is durable (an
    atomic rename that the kernel never flushed is atomic only until the
    power fails)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # pragma: no cover — exotic FS without dir-open
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write_json(path: Path, obj: Any) -> None:
    """Temp path in the SAME directory + flush + fsync + os.replace: a
    reader (or a crash) sees either the old file or the complete new one,
    never a torn write — the manifest must itself be un-tearable or it
    certifies nothing."""
    path = Path(path)
    fd, tmp = tempfile.mkstemp(
        prefix=f".{path.name}.", suffix=".tmp", dir=path.parent
    )
    try:
        with os.fdopen(fd, "w") as fh:
            json.dump(obj, fh, sort_keys=True)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(path.parent)


def _file_sha256(path: Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def build_manifest(step_dir: Path) -> Dict[str, Any]:
    """Per-file size + sha256 over everything under one committed step."""
    files: Dict[str, Dict[str, Any]] = {}
    for p in sorted(Path(step_dir).rglob("*")):
        if p.is_file():
            files[str(p.relative_to(step_dir))] = {
                "size": p.stat().st_size,
                "sha256": _file_sha256(p),
            }
    return {
        "manifest_version": 1,
        "wall_time_s": round(time.time(), 3),
        "n_files": len(files),
        "files": files,
    }


def verify_manifest(step_dir: Path, manifest: Dict[str, Any]) -> List[str]:
    """Mismatches between a step dir and its manifest; empty = verified.
    Extra files are tolerated (Orbax layouts grow metadata); a missing,
    resized, or checksum-failed manifested file is corruption."""
    errs: List[str] = []
    step_dir = Path(step_dir)
    for rel, meta in manifest.get("files", {}).items():
        p = step_dir / rel
        if not p.is_file():
            errs.append(f"{rel}: missing")
            continue
        size = p.stat().st_size
        if size != meta.get("size"):
            errs.append(f"{rel}: size {size} != manifest {meta.get('size')}")
            continue
        if _file_sha256(p) != meta.get("sha256"):
            errs.append(f"{rel}: sha256 mismatch")
    return errs


# -- pure-file pod helpers (no Orbax manager: these run against PEER host
# directories, whose managers live in other processes) ----------------------


# step_valid_in_dir result cache keyed by the manifest's (mtime_ns, size)
# signature — the same staleness contract as CheckpointManager's
# _verify_cache, held at module level because the pod read side sweeps
# PEER dirs (valid_steps × peers × retained steps) on every reconcile and
# would otherwise re-sha256 multi-GB checkpoints per call. The
# manifest-absent fallback is never cached (it is one is_dir()), which
# also keeps the preemption retention poll live while an async commit is
# still landing.
_step_valid_cache: Dict[Tuple[str, int], Tuple[Tuple[int, int], bool]] = {}


def step_valid_in_dir(directory, step: int) -> bool:
    """True when `step` is safe to restore from `directory`, judged from
    files alone: a present manifest must verify bit-for-bit; an absent
    manifest falls back to the commit marker (the same contract as
    CheckpointManager.verify_step, manager-free so it can judge a PEER
    host's dir)."""
    directory = Path(directory)
    step_dir = directory / str(int(step))
    mpath = directory / f"manifest_{int(step)}.json"
    try:
        st = mpath.stat()
    except OSError:
        return step_dir.is_dir()
    sig = (st.st_mtime_ns, st.st_size)
    key = (str(directory), int(step))
    cached = _step_valid_cache.get(key)
    if cached is not None and cached[0] == sig:
        return cached[1]
    try:
        with open(mpath) as fh:
            manifest = json.load(fh)
    except (OSError, json.JSONDecodeError):
        ok = False
    else:
        ok = not verify_manifest(step_dir, manifest)
    _step_valid_cache[key] = (sig, ok)
    return ok


def quarantine_step_in_dir(directory, step: int) -> Optional[str]:
    """Move one step OUT of a host dir's step namespace (to the hidden
    `.quarantine/<step>_<ts>`, Orbax's scanner never sees it) and drop
    its manifest — the pure-file half of _quarantine_torn, callable
    against PEER dirs during pod reconciliation. Tolerant of races: N
    relaunched hosts reconcile concurrently over shared storage, and the
    sibling that moved the dir first wins (ENOENT here is success, not
    failure). Returns the quarantine path (None when already gone)."""
    directory = Path(directory)
    step = int(step)
    step_dir = directory / str(step)
    dest: Optional[Path] = None
    if step_dir.is_dir():
        qdir = directory / ".quarantine"
        try:
            qdir.mkdir(exist_ok=True)
            dest = qdir / f"{step}_{time.strftime('%Y%m%d_%H%M%S')}"
            step_dir.rename(dest)
        except OSError:
            dest = None
        if dest is None and step_dir.is_dir():
            # The rename failed with the step dir STILL IN PLACE
            # (EACCES/EBUSY on shared storage — not a sibling winning the
            # race): keep the manifest. It is the evidence that marks the
            # step invalid; dropping it would flip step_valid_in_dir's
            # absent-manifest fallback to "valid" on a known-bad step.
            return None
    try:
        (directory / f"manifest_{step}.json").unlink()
    except OSError:
        pass
    return str(dest) if dest is not None else None


class _SpanSink:
    """Writer shim for the checkpoint spans: forwards to the manager's
    metrics_writer when one is attached, else straight to the global
    flight recorder — the same no-writer fallback every other sink takes."""

    def __init__(self, mgr: "CheckpointManager"):
        self._mgr = mgr

    def write(self, rec: dict) -> None:
        from glom_tpu.tracing.flight import write_or_observe

        write_or_observe(self._mgr.metrics_writer, rec)


class CheckpointManager:
    """Thin wrapper over orbax.CheckpointManager for TrainState pytrees.

    save()/wait() are span-covered (tracing.spans.spanned:
    host_checkpoint_save / host_checkpoint_wait): with async saves the
    save() span bounds the blocking serialize-and-enqueue slice and the
    wait() span the drain — the last unattributed host-time sinks the
    ROADMAP named. Pass `metrics_writer` to land the span events in the
    run's metrics stream (train/cli.py does).

    Manifest discipline: the checksum manifest for a step can only be
    computed AFTER Orbax commits it, so async saves queue the step as
    pending and the manifest lands at the next synchronization point —
    the following save(), wait(), close(), or any read (valid_steps /
    latest_step / restore). A kill inside that window leaves a committed
    step with no manifest, which the read side accepts on Orbax's own
    commit marker (see module docstring)."""

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
        async_save: bool = True,
        metrics_writer=None,
        pod_peers: Optional[Sequence[str]] = None,
    ):
        if not HAVE_ORBAX:
            raise RuntimeError("orbax-checkpoint is not available")
        # POD MODE (docs/RESILIENCE.md, coordinated preemption):
        # `pod_peers` names the SIBLING hosts' checkpoint dirs on shared
        # storage. The read side then only hands out steps whose per-host
        # manifests are ALL valid, and a half-committed step (valid here,
        # torn or absent on a peer — the signature of an uncoordinated or
        # aborted pod save) is quarantined on EVERY host so no later
        # Orbax bookkeeping can resurrect it. None = the single-host
        # contract, bit-for-bit unchanged.
        self.pod_peers: List[Path] = [Path(p) for p in (pod_peers or [])]
        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=async_save,
        )
        self._mgr = ocp.CheckpointManager(self.directory, options=options)
        self._async = async_save
        self._pending: Set[int] = set()  # committed-manifest debt
        # Orbax managers are not reentrant and not thread-safe: every
        # manager operation rides this RLock so concurrent callers (the
        # preemption hook's worker thread vs the training loop) serialize
        # instead of corrupting the manager. The SIGTERM grace path
        # deliberately does NOT share this instance — see
        # preemption_save() below for why.
        self._op_lock = threading.RLock()
        # verify_step result cache keyed by the manifest's (mtime_ns,
        # size) signature: the resume path asks "is this step good?"
        # more than once (latest_step, then restore), and re-hashing
        # every file of every retained multi-GB step per ask would put
        # minutes of dead time into exactly the recovery path this layer
        # exists to speed up. A rewritten manifest (new signature)
        # invalidates its entry; data corruption AFTER a verified pass
        # is the accepted staleness (the same window any
        # verify-then-read has).
        self._verify_cache: Dict[int, Tuple[Tuple[int, int], bool]] = {}
        self.metrics_writer = metrics_writer
        from glom_tpu.tracing.spans import spanned

        sink = _SpanSink(self)
        self.save = spanned("host_checkpoint_save", writer=sink)(self.save)
        self.wait = spanned("host_checkpoint_wait", writer=sink)(self.wait)

    # -- manifest plumbing -------------------------------------------------

    def _manifest_path(self, step: int) -> Path:
        return self.directory / f"manifest_{int(step)}.json"

    def _step_dir(self, step: int) -> Path:
        return self.directory / str(int(step))

    def _emit_recovery(self, rec: dict) -> None:
        from glom_tpu.resilience.faults import emit_recovery

        emit_recovery(self.metrics_writer, rec)

    def _finalize_pending(self) -> None:
        """Write manifests for pending steps Orbax has committed, and
        garbage-collect manifests of steps Orbax has retired
        (max_to_keep)."""
        committed = set(self._mgr.all_steps())
        for step in sorted(self._pending & committed):
            step_dir = self._step_dir(step)
            if step_dir.is_dir():
                atomic_write_json(
                    self._manifest_path(step), build_manifest(step_dir)
                )
            self._pending.discard(step)
        for p in self.directory.glob("manifest_*.json"):
            try:
                step = int(p.stem.split("_", 1)[1])
            except ValueError:
                continue
            if step not in committed and step not in self._pending:
                try:
                    p.unlink()
                except OSError:
                    pass

    def _quarantine_torn(self, step: int) -> Optional[str]:
        """Move a torn step OUT of Orbax's step namespace and reconcile
        the manager's bookkeeping. Skipping a torn step on restore is not
        enough on its own: the torn dir still reads as the latest step,
        so Orbax DECLINES (should_save False) every later save at or
        below it and the retrained state would never persist — resume
        would re-train the same span forever. The corrupt bytes are
        preserved under .quarantine/<step>_<ts> for postmortems — a
        HIDDEN dir, because Orbax's step scanner raises on any visible
        non-step directory name in the root (measured on 0.7) — and the
        manifest + verify-cache entry drop with the step. Returns the
        quarantine path (None when the dir was already gone)."""
        step = int(step)
        step_dir = self._step_dir(step)
        dest: Optional[Path] = None
        if step_dir.is_dir():
            qdir = self.directory / ".quarantine"
            try:
                qdir.mkdir(exist_ok=True)
                dest = qdir / f"{step}_{time.strftime('%Y%m%d_%H%M%S')}"
                step_dir.rename(dest)
            except OSError:
                dest = None
        try:
            # Reconciles Orbax's internal step list; warns (dir already
            # moved) but updates the bookkeeping either way.
            self._mgr.delete(step)
        except Exception:  # noqa: BLE001 — bookkeeping-only, best effort
            pass
        try:
            self._manifest_path(step).unlink()
        except OSError:
            pass
        self._verify_cache.pop(step, None)
        return str(dest) if dest is not None else None

    def verify_step(self, step: int) -> bool:
        """True when `step` is safe to restore: a present manifest must
        verify bit-for-bit; an absent manifest falls back to Orbax's
        commit marker (legacy step, or a kill between commit and manifest
        write)."""
        with self._op_lock:
            mpath = self._manifest_path(step)
            try:
                st = mpath.stat()
            except OSError:
                # No manifest: Orbax's commit IS the atomic rename from
                # the tmp dir to the final step dir, so existence of the
                # step dir is the commit marker — read from the
                # FILESYSTEM, not Orbax's step-list cache, which goes
                # stale exactly when the preemption path races a
                # concurrent background commit.
                self._verify_cache.pop(int(step), None)
                return self._step_dir(step).is_dir()
            sig = (st.st_mtime_ns, st.st_size)
            cached = self._verify_cache.get(int(step))
            if cached is not None and cached[0] == sig:
                return cached[1]
            try:
                with open(mpath) as fh:
                    manifest = json.load(fh)
            except (OSError, json.JSONDecodeError):
                # A torn manifest cannot certify its step
                # (atomic_write_json makes this unreachable for OUR
                # writes; a foreign/corrupt file still must not crash the
                # read side).
                ok = False
            else:
                ok = not verify_manifest(self._step_dir(step), manifest)
            self._verify_cache[int(step)] = (sig, ok)
            return ok

    def valid_steps(self) -> List[int]:
        """Ascending steps that pass verification — the only steps the
        restore path will ever hand out. In pod mode a step must verify
        on EVERY host (here by manifest, on peers by the pure-file
        check): latest_step() then reports the newest COMMON step, which
        is what a gang resume must agree on."""
        with self._op_lock:
            self._mgr.wait_until_finished()
            self._finalize_pending()
            return [
                s
                for s in sorted(self._mgr.all_steps())
                if self.verify_step(s)
                and all(step_valid_in_dir(p, s) for p in self.pod_peers)
            ]

    # -- save / restore ----------------------------------------------------

    def save(self, step: int, state: Any, *, levels: Optional[Any] = None) -> bool:
        """Save state (+ optional carried temporal `levels`) at `step`."""
        with self._op_lock:
            # Settle the PREVIOUS async save first: its manifest debt can
            # only be paid once Orbax commits, and back-to-back saves are
            # the one place that is guaranteed (Orbax serializes them
            # anyway).
            self._mgr.wait_until_finished()
            self._finalize_pending()
            items = {"state": ocp.args.StandardSave(state)}
            if levels is not None:
                items["levels"] = ocp.args.StandardSave(levels)
            saved = self._mgr.save(step, args=ocp.args.Composite(**items))
            if saved:
                self._pending.add(int(step))
                if not self._async:
                    self._mgr.wait_until_finished()
                    self._finalize_pending()
            return saved

    def restore(
        self,
        step: Optional[int] = None,
        *,
        abstract_state: Any,
        abstract_levels: Optional[Any] = None,
    ):
        """Restore the latest VALID (or a specific) step.

        abstract_state: jax.eval_shape-style pytree of ShapeDtypeStruct,
        optionally with .sharding set — restored arrays land directly in
        that sharding (no host bounce), which is what makes multi-host
        resume work.

        step=None walks the valid steps newest-first: a step that fails
        verification — or that verifies (no manifest) but still blows up
        inside Orbax deserialization — is skipped with a stamped
        "recovery" event and the previous one restores; the recovery loop
        never dies on a torn file. An EXPLICIT step that fails
        verification raises CheckpointCorruptError instead.

        POD MODE (`pod_peers=`): step=None additionally requires the
        candidate to be valid on every peer host dir; a half-committed
        step is quarantined on EVERY host (stamped
        "quarantine-half-step") and the walk falls back to the newest
        common step — the reconciled step a relaunched gang agrees on.
        Returns (step, state) or (step, (state, levels)).
        """
        with self._op_lock:
            return self._restore_locked(step, abstract_state, abstract_levels)

    def _restore_locked(self, step, abstract_state, abstract_levels):
        self._mgr.wait_until_finished()
        self._finalize_pending()
        if step is not None:
            if not self.verify_step(step):
                raise CheckpointCorruptError(
                    f"checkpoint step {step} in {self.directory} failed "
                    "manifest verification (torn or corrupted)"
                )
            candidates = [int(step)]
        else:
            # LAZY walk: verify per candidate inside the loop, newest
            # first — the common resume touches only the newest step's
            # hashes instead of sweeping every retained multi-GB step
            # up front (the recovery path must not spend minutes
            # re-verifying checkpoints it will never restore).
            candidates = sorted(self._mgr.all_steps(), reverse=True)
        last_exc: Optional[BaseException] = None
        for s in candidates:
            if step is None and not self.verify_step(s):
                rec = {
                    "action": "skip-torn-checkpoint",
                    "step": int(s),
                    "note": "manifest verification failed",
                    "quarantined": self._quarantine_torn(s),
                }
                if self.pod_peers:
                    # A step torn HERE is a half-committed step for the
                    # whole pod: the peers' (possibly pristine) copies
                    # must go with it, or their next resume lands on a
                    # step this host no longer has.
                    rec["peer_quarantined"] = {
                        str(p): quarantine_step_in_dir(p, s)
                        for p in self.pod_peers
                    }
                self._emit_recovery(rec)
                continue
            if step is None and self.pod_peers:
                invalid = [
                    str(p)
                    for p in self.pod_peers
                    if not step_valid_in_dir(p, s)
                ]
                if invalid:
                    # Half-committed pod step: valid here, torn or absent
                    # on a peer — quarantine it on EVERY host (the
                    # multi-host twin of the torn-step path: keeping any
                    # copy would let that host's Orbax bookkeeping hold
                    # the latest-step slot at a step the pod cannot
                    # agree on) and fall back to the previous candidate.
                    self._emit_recovery(
                        {
                            "action": "quarantine-half-step",
                            "step": int(s),
                            "invalid_hosts": invalid,
                            "quarantined": {
                                "self": self._quarantine_torn(s),
                                **{
                                    str(p): quarantine_step_in_dir(p, s)
                                    for p in self.pod_peers
                                },
                            },
                        }
                    )
                    continue
            items = {"state": ocp.args.StandardRestore(abstract_state)}
            if abstract_levels is not None:
                items["levels"] = ocp.args.StandardRestore(abstract_levels)
            try:
                restored = self._mgr.restore(s, args=ocp.args.Composite(**items))
            except Exception as e:  # noqa: BLE001 — any torn step skips
                if step is not None:
                    raise
                last_exc = e
                self._emit_recovery(
                    {
                        "action": "skip-torn-checkpoint",
                        "step": s,
                        "note": f"{type(e).__name__}: {e}"[:300],
                        "quarantined": self._quarantine_torn(s),
                    }
                )
                continue
            if abstract_levels is not None:
                return s, (restored["state"], restored["levels"])
            return s, restored["state"]
        if last_exc is not None:
            raise FileNotFoundError(
                f"no restorable checkpoint in {self.directory} (every "
                f"candidate failed; last: {last_exc})"
            )
        raise FileNotFoundError(f"no checkpoint found in {self.directory}")

    def latest_step(self) -> Optional[int]:
        """Newest VERIFIED step (None when nothing valid exists) — a torn
        newest checkpoint yields the previous one, not a crash."""
        steps = self.valid_steps()
        return steps[-1] if steps else None

    def all_steps(self):
        with self._op_lock:
            return self._mgr.all_steps()

    def wait(self):
        """Block until any in-flight async save lands (and pay its
        manifest debt)."""
        with self._op_lock:
            self._mgr.wait_until_finished()
            self._finalize_pending()

    def close(self):
        with self._op_lock:
            self._mgr.wait_until_finished()
            self._finalize_pending()
            self._mgr.close()


def preemption_save(
    checkpoint_dir, state: Any, step: int, *, metrics_writer=None
) -> int:
    """THE SIGTERM grace-window save (tracing/flight.set_checkpoint_hook
    plugs this in via a closure over the live trainer): save `state` at
    `step` through a THROWAWAY sync manager, not the training loop's —
    the signal handler pauses the main thread wherever it was, possibly
    inside the loop manager's save holding its op lock, and a paused
    owner never releases (measured deadlock, not theory). Orbax per-step
    dirs + atomic commit make two managers safe side by side; a same-step
    race with the loop's async background commit that still lands the
    step counts as SUCCESS (the state is on disk — whose write won is
    irrelevant). Returns the step; raises when no save landed (the hook
    stamps the failure on the recovery record)."""
    mgr = CheckpointManager(
        checkpoint_dir, async_save=False, metrics_writer=metrics_writer
    )
    try:
        if mgr.verify_step(step):
            return step  # already committed (e.g. the loop's save)
        try:
            saved = mgr.save(step, state)
        except Exception:
            if not mgr.verify_step(step):
                raise
            saved = True
        if not saved and not mgr.verify_step(step):
            # Orbax DECLINED the save (a later — possibly torn — step
            # owns the latest-step slot) and nothing committed: that is
            # a failure the recovery record must carry, never a silent
            # ok=true pointing at a step that does not exist.
            raise RuntimeError(
                f"orbax declined the save for step {step} and no "
                "committed step exists (a torn later step may own the "
                "step namespace)"
            )
        return step
    finally:
        mgr.close()


def abstract_like(tree: Any) -> Any:
    """Shape/dtype skeleton of a pytree (for restore targets)."""
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype), tree
    )
