"""Chain-timing helpers shared by the bench harnesses (bench*.py).

The only reliable sync on the tunneled TPU platform is fetching a
device-side-reduced scalar to host (`block_until_ready` returns early), and
every fetch pays a large fixed dispatch+RTT cost (~100 ms) that is not
device throughput. So all benches time K ops chained inside one compiled
fori_loop (a data-dependent carry serializes iterations so the compiler
cannot dedup/overlap/hoist them) and compute

    per_op = (t_chain - t_rtt) / K

with ONE long chain carrying ~seconds of device work and t_rtt measured on
a trivial jitted scalar. A two-chain slope, (t_long - t_short) / dK, was
tried and REJECTED: the chains run at different clock-ramp states and the
slope attributes the ramp to fixed cost — it read 5-25% above the physical
matmul-bound floor (audited against a pure-matmul probe that pinned the
chip's achievable bf16 peak at 196.6 TF/s). The long-chain form can only
over-credit by rtt-jitter / t_chain, ~2% at a 1+ s chain.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp


def best_fetch_time(fn, *args, repeats: int = 6) -> float:
    """Min wall time of `float(fn(*args))` over `repeats`, after a warm
    (compile) call. `fn` must return a scalar; fetching it to host is the
    sync. Min, not mean: jitter and throttling only ever slow things down,
    and a finiteness check on every fetch catches silent NaNs."""
    warm = float(fn(*args))
    if not jnp.isfinite(warm):
        raise RuntimeError(f"non-finite benchmark output: {warm}")
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = float(fn(*args))
        times.append(time.perf_counter() - t0)
        if not jnp.isfinite(out):
            raise RuntimeError(f"non-finite benchmark output: {out}")
    return min(times)


def measure_rtt(example, repeats: int = 6) -> float:
    """Fixed dispatch+fetch cost of one call: time a trivial jitted scalar
    derived from `example` (kept data-dependent so nothing constant-folds
    the round trip away)."""
    return best_fetch_time(
        jax.jit(lambda x: jnp.sum(x) * 1e-30 + 1.0), example, repeats=repeats
    )


def calibrated_chain_time(
    chain,
    rtt_example,
    *,
    repeats: int = 6,
    calib_k: int = 32,
    target_s: float = 1.0,
    max_k: int = 50_000,
) -> float:
    """Per-iteration time of `chain(k) -> scalar` (k a traced fori_loop
    bound, so ONE jit serves every k). For ops whose cost spans µs..ms the
    chain length must adapt: first estimate per-op cost from a short
    calibration chain, then size k to put ~target_s of device work in the
    measured chain, and return (t_chain - rtt) / k.

    `rtt_example`: a device-resident array the RTT probe reads. RTT is
    re-measured HERE, immediately before the measured chain — a stale RTT
    taken minutes earlier would re-introduce drift the subtraction exists
    to cancel. target_s=1.0 keeps the rtt-jitter error bound at ~2%."""

    def best(k):
        return best_fetch_time(chain, jnp.int32(k), repeats=repeats)

    rtt0 = measure_rtt(rtt_example, repeats=repeats)
    t_calib = best(calib_k)
    per_est = max((t_calib - rtt0) / calib_k, 1e-7)
    k = int(min(max(target_s / per_est, calib_k), max_k))
    rtt = measure_rtt(rtt_example, repeats=repeats)
    per = (best(k) - rtt) / k
    if per <= 0:
        raise RuntimeError(f"degenerate chain timing: k={k} rtt={rtt:.4f}")
    return per
