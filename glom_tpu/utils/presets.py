"""The five driver benchmark configurations (BASELINE.md) as named presets.

Each preset bundles the model config, a training config, and the mesh /
SP strategy the config was designed to exercise. Mesh sizes here describe
the TARGET topology; `scaled_to(num_devices)` shrinks the mesh to whatever
is actually available (e.g. the 8-device CPU test harness or one chip).
"""

from __future__ import annotations

import dataclasses
from typing import Dict

from glom_tpu.utils.config import GlomConfig, MeshConfig, ServeConfig, TrainConfig
from glom_tpu.utils.helpers import halo_supported


@dataclasses.dataclass(frozen=True)
class Preset:
    name: str
    description: str
    model: GlomConfig
    train: TrainConfig
    mesh: MeshConfig
    sp_strategy: str = "none"  # none | ring | ulysses | halo | auto
    # Serving policy (glom_tpu/serve): the bucket ladder the engine
    # precompiles and the batcher's admission knobs. The default suits the
    # small correctness configs; the throughput presets override it.
    serve: ServeConfig = ServeConfig()

    def scaled_to(self, num_devices: int) -> "Preset":
        """Shrink the mesh to fit `num_devices`. Data parallelism is the
        elastic axis — shrink it FIRST so the structurally interesting
        axes (seq sharding, the TP hidden split) survive on small device
        counts; a scaled-down pod preset still exercises its declared
        data x seq x model composition. Divisibility is preserved: halving
        an axis keeps batch % data == 0 and num_patches % seq == 0."""
        data, seq, model = self.mesh.data, self.mesh.seq, self.mesh.model
        while data * seq * model > num_devices and data > 1:
            data //= 2
        while data * seq * model > num_devices and seq > 1:
            seq //= 2
        while data * seq * model > num_devices and model > 1:
            model //= 2
        # A scaled-down mesh is a single-slice deployment (the virtual test
        # harness, or one real slice): the multi-slice DCN split only
        # describes the full-size topology, so collapse it when shrinking.
        shrunk = (data, seq, model) != self.mesh.shape
        ns = 1 if shrunk else self.mesh.num_slices
        mesh = MeshConfig(data=data, seq=seq, model=model, num_slices=ns)
        sp = self.sp_strategy if mesh.seq > 1 else "none"
        if sp == "halo" and not halo_supported(
            mesh.seq, self.model.num_patches_side, self.model.local_consensus_radius
        ):
            # Shrinking the mesh can break halo's one-hop precondition
            # (fewer rows per shard); ring is exact for any radius.
            sp = "ring"
        return dataclasses.replace(self, mesh=mesh, sp_strategy=sp)


PRESETS: Dict[str, Preset] = {}


def _register(p: Preset) -> Preset:
    PRESETS[p.name] = p
    return p


# 1. MNIST 28x28, patch=7, levels=4, dim=128 — forward denoise (CPU ref)
_register(
    Preset(
        name="mnist",
        description="MNIST 28x28 p7 L4 d128 — correctness reference",
        model=GlomConfig(dim=128, levels=4, image_size=28, patch_size=7),
        train=TrainConfig(batch_size=32, learning_rate=3e-4, noise_std=0.5),
        mesh=MeshConfig(),
    )
)

# 2. CIFAR-10 32x32, patch=4, levels=5, dim=256 — denoise training
_register(
    Preset(
        name="cifar10",
        description="CIFAR-10 32x32 p4 L5 d256 — self-supervised denoise train",
        model=GlomConfig(dim=256, levels=5, image_size=32, patch_size=4),
        train=TrainConfig(
            batch_size=64, learning_rate=3e-4, noise_std=0.5,
            compute_dtype="bfloat16", use_pallas=True, scan_unroll=True,
        ),
        mesh=MeshConfig(),
    )
)

# 3. ImageNet-64, patch=8, levels=6, dim=512, local consensus window=7.
# The 8x8 patch grid sharded seq=2 holds 4 rows per shard < floor(radius)=7,
# so the one-hop halo precondition can NEVER hold for this geometry (and at
# radius 7 on side 8 the mask barely masks anyway) — an exact GLOBAL SP
# form must stand in; which one is the selector's call (see sp_strategy
# below). See `imagenet256-local` for the config where halo actually pays.
_register(
    Preset(
        name="imagenet64-local",
        description="ImageNet-64 p8 L6 d512 radius7 — local-mask path",
        model=GlomConfig(
            dim=512, levels=6, image_size=64, patch_size=8, local_consensus_radius=7
        ),
        train=TrainConfig(
            batch_size=64, learning_rate=3e-4, noise_std=0.5,
            compute_dtype="bfloat16", use_pallas=True, scan_unroll=True,
        ),
        mesh=MeshConfig(data=4, seq=2),
        # intent: local consensus. 'auto' resolves the mechanism: side 8 /
        # seq 2 gives 4 rows per shard < radius 7, so halo is geometrically
        # impossible; the selector then applies the global crossover and
        # picks ULYSSES (L=6 divides seq=2, n=64 < 2048 — the small-n
        # regime it measured fastest; the local mask rides along exactly).
        sp_strategy="auto",
    )
)

# 3b. Long-context local-consensus config where the halo path pays: a 32x32
# patch grid (n=1024) with radius 7 sharded seq=4 gives 8 rows per shard
# >= 7 halo rows, so each shard exchanges one ~22%-of-n halo with each
# neighbor instead of ring-rotating the full k/v — O(r*side) comms, not O(n).
_register(
    Preset(
        name="imagenet256-local",
        description="ImageNet-256 p8 L6 d512 radius7 — halo-exchange long-context",
        model=GlomConfig(
            dim=512, levels=6, image_size=256, patch_size=8, local_consensus_radius=7
        ),
        train=TrainConfig(
            batch_size=32, learning_rate=3e-4, noise_std=0.5,
            compute_dtype="bfloat16", use_pallas=True, scan_unroll=True,
        ),
        mesh=MeshConfig(data=2, seq=4),
        # intent: local consensus. side 32 / seq 4 = 8 rows per shard >=
        # radius 7, so 'auto' resolves to halo (one-hop neighbor exchange).
        sp_strategy="auto",
    )
)

# 4. ImageNet-224, patch=14, levels=6, dim=512 — data-parallel v5e-8
_register(
    Preset(
        name="imagenet224-dp8",
        description="ImageNet-224 p14 L6 d512 — DP over a v5e-8 slice",
        model=GlomConfig(dim=512, levels=6, image_size=224, patch_size=14),
        train=TrainConfig(
            batch_size=64, learning_rate=3e-4, noise_std=0.5,
            compute_dtype="bfloat16", use_pallas=True, scan_unroll=True,
        ),
        mesh=MeshConfig(data=8),
        # The flagship serving config: bf16 fused forward, a deeper bucket
        # ladder (heavy traffic fills big buckets; the small ones cover the
        # tail), and TWO-TIER consensus early exit — a bucket exits when
        # its fastest three-quarters quorum converges, stragglers
        # re-bucket through the continuation queue with their remaining
        # budget (docs/SERVING.md). Streaming: 1 GiB of HBM buys ~680
        # concurrent warm sessions (column_state_bytes = 256 patches x 6
        # levels x 512 dim x bf16 ~= 1.5 MiB/stream); a stream quiet for
        # a minute cold-starts its next frame. Dead engines re-admit
        # after 3 clean probation dispatches.
        serve=ServeConfig(
            buckets=(1, 2, 4, 8, 16),
            max_batch=16,
            max_delay_ms=3.0,
            queue_depth=256,
            iters="auto",
            exit_threshold=1e-3,
            min_iters=4,
            exit_quorum=0.75,
            max_continuations=2,
            compute_dtype="bfloat16",
            use_pallas=True,
            column_cache_bytes=1 << 30,
            column_cache_ttl_s=60.0,
            rejoin_threshold=3,
            # Paged column memory (docs/SERVING.md): the 1 GiB cache
            # budget lives in a device page pool — 2728 pages x 64
            # tokens x 6 levels x 512 dim x bf16 = 384 KiB/page (~682
            # full-resolution streams at 4 pages each), warm frames
            # assembled in-graph with ZERO host->device levels0 bytes.
            # Ragged admission stays a workload opt-in (bench_serve.py
            # --ragged / --banded-ab; it composes with the continuation
            # queue on the auto route — stragglers re-enter ragged with
            # their remaining budget). When opted in, the BANDED
            # consensus route prices the duplicated k/v working set per
            # PAGE instead of per token (64x smaller here), which is
            # what lets a 16-row ragged signature fit one chip at all;
            # aliased write-backs land pages in place instead of
            # copying the 1 GiB pool per write.
            page_pool_pages=2728,
            page_tokens=64,
            ragged_attention="banded",
            pool_aliasing=True,
        ),
    )
)

# 5. ImageNet-224, patch=14, levels=12, dim=1024 — pod-scale v5e-256, remat.
# Laid out as 4 DCN-connected slices of 64 chips: the 64-way data axis
# factors into 4 (outer, DCN) x 16 (inner, ICI); seq/model ride ICI inside
# a slice. XLA decomposes the gradient allreduce hierarchically from the
# hybrid device placement (parallel/mesh.py).
_register(
    Preset(
        name="imagenet224-pod",
        description="ImageNet-224 p14 L12 d1024 — v5e-256 pod (4 DCN slices), remat",
        model=GlomConfig(dim=1024, levels=12, image_size=224, patch_size=14),
        train=TrainConfig(
            batch_size=256,
            learning_rate=3e-4,
            noise_std=0.5,
            compute_dtype="bfloat16",
            # use_pallas rides the manual shard_map path, which composes the
            # fused kernels with the declared data x seq x model mesh: the
            # TP (model=2) hidden split is a hand-written Megatron psum in
            # parallel/manual.py, per-rank f/mp = 2048 stays MXU-tileable.
            # scan_unroll stays off: remat + unroll defeat each other.
            use_pallas=True,
            remat=True,
        ),
        mesh=MeshConfig(data=64, seq=2, model=2, num_slices=4),
        # intent: global consensus at n=256. 'auto' resolves to Ulysses
        # (L=12 divides seq=2; measured 1.46x over ring at n=256/seq=2 —
        # results/sp_crossover.jsonl).
        sp_strategy="auto",
        # Pod-scale serving: each engine replica is an 8-chip (data=4 x
        # seq=2) serve mesh (parallel/serve_mesh.py) — the d=1024/L=12
        # model batched 32-deep does not serve interactively on one chip.
        # Buckets divide by mesh_data=4; a v5e-256 pod fans out 32 such
        # replicas behind shared admission (runtime.make_engine_meshes).
        serve=ServeConfig(
            buckets=(4, 8, 16, 32),
            max_batch=32,
            max_delay_ms=5.0,
            queue_depth=512,
            iters="auto",
            exit_threshold=1e-3,
            min_iters=4,
            exit_quorum=0.75,
            max_continuations=2,
            mesh_data=4,
            mesh_seq=2,
            compute_dtype="bfloat16",
            # Streaming at pod scale: 2 GiB/replica of column cache
            # (d=1024/L=12 columns cost ~6 MiB/stream -> ~340 streams per
            # 8-chip replica, 32 replicas behind shared admission), and
            # probation rejoin so a recovered replica re-enters the
            # fan-out without a restart (docs/RESILIENCE.md).
            column_cache_bytes=2 << 30,
            column_cache_ttl_s=60.0,
            rejoin_threshold=3,
            # Paged pool per 8-chip replica: the page axis shards over
            # 'data' (pages % mesh_data == 0 — 341 pages/chip), and the
            # paged warm signature gathers it with one registered
            # all_gather (parallel/serve_mesh.py). 1364 pages x 64
            # tokens x 12 levels x 1024 dim x bf16 = 1.5 MiB/page ->
            # ~341 full-res streams resident per replica.
            page_pool_pages=1364,
            page_tokens=64,
        ),
    )
)


def get_preset(name: str) -> Preset:
    if name not in PRESETS:
        raise KeyError(f"unknown preset {name!r}; available: {sorted(PRESETS)}")
    return PRESETS[name]
