"""Small functional helpers shared across the framework.

Reference parity: `exists` / `default` mirror the null-coalescing helpers in the
reference (glom_pytorch/glom_pytorch.py:13-17) used for the optional `iters` /
`levels` forward arguments.
"""

from __future__ import annotations

import math

import jax.numpy as jnp

# The *soft* self-attention penalty used by consensus attention when
# attend_self=False. Deliberately NOT -inf: columns attend weakly to
# themselves. (reference: glom_pytorch/glom_pytorch.py:9)
TOKEN_ATTEND_SELF_VALUE = -5e-4


def exists(val):
    return val is not None


def default(val, d):
    return val if exists(val) else d


def l2norm(x: jnp.ndarray, axis: int = -1, eps: float = 1e-12) -> jnp.ndarray:
    """L2-normalize along `axis`, matching torch.nn.functional.normalize:
    x / max(||x||_2, eps).
    """
    norm = jnp.linalg.norm(x, ord=2, axis=axis, keepdims=True)
    return x / jnp.maximum(norm, eps)


def halo_supported(seq: int, side: int, radius: float) -> bool:
    """True when one-hop halo-exchange consensus is valid for `seq`-way
    row-band sharding of a side x side patch grid with the given local
    radius: the halo a shard needs from each neighbor (floor(radius) grid
    rows — integer grid distances, so a patch within Euclidean radius r is
    at most floor(r) rows away) must fit inside one neighboring shard.

    Pure geometry — lives here (a leaf module) so config/preset code can
    check it without importing the parallel runtime. parallel.halo validates
    against this same predicate; ring consensus is the exact fallback for
    any geometry where this is False.
    """
    if radius <= 0 or side % seq != 0:
        return False
    return (side // seq) >= math.floor(radius)


def max_neg_value(dtype) -> float:
    """The -finfo.max fill used for the *hard* (local-radius) attention mask.

    Distinct from TOKEN_ATTEND_SELF_VALUE — the reference uses two different
    mask semantics in one attention op (soft self-penalty vs hard locality
    cutoff). (reference: glom_pytorch/glom_pytorch.py:63-67)
    """
    return -jnp.finfo(dtype).max
