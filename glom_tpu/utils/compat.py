"""JAX version-compatibility shims — the ONE module that owns them.

The codebase is written against the current jax API line (jax.shard_map,
vma-typed arrays via jax.typeof/lax.pcast, pltpu.CompilerParams); CI pins
that line. Some execution images ship the older 0.4.x line where those
names do not exist (shard_map lives in jax.experimental, check_vma is
spelled check_rep, there is no vma type system at all, and the Pallas
compiler-params class is TPUCompilerParams). Every call site routes
through here so the rest of the tree reads as current-API code and the
fallbacks live in exactly one place.
"""

from __future__ import annotations

import jax
from jax import lax

_HAS_NEW_SHARD_MAP = hasattr(jax, "shard_map")
if not _HAS_NEW_SHARD_MAP:  # pragma: no cover - exercised only on old jax
    from jax.experimental.shard_map import shard_map as _old_shard_map

# Partial-manual shard_map (manual over one axis, auto over the rest)
# nested inside a GSPMD-sharded jit is only sound on the current jax line:
# the 0.4.x lowering emits a PartitionId instruction the SPMD partitioner
# rejects ("meaning is ambiguous") whenever an auto axis is real (>1).
# Callers that would build that composition route to the fully-manual
# region instead when this is False.
HAS_PARTIAL_MANUAL = _HAS_NEW_SHARD_MAP


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True,
              axis_names=None):
    """jax.shard_map, with check_vma mapped to the old check_rep kwarg and
    the partial-manual axis_names set mapped to the old complementary
    `auto` set (where the rep checker must be off — it predates partial
    manual and rejects it)."""
    if _HAS_NEW_SHARD_MAP:
        kw = {} if axis_names is None else {"axis_names": axis_names}
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma, **kw,
        )
    kw = {}
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        if auto:
            kw["auto"] = auto
            check_vma = False
    return _old_shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma, **kw,
    )


def axis_size(axis_name: str) -> int:
    """lax.axis_size(name) inside a manual region; the old line spells it
    jax.core.axis_frame(name).size (still a static python int)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    import jax.core as jcore  # pragma: no cover - old jax

    frame = jcore.axis_frame(axis_name)
    return frame if isinstance(frame, int) else frame.size


def array_vma(x) -> tuple:
    """tuple(jax.typeof(x).vma); () where the vma type system doesn't
    exist (old jax, or check_vma=False regions — both need no pcast)."""
    try:
        return tuple(jax.typeof(x).vma)
    except AttributeError:
        return ()


def pcast_varying(x, vma: tuple):
    """lax.pcast(x, vma, to='varying'); identity when vma is empty or
    pcast is unavailable (no vma checker to satisfy in either case)."""
    if not vma or not hasattr(lax, "pcast"):
        return x
    return lax.pcast(x, vma, to="varying")


def install_pallas_tpu_compat() -> None:
    """Alias pltpu.CompilerParams to the old TPUCompilerParams name when
    only the latter exists. Import-time no-op on current jax."""
    from jax.experimental.pallas import tpu as pltpu

    if not hasattr(pltpu, "CompilerParams"):  # pragma: no cover - old jax
        pltpu.CompilerParams = pltpu.TPUCompilerParams
