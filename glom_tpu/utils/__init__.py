from glom_tpu.utils.helpers import (
    TOKEN_ATTEND_SELF_VALUE,
    default,
    exists,
    l2norm,
    max_neg_value,
)

__all__ = [
    "TOKEN_ATTEND_SELF_VALUE",
    "default",
    "exists",
    "l2norm",
    "max_neg_value",
]
