"""EQuARX-style block-scaled int8 quantized all-reduce — EMULATION.

EQuARX (arXiv:2506.17615) quantizes all-reduce payloads inside XLA's
collective pipeline: each hop of the ring carries int8 blocks plus one
scale per block, dequantizing to accumulate. That lives in the compiler;
from JAX the honest reachable form is wire-emulation: block-quantize the
gradient to int8, dequantize, and hand the result to the (full-precision)
reduction collective. This models exactly ONE quantization hop — the
dominant error term of the real scheme for small replica counts — and lets
the framework measure the accuracy cost and price the 4x wire-bytes saving
(utils/metrics.comm_volume_model) before the compiler hook exists.

EXPERIMENTAL: quantization changes gradient numerics (bounded below);
gated behind TrainConfig.quantized_reduce, never on by default, and the
flag is stamped into every metrics record so no run can silently train on
quantized gradients.

Wire accounting AND wall-time: this module carries no collectives of its
own — the quantized payload rides parallel/manual.py's registered
psum_scatter sites, which price the wire at `quantized_wire_bytes` (the
int8 + scales payload the real collective would carry) through
counters.timed_collective. The capacity observatory's per-collective
wall-time therefore times the quantized schedule at its REAL f32 payload
today (the emulation dequantizes before the collective); when the
compiler hook lands the ~4x wire cut (ROADMAP item 3), the measured
wall_ms vs the α-β model's byte-derived prediction is exactly the drift
signal that will prove the cut is real on the clock, not just in the
byte counters.

Error bound (locked by tests/test_zero.py): symmetric per-block max-abs
scaling with round-to-nearest gives |x - dq(q(x))| <= max|block| / (2*127)
per element — zero blocks are exact (scale guard), and the bound is tight
at the block maximum.
"""

from __future__ import annotations

import jax.numpy as jnp

# 128 f32 elements share one f32 scale: 1/128 metadata overhead on the
# wire, and a block is small enough that one outlier only poisons 127
# neighbors' resolution (the EQuARX block-scaling argument).
DEFAULT_BLOCK = 128
INT8_MAX = 127.0


def block_quantize_int8(x: jnp.ndarray, block: int = DEFAULT_BLOCK):
    """x (any shape) -> (q int8 [nb, block], scales f32 [nb, 1], n_pad).

    Flattens, zero-pads to a whole number of blocks, and quantizes each
    block symmetrically by its max-abs. All-zero blocks get scale 1 so the
    round trip is exact (0/1 -> 0 -> 0) with no divide-by-zero."""
    flat = x.astype(jnp.float32).reshape(-1)
    n = flat.shape[0]
    n_pad = (-n) % block
    flat = jnp.pad(flat, (0, n_pad))
    blocks = flat.reshape(-1, block)
    absmax = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    scales = jnp.where(absmax > 0, absmax / INT8_MAX, 1.0)
    q = jnp.clip(jnp.round(blocks / scales), -INT8_MAX, INT8_MAX).astype(jnp.int8)
    return q, scales, n_pad


def block_dequantize_int8(q, scales, n_pad: int, shape, dtype) -> jnp.ndarray:
    flat = (q.astype(jnp.float32) * scales).reshape(-1)
    n = flat.shape[0] - n_pad
    return flat[:n].reshape(shape).astype(dtype)


def quantize_dequantize(x: jnp.ndarray, block: int = DEFAULT_BLOCK) -> jnp.ndarray:
    """One wire-quantization hop: what a tensor looks like after riding the
    quantized collective once. Applied to gradients pre-reduction when
    TrainConfig.quantized_reduce is set."""
    q, scales, n_pad = block_quantize_int8(x, block)
    return block_dequantize_int8(q, scales, n_pad, x.shape, x.dtype)


def quantized_wire_bytes(num_elements: int, block: int = DEFAULT_BLOCK) -> int:
    """Payload bytes on the wire for one quantized tensor: int8 values plus
    one f32 scale per block (vs num_elements * 4 for f32)."""
    nb = -(-num_elements // block)
    return num_elements + nb * 4
