"""Device mesh construction and multi-host initialization.

The reference has NO distributed support (SURVEY.md §2.2: no
torch.distributed / NCCL anywhere). This module is the TPU-native
communication backend: a named `Mesh` over the chip topology, with XLA
emitting collectives over ICI from sharding annotations (pjit/GSPMD) or from
explicit shard_map collectives (ring / halo / all-to-all in this package).

Axis convention (see utils.config.MeshConfig):
  data  — batch sharding (DP); gradient allreduce rides ICI (multi-slice
          setups put the outermost data axis on DCN)
  seq   — patch-axis sharding (SP): ring consensus / halo exchange
  model — dim sharding (TP) of the grouped-FFW weights
"""

from __future__ import annotations

import os
import warnings
from typing import Optional

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from glom_tpu.utils.config import MeshConfig


def make_mesh(cfg: MeshConfig, devices: Optional[list] = None) -> Mesh:
    """Build a Mesh of shape (data, seq, model) over the available devices.

    Uses mesh_utils.create_device_mesh on real TPU slices so mesh axes map
    contiguously onto the ICI torus (nearest-neighbor collectives stay on
    ICI links); falls back to a simple reshape for CPU/virtual devices.
    """
    devices = devices if devices is not None else jax.devices()
    n = cfg.num_devices
    if n > len(devices):
        raise ValueError(
            f"mesh {cfg.shape} needs {n} devices, only {len(devices)} available"
        )
    devices = devices[:n]
    if cfg.num_slices > 1:
        return _make_hybrid_mesh(cfg, devices)
    if devices[0].platform == "tpu":
        try:
            dev_array = mesh_utils.create_device_mesh(cfg.shape, devices=devices)
        except (ValueError, AssertionError):
            dev_array = np.asarray(devices).reshape(cfg.shape)
    else:
        dev_array = np.asarray(devices).reshape(cfg.shape)
    return Mesh(dev_array, cfg.axis_names)


def _make_hybrid_mesh(cfg: MeshConfig, devices: list) -> Mesh:
    """Multi-slice (ICI x DCN) mesh: the data axis factors as
    num_slices (outer, DCN) x data/num_slices (inner, ICI); seq and model
    stay intra-slice. The logical mesh keeps the plain (data, seq, model)
    axis names — hierarchy lives entirely in device placement, where XLA
    reads it to emit a reduce-scatter-on-ICI / allreduce-on-DCN
    decomposition for the gradient sync (BASELINE config 5, v5e-256 as
    multi-slice).

    On CPU/virtual devices (and TPU fallback) a slice-major reshape gives
    the same logical layout: device order is assumed slice-contiguous,
    which matches how multi-process virtual harnesses enumerate them.
    """
    s = cfg.num_slices
    ici_shape = (cfg.data // s, cfg.seq, cfg.model)
    dcn_shape = (s, 1, 1)
    if devices[0].platform == "tpu":
        try:
            dev_array = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=devices
            )
        except (ValueError, AssertionError) as e:
            # A raw reshape assumes enumeration order is slice-contiguous;
            # if it is not, seq/model collectives can land on DCN links — a
            # silent order-of-magnitude regression. Never hide this on real
            # hardware.
            warnings.warn(
                f"create_hybrid_device_mesh failed ({e}); falling back to a "
                "slice-major reshape of jax.devices() — verify the device "
                "order is slice-contiguous or intra-slice collectives may "
                "ride DCN",
                stacklevel=3,
            )
            dev_array = np.asarray(devices).reshape(cfg.shape)
    else:
        dev_array = np.asarray(devices).reshape(cfg.shape)
    return Mesh(dev_array, cfg.axis_names)


def replica_device_groups(devices: list, per_replica: int) -> list:
    """Partition `devices` into contiguous groups of `per_replica` — one
    group per serving engine replica (multi-engine fan-out,
    docs/SERVING.md). Contiguous slices keep each replica's mesh on
    neighboring ICI links (jax.devices() enumerates torus-contiguously on
    TPU); leftover devices beyond the last full group are unused rather
    than silently forming an undersized replica."""
    if per_replica < 1:
        raise ValueError(f"per_replica {per_replica} must be >= 1")
    n_groups = len(devices) // per_replica
    if n_groups < 1:
        raise ValueError(
            f"{len(devices)} devices cannot host a {per_replica}-device "
            "replica"
        )
    return [
        devices[i * per_replica : (i + 1) * per_replica]
        for i in range(n_groups)
    ]


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bring-up: the analog of torch's init_process_group, but via
    the JAX distributed runtime (coordinator + heartbeat failure detection).

    No-op on single-process. Args fall back to the standard env vars
    (JAX_COORDINATOR_ADDRESS, JAX_NUM_PROCESSES, JAX_PROCESS_ID) so launch
    scripts can stay declarative.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if coordinator_address is None:
        return  # single host
    kwargs = {"coordinator_address": coordinator_address}
    if num_processes is not None or "JAX_NUM_PROCESSES" in os.environ:
        kwargs["num_processes"] = int(
            num_processes
            if num_processes is not None
            else os.environ["JAX_NUM_PROCESSES"]
        )
    if process_id is not None or "JAX_PROCESS_ID" in os.environ:
        kwargs["process_id"] = int(
            process_id if process_id is not None else os.environ["JAX_PROCESS_ID"]
        )
    jax.distributed.initialize(**kwargs)
