"""Device mesh construction and multi-host initialization.

The reference has NO distributed support (SURVEY.md §2.2: no
torch.distributed / NCCL anywhere). This module is the TPU-native
communication backend: a named `Mesh` over the chip topology, with XLA
emitting collectives over ICI from sharding annotations (pjit/GSPMD) or from
explicit shard_map collectives (ring / halo / all-to-all in this package).

Axis convention (see utils.config.MeshConfig):
  data  — batch sharding (DP); gradient allreduce rides ICI (multi-slice
          setups put the outermost data axis on DCN)
  seq   — patch-axis sharding (SP): ring consensus / halo exchange
  model — dim sharding (TP) of the grouped-FFW weights
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from glom_tpu.utils.config import MeshConfig


def make_mesh(cfg: MeshConfig, devices: Optional[list] = None) -> Mesh:
    """Build a Mesh of shape (data, seq, model) over the available devices.

    Uses mesh_utils.create_device_mesh on real TPU slices so mesh axes map
    contiguously onto the ICI torus (nearest-neighbor collectives stay on
    ICI links); falls back to a simple reshape for CPU/virtual devices.
    """
    devices = devices if devices is not None else jax.devices()
    n = cfg.num_devices
    if n > len(devices):
        raise ValueError(
            f"mesh {cfg.shape} needs {n} devices, only {len(devices)} available"
        )
    devices = devices[:n]
    if devices[0].platform == "tpu":
        try:
            dev_array = mesh_utils.create_device_mesh(cfg.shape, devices=devices)
        except (ValueError, AssertionError):
            dev_array = np.asarray(devices).reshape(cfg.shape)
    else:
        dev_array = np.asarray(devices).reshape(cfg.shape)
    return Mesh(dev_array, cfg.axis_names)


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bring-up: the analog of torch's init_process_group, but via
    the JAX distributed runtime (coordinator + heartbeat failure detection).

    No-op on single-process. Args fall back to the standard env vars
    (JAX_COORDINATOR_ADDRESS, JAX_NUM_PROCESSES, JAX_PROCESS_ID) so launch
    scripts can stay declarative.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if coordinator_address is None:
        return  # single host
    kwargs = {"coordinator_address": coordinator_address}
    if num_processes is not None or "JAX_NUM_PROCESSES" in os.environ:
        kwargs["num_processes"] = int(
            num_processes
            if num_processes is not None
            else os.environ["JAX_NUM_PROCESSES"]
        )
    if process_id is not None or "JAX_PROCESS_ID" in os.environ:
        kwargs["process_id"] = int(
            process_id if process_id is not None else os.environ["JAX_PROCESS_ID"]
        )
    jax.distributed.initialize(**kwargs)
