"""The sharded serving forward: bucket batches over a (data, seq) mesh
with early exit legal inside the loop.

PR 4's InferenceEngine runs every bucket on one device. This module is the
multi-chip route: the SAME bucket/AOT-warmup/donation discipline, but the
forward is one manual `shard_map` over ('data', 'seq') — batch rows
sharded over 'data', the patch axis over 'seq' — so a bucket too big (or a
model too slow) for one chip serves across a slice. The structural
constraint the training path never had: the consensus-attention and
witness collectives must be legal INSIDE the `iters="auto"`
`lax.while_loop` body, whose trip count is data-dependent. They are —
shard_map collectives trace like any other op in a while body (every shard
runs the same loop, and the exit decision is itself a psum, so all shards
agree on every trip) — but each one is a wire-moving site the measured
collective counters must price, hence every psum here sits in a
`record_collective`-calling function and this module is registered with
glom-lint's collective-coverage checker (analysis/core.py
registration_modules).

Witness decomposition over 'seq': per-row agreement needs the mean over
the FULL patch axis, so the per-shard partial sums psum over 'seq' (two
[b_loc, ...] f32 hops per iteration); the quorum count psums its int32
scalar over 'data'. With seq == 1 the witness is computed by the exact
single-device `batch_agreement` reduction — no collective, and the
data-sharded forward is row-for-row the same program as the single-device
engine (the threshold-0 parity test in tests/test_serve_mesh.py holds
BITWISE on the CPU mesh).

Pricing convention: while_loop bodies trace once but execute up to the
static budget, so the engine's counting trace wraps the loop in
`counters.scaled(max_iters)` — the recorded bytes price the BUDGET (the
bound the wire must provision for), not the data-dependent realized trip
count. The fixed route's scan prices per execution the same way the
training scans do.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from glom_tpu.models.core import contribution_divisor, update_step
from glom_tpu.ops.patch import image_to_tokens
from glom_tpu.parallel.manual import shard_consensus_fn
from glom_tpu.parallel.mesh import make_mesh
from glom_tpu.telemetry import counters as tele_counters
from glom_tpu.utils.compat import shard_map
from glom_tpu.utils.config import GlomConfig, MeshConfig, ServeConfig

# Module-level axis constants (the *_AXIS vocabulary glom-lint's
# collective checker resolves statically): same names, same meaning as
# parallel/manual.py's training mesh.
DATA_AXIS = "data"
SEQ_AXIS = "seq"


def make_serve_mesh(scfg: ServeConfig, devices: Optional[list] = None):
    """The engine's mesh, or None for the single-device route. Axis names
    reuse the training vocabulary ('data', 'seq') so the collective
    counters, glom-lint's axis vocabulary, and the docs all speak one
    language; 'model' stays 1 — serve-side TP is ROADMAP item 3's seam."""
    if scfg.mesh_data == 1 and scfg.mesh_seq == 1:
        return None
    return make_mesh(
        MeshConfig(data=scfg.mesh_data, seq=scfg.mesh_seq), devices
    )


def serve_shardings(mesh, params, *, warm: bool = False, paged: bool = False):
    """(in_shardings, out_shardings) for one sharded bucket signature:
    params replicated, the image batch and validity mask sharded over
    'data', a warm levels carry over ('data', 'seq') — or, on the PAGED
    route, the pool buffer sharded on its PAGE axis over 'data' plus the
    replicated page-index map; outputs mirror the forward's (levels,
    iters_run, row_converged, row_iters) contract. Spec resolution lives
    HERE (one place) so the engine's AOT compile and its per-attempt
    device_put can never disagree about layout."""
    if warm and paged:
        raise ValueError("warm (host levels0) and paged are exclusive")
    rep = NamedSharding(mesh, P())
    batch = NamedSharding(mesh, P(DATA_AXIS))
    rows = NamedSharding(mesh, P(DATA_AXIS))
    lv = NamedSharding(mesh, P(DATA_AXIS, SEQ_AXIS))
    pool_sh = NamedSharding(mesh, P(DATA_AXIS))
    param_sh = jax.tree_util.tree_map(lambda _: rep, params)
    in_sh = (param_sh, batch, rows)
    if warm:
        in_sh = in_sh + (lv,)
    elif paged:
        in_sh = in_sh + (pool_sh, rep)
    out_sh = (lv, rep, rows, rows)
    return in_sh, out_sh


def _psum_wire(x, axis_name: str, k: int, site: str = "serve_psum"):
    """A registered allreduce: the one wrapper every wire-moving psum in
    this module goes through, so the measured counters (and glom-lint's
    coverage rule) see each site. `site` names the call site for the
    per-collective wall-time harness (counters.timed_collective — the
    capacity observatory's timing seam; distinct witness/quorum sites
    stamp distinct collective_time rows)."""
    return tele_counters.timed_collective(
        site, axis_name, "reduce",
        tele_counters.ring_allreduce_bytes(x, k),
        lambda v: lax.psum(v, axis_name), x, collective="psum",
    )


def _gather_pages_wire(pool_loc, k: int):
    """The WHOLE-POOL page gather (docs/SERVING.md, "Paged column
    memory"): the pool buffer shards its page axis over 'data', and a
    paged warm dispatch materializes the full pool per shard with one
    registered all_gather before the page-index take. Wire is priced at
    the whole pool shard ((k-1) x local bytes — the provisioning bound;
    ServeConfig.page_gather picks this or the needed-pages exchange)."""
    return tele_counters.timed_collective(
        "page_pool_all_gather", DATA_AXIS, "gather",
        tele_counters.ring_all_gather_bytes(pool_loc, k),
        lambda p: lax.all_gather(p, DATA_AXIS, axis=0, tiled=True),
        pool_loc, collective="all_gather", dim=0,
    )


def _scatter_needed_pages_wire(pool_loc, page_idx, k: int, b_loc: int):
    """The NEEDED-PAGES-ONLY exchange (the PR 11 follow-on): instead of
    all_gathering the whole pool, every shard contributes the pages it
    OWNS of every destination shard's referenced list, and one registered
    psum_scatter delivers shard d exactly its own rows' pages — wire is
    k x rows x pages-per-row page payloads, independent of pool size.

    The payload moves as BITCAST integers: exactly one shard owns any
    referenced page (the rest contribute zero words), so the integer sum
    reproduces the owner's bit pattern EXACTLY — float summation would
    turn a stored -0.0 into +0.0 and break the threshold-0 bitwise
    parity contract. Unowned slots (page index -1) deliver zeros; the
    caller's cold-init select replaces them.

    page_idx: [k*b_loc, pages_per_row] replicated int32. Returns
    [b_loc, pages_per_row, page_tokens, L, d] — this shard's rows' pages.
    """
    import jax

    pps = pool_loc.shape[0]  # pages per shard
    ppr = page_idx.shape[1]
    int_t = jnp.int16 if pool_loc.dtype == jnp.bfloat16 else jnp.int32
    flat = page_idx.reshape(k, b_loc * ppr)  # destination-major needs
    didx = lax.axis_index(DATA_AXIS)
    owner = jnp.where(flat >= 0, flat // pps, -1)
    local = jnp.clip(flat - didx * pps, 0, pps - 1)
    mine = owner == didx
    pool_bits = jax.lax.bitcast_convert_type(pool_loc, int_t)
    contrib = jnp.where(
        mine[..., None, None, None],
        pool_bits[local],
        jnp.zeros((), int_t),
    )  # [k, b_loc*ppr, pt, L, d] as integers
    got = tele_counters.timed_collective(
        "page_needed_psum_scatter", DATA_AXIS, "reduce_scatter",
        tele_counters.ring_reduce_scatter_bytes(contrib, k),
        lambda c: lax.psum_scatter(
            c, DATA_AXIS, scatter_dimension=0, tiled=True
        ),
        contrib, collective="psum_scatter", dim=0,
    )
    pages = jax.lax.bitcast_convert_type(
        got.reshape(b_loc, ppr, *pool_loc.shape[1:]), pool_loc.dtype
    )
    return pages


def _sharded_row_agreement(levels, n: int, seq: int) -> jnp.ndarray:
    """Per-row [b_loc, L] consensus agreement over the FULL patch axis
    from a seq-sharded [b_loc, n_loc, L, d] state: the
    early_exit.batch_agreement reduction decomposed into local partial
    sums + two psums over 'seq'. seq == 1 callers use batch_agreement
    directly (bitwise-identical, collective-free)."""
    x = levels.astype(jnp.float32)
    eps = 1e-8
    xhat = x / (jnp.linalg.norm(x, axis=-1, keepdims=True) + eps)
    part = jnp.sum(xhat, axis=1, keepdims=True)  # [b_loc, 1, L, d]
    mean = _psum_wire(part, SEQ_AXIS, seq, site="witness_mean_psum") / n
    mhat = mean / (jnp.linalg.norm(mean, axis=-1, keepdims=True) + eps)
    cos = jnp.sum(jnp.sum(xhat * mhat, axis=-1), axis=1)  # [b_loc, L]
    return _psum_wire(cos, SEQ_AXIS, seq, site="witness_cos_psum") / n


def make_serve_forward(
    mesh,
    cfg: GlomConfig,
    *,
    route,
    max_iters: Optional[int] = None,
    threshold: float = 1e-3,
    min_iters: int = 1,
    quorum: float = 1.0,
    compute_dtype=None,
    use_pallas: bool = False,
    sp_strategy: str = "auto",
    warm: bool = False,
    page_tokens: Optional[int] = None,
    page_gather: str = "auto",
):
    """Build the sharded bucket forward for one engine signature.

    route: "auto" (tiered early exit, budget `max_iters`) or an int (fixed
    iteration count — the ladder's capped route and the non-auto configs).
    Returns fn(params, img [b,c,H,W], mask [b]) — plus levels0
    [b, n, L, d] when warm — -> (levels [b,n,L,d], iters_run int32,
    row_converged [b] bool, row_iters [b] int32): the same 4-tuple contract
    as the single-device tiered route, so the engine treats both
    identically. The per-shard loop body is the reference-layout
    `update_step` (the SAME contract as serve/early_exit), with consensus
    swapped for the per-shard ring/ulysses/halo body when seq > 1.

    page_tokens selects the PAGED warm variant instead: the signature
    takes (pool [n_pages, page_tokens, L, d] sharded on its page axis
    over 'data', page_idx [b, pages_per_row] replicated int32, -1 =
    cold row) and each shard assembles its rows' levels0 in-graph — one
    registered all_gather of the pool over 'data' (the sharded page
    gather), a page-index take, then the seq band slice. Warm column
    state never crosses the host boundary on this route.
    """
    from glom_tpu.serve.early_exit import (
        _validate_auto_args,
        batch_agreement,
        quorum_need,
        row_agreement_delta,
    )

    seq = mesh.shape[SEQ_AXIS]
    dp = mesh.shape[DATA_AXIS]
    auto = route == "auto"
    if auto:
        T = max_iters if max_iters is not None else cfg.default_iters
        _validate_auto_args(T, min_iters, threshold)
    else:
        T = int(route)
        if T < 1:
            raise ValueError(f"route={route!r}: an int >= 1 or 'auto'")
    if cfg.num_patches % seq != 0:
        raise ValueError(
            f"patches {cfg.num_patches} not divisible by seq axis {seq}"
        )

    if use_pallas:
        from glom_tpu.kernels import fused_grouped_ffw

        ffw_fn = fused_grouped_ffw
    else:
        from glom_tpu.ops.ffw import grouped_ffw

        ffw_fn = grouped_ffw

    consensus_shard = shard_consensus_fn(cfg, seq, sp_strategy)
    if consensus_shard is None:
        # seq == 1: the dense single-device consensus — the branch the
        # bitwise parity test pins against the single-device engine.
        from functools import partial

        from glom_tpu.ops.consensus import build_local_mask, consensus_attention

        local_mask = build_local_mask(
            cfg.num_patches_side, cfg.local_consensus_radius
        )
        consensus_shard = partial(
            consensus_attention,
            attend_self=cfg.consensus_self,
            local_mask=local_mask,
        )

    n = cfg.num_patches
    n_loc = n // seq
    thr = jnp.float32(threshold)

    def body_fn(glom_params, img, mask, levels0):
        # Identical prologue ORDER to early_exit._build_update_step: cast
        # once, tokenize, then slice this shard's patch band.
        if compute_dtype is not None:
            glom_params = jax.tree_util.tree_map(
                lambda t: t.astype(compute_dtype), glom_params
            )
            img = img.astype(compute_dtype)
            if levels0 is not None:
                levels0 = levels0.astype(compute_dtype)

        tokens = image_to_tokens(
            glom_params.token_embed, img, cfg.patch_size
        )  # [b_loc, n, d]
        seq_idx = lax.axis_index(SEQ_AXIS)
        tokens_loc = lax.dynamic_slice_in_dim(
            tokens, seq_idx * n_loc, n_loc, axis=1
        )
        pos_loc = lax.dynamic_slice_in_dim(
            glom_params.pos_emb, seq_idx * n_loc, n_loc, axis=0
        )
        b_loc = tokens_loc.shape[0]
        pos = pos_loc[None, :, None, :]  # [1, n_loc, 1, d]
        bottom = tokens_loc[:, :, None, :]  # [b_loc, n_loc, 1, d]
        if levels0 is None:
            levels = jnp.broadcast_to(
                glom_params.init_levels[None, None],
                (b_loc, n_loc, cfg.levels, tokens_loc.shape[-1]),
            ).astype(tokens_loc.dtype)
        else:
            levels = levels0
        divisor = contribution_divisor(cfg.levels, jnp.float32)

        def step(lv):
            return update_step(
                glom_params, lv, bottom, pos, divisor,
                consensus_fn=consensus_shard, ffw_fn=ffw_fn,
            )

        def row_agreement(lv):
            if seq == 1:
                return batch_agreement(lv)
            return _sharded_row_agreement(lv, n, seq)

        valid = mask.astype(bool)

        if not auto:
            # Fixed route: scan T updates; every row "converged" by fiat
            # (there is no witness and no continuation on this route).
            with tele_counters.scaled(T):
                final, _ = lax.scan(
                    lambda lv, _: (step(lv), None), levels, None, length=T
                )
            return (
                final,
                jnp.int32(T),
                jnp.ones((b_loc,), bool),
                jnp.full((b_loc,), T, jnp.int32),
            )

        # The quorum target over ALL valid rows: one registered int hop
        # over 'data' outside the loop.
        n_valid = _psum_wire(
            jnp.sum(valid.astype(jnp.float32)), DATA_AXIS, dp,
            site="quorum_valid_psum",
        )
        need = quorum_need(quorum, n_valid)

        def cond(carry):
            lv, prev_rows, i, conv, row_iters = carry
            n_conv_loc = jnp.sum(
                jnp.logical_and(conv, valid).astype(jnp.int32)
            )
            n_conv = _psum_wire(
                n_conv_loc, DATA_AXIS, dp, site="quorum_exit_psum"
            )
            return jnp.logical_and(i < T, n_conv < need)

        def body(carry):
            lv, prev_rows, i, conv, row_iters = carry
            new = step(lv)
            agree_rows = row_agreement(new)  # [b_loc, L]
            delta = row_agreement_delta(agree_rows, prev_rows)
            newly = jnp.logical_and(i + 1 >= min_iters, delta < thr)
            first = jnp.logical_and(newly, jnp.logical_not(conv))
            row_iters = jnp.where(first, i + 1, row_iters)
            return (
                new, agree_rows, i + 1,
                jnp.logical_or(conv, newly), row_iters,
            )

        init_rows = row_agreement(levels)
        with tele_counters.scaled(T):
            final, _, iters_run, conv, row_iters = lax.while_loop(
                cond,
                body,
                (
                    levels,
                    init_rows,
                    jnp.int32(0),
                    jnp.zeros((b_loc,), bool),
                    jnp.full((b_loc,), T, jnp.int32),
                ),
            )
        row_iters = jnp.where(conv, row_iters, iters_run)
        return final, iters_run, conv, row_iters

    batch_spec = P(DATA_AXIS)
    lv_spec = P(DATA_AXIS, SEQ_AXIS)
    out_specs = (lv_spec, P(), P(DATA_AXIS), P(DATA_AXIS))

    if warm and page_tokens is not None:
        raise ValueError("warm (host levels0) and page_tokens are exclusive")
    if page_tokens is not None:
        if n % page_tokens != 0:
            raise ValueError(
                f"page_tokens {page_tokens} does not divide patches {n}"
            )
        pt = page_tokens

        if page_gather not in ("auto", "pool", "needed"):
            raise ValueError(
                f"page_gather {page_gather!r}: 'auto', 'pool', or 'needed'"
            )

        def paged_body(glom_params, img, mask, pool_loc, page_idx):
            # The sharded page materialization: pool pages live 1/dp per
            # shard. Two registered routes (ServeConfig.page_gather):
            # "pool" all_gathers the WHOLE pool (the provisioning bound),
            # "needed" psum_scatters ONLY the referenced pages; "auto"
            # picks whichever moves fewer bytes at this signature's
            # STATIC shapes — decided at trace time, and the compile
            # trace's counted bytes record the choice.
            b_loc = img.shape[0]
            didx = lax.axis_index(DATA_AXIS)
            mode = page_gather
            if mode == "auto":
                elt = pool_loc.dtype.itemsize
                page_elts = pt * cfg.levels * cfg.dim
                whole = (dp - 1) * pool_loc.shape[0] * page_elts * elt
                needed = (
                    (dp - 1) * b_loc * page_idx.shape[1] * page_elts * elt
                )
                mode = "needed" if needed < whole else "pool"
            if mode == "needed":
                with jax.named_scope("page_scatter_needed"):
                    pages = _scatter_needed_pages_wire(
                        pool_loc, page_idx, dp, b_loc
                    )
                my_idx = lax.dynamic_slice_in_dim(
                    page_idx, didx * b_loc, b_loc, axis=0
                )
            else:
                with jax.named_scope("page_gather"):
                    pool_full = _gather_pages_wire(pool_loc, dp)
                my_idx = lax.dynamic_slice_in_dim(
                    page_idx, didx * b_loc, b_loc, axis=0
                )  # [b_loc, pages_per_row]
                with jax.named_scope("page_take"):
                    pages = pool_full[
                        jnp.clip(my_idx, 0, pool_full.shape[0] - 1)
                    ]
            init = jnp.broadcast_to(
                glom_params.init_levels[None],
                (pt, cfg.levels, cfg.dim),
            ).astype(pool_loc.dtype)
            pages = jnp.where(
                (my_idx >= 0)[..., None, None, None], pages, init
            )
            lv_full = pages.reshape(b_loc, n, cfg.levels, cfg.dim)
            seq_idx = lax.axis_index(SEQ_AXIS)
            lv_loc = lax.dynamic_slice_in_dim(
                lv_full, seq_idx * n_loc, n_loc, axis=1
            )
            return body_fn(glom_params, img, mask, lv_loc)

        return shard_map(
            paged_body,
            mesh=mesh,
            in_specs=(P(), batch_spec, batch_spec, P(DATA_AXIS), P()),
            out_specs=out_specs,
            check_vma=False,
        )
    if warm:
        return shard_map(
            body_fn,
            mesh=mesh,
            in_specs=(P(), batch_spec, batch_spec, lv_spec),
            out_specs=out_specs,
            check_vma=False,
        )
    return shard_map(
        lambda p, img, mask: body_fn(p, img, mask, None),
        mesh=mesh,
        in_specs=(P(), batch_spec, batch_spec),
        out_specs=out_specs,
        check_vma=False,
    )
