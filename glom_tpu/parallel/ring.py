"""Ring consensus: exact sequence-parallel consensus attention.

The reference materializes a dense [b, L, n, n] similarity on one device
(glom_pytorch/glom_pytorch.py:58) — O(n^2) memory, single-chip. Here the
patch axis n is sharded over the 'seq' mesh axis; each step every shard
computes attention of its local queries against the k/v block it currently
holds, then rotates k/v to its ring neighbor with `lax.ppermute` (ICI
nearest-neighbor), accumulating with an online (flash-style) softmax. After
S steps every query has seen every key: bitwise-equivalent attention, O(n/S)
memory per chip, and the ppermute for step r+1 is issued before step r's
compute so XLA overlaps communication with the einsums.

Mask parity with the dense op (SURVEY.md §3.2 items 3-4):
  * self mask: global-index diagonal REPLACED with -5e-4 (soft), computed
    from the rotating block's global offset;
  * local-radius mask: hard -finfo.max beyond Euclidean patch-grid radius,
    recomputed per block from global row/col coordinates (integer-exact:
    squared distances compared against radius^2).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from glom_tpu.utils.compat import array_vma, axis_size, pcast_varying, shard_map
from glom_tpu.utils.helpers import TOKEN_ATTEND_SELF_VALUE, l2norm

NEG_MAX = -jnp.finfo(jnp.float32).max


def _grid_coords(idx: jnp.ndarray, side: int):
    return idx // side, idx % side


def _block_sim_masks(
    sim: jnp.ndarray,
    i_offset: jnp.ndarray,
    j_offset: jnp.ndarray,
    n_i: int,
    n_j: int,
    *,
    attend_self: bool,
    side: int,
    radius: float,
    n_total: int,
) -> jnp.ndarray:
    """Apply self/local/validity masks to one [b, L, n_i, n_j] sim block whose
    rows/cols sit at global offsets i_offset/j_offset."""
    idx_i = i_offset + lax.iota(jnp.int32, n_i)[:, None]  # [n_i, 1]
    idx_j = j_offset + lax.iota(jnp.int32, n_j)[None, :]  # [1, n_j]

    if not attend_self:
        eye = idx_i == idx_j
        sim = jnp.where(eye[None, None], TOKEN_ATTEND_SELF_VALUE, sim)

    invalid = (idx_j < 0) | (idx_j >= n_total)  # out-of-image halo positions
    if radius > 0:
        ri, ci = _grid_coords(idx_i, side)
        rj, cj = _grid_coords(idx_j, side)
        dist2 = (ri - rj) ** 2 + (ci - cj) ** 2
        invalid = invalid | (dist2.astype(jnp.float32) > radius * radius)
    sim = jnp.where(invalid[None, None], NEG_MAX, sim)
    return sim


def ring_consensus_shard(
    x: jnp.ndarray,
    *,
    axis_name: str,
    attend_self: bool,
    side: int,
    radius: float,
) -> jnp.ndarray:
    """Per-shard body (call under shard_map with n sharded over `axis_name`).

    x: [b, n_loc, L, d] local block -> [b, n_loc, L, d].
    """
    S = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, n_loc, L, d = x.shape
    n_total = n_loc * S
    scale = d ** -0.5
    perm = [(i, (i - 1) % S) for i in range(S)]  # shard p receives p+1's block

    q = x.astype(jnp.float32)
    k0 = l2norm(q, axis=-1)
    v0 = q
    i_offset = my * n_loc

    # The accumulators start device-invariant but become device-varying via
    # the rotating blocks; mark them varying up front so the fori_loop carry
    # types line up (JAX vma tracking under shard_map). Match x's varying
    # axes, not just the ring axis — this body may run inside a larger
    # manual region (e.g. parallel.manual's (data, seq) shard_map).
    vma = array_vma(x)

    def varying(t):
        return pcast_varying(t, vma)

    m0 = varying(jnp.full((b, L, n_loc, 1), NEG_MAX, jnp.float32))
    s0 = varying(jnp.zeros((b, L, n_loc, 1), jnp.float32))
    o0 = varying(jnp.zeros((b, L, n_loc, d), jnp.float32))

    def body(r, carry):
        m, s, o, k_blk, v_blk = carry
        # Issue next rotation first — no data dependence on this step's
        # compute, so XLA overlaps the ICI transfer with the einsums.
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)

        owner = (my + r) % S  # whose block we hold at step r
        j_offset = owner * n_loc
        sim = (
            jnp.einsum("bild,bjld->blij", q, k_blk, preferred_element_type=jnp.float32)
            * scale
        )
        sim = _block_sim_masks(
            sim,
            i_offset,
            j_offset,
            n_loc,
            n_loc,
            attend_self=attend_self,
            side=side,
            radius=radius,
            n_total=n_total,
        )
        blk_max = jnp.max(sim, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, blk_max)
        corr = jnp.exp(m - m_new)
        p = jnp.exp(sim - m_new)
        s_new = s * corr + jnp.sum(p, axis=-1, keepdims=True)
        o_new = o * corr + jnp.einsum(
            "blij,bjld->blid", p, v_blk, preferred_element_type=jnp.float32
        )
        return m_new, s_new, o_new, k_nxt, v_nxt

    m, s, o, _, _ = lax.fori_loop(0, S, body, (m0, s0, o0, k0, v0))
    out = o / s  # [b, L, n_loc, d]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(x.dtype)


def make_ring_consensus(
    mesh,
    *,
    attend_self: bool,
    side: int,
    radius: float = 0.0,
    axis_name: str = "seq",
):
    """Build a consensus_fn: [b, n, L, d] -> [b, n, L, d] with n sharded over
    `axis_name`. Drop-in for glom_forward(consensus_fn=...)."""
    fn = partial(
        ring_consensus_shard,
        axis_name=axis_name,
        attend_self=attend_self,
        side=side,
        radius=radius,
    )
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=jax.sharding.PartitionSpec(None, axis_name, None, None),
        out_specs=jax.sharding.PartitionSpec(None, axis_name, None, None),
        axis_names={axis_name},
    )
