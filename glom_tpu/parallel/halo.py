"""Halo-exchange consensus for the local-radius path (BASELINE config 3).

When `local_consensus_radius` r > 0 the reference still materializes the
full n x n similarity and masks it (glom_pytorch/glom_pytorch.py:65-67).
But locality means a patch only attends within r grid rows/cols — so with
the patch grid sharded into contiguous ROW BANDS over the 'seq' axis, each
shard needs exactly `floor(r)` rows from each neighbor (grid distances are
integers: a patch within Euclidean radius r is at most floor(r) rows away),
not the whole ring: two nearest-neighbor ppermutes (one up, one down, both
riding a single ICI hop) instead of S ring steps. Communication
O(r * side * L * d) per shard, independent of n.

Requires rows_per_shard >= floor(r) (one-hop halo — the predicate is
helpers.halo_supported); use the ring for larger radii or finer shardings.

Out-of-image halo slots (top shard's upper halo, bottom shard's lower halo)
arrive zero-filled from the non-periodic ppermute and are hard-masked via
their global indices, so they contribute exactly zero attention.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from glom_tpu.parallel.ring import _block_sim_masks
from glom_tpu.utils.compat import axis_size, shard_map
from glom_tpu.utils.helpers import halo_supported, l2norm


def halo_consensus_shard(
    x: jnp.ndarray,
    *,
    axis_name: str,
    attend_self: bool,
    side: int,
    radius: float,
) -> jnp.ndarray:
    """Per-shard body (under shard_map; n sharded over `axis_name` in
    row-major row bands). x: [b, n_loc, L, d] -> [b, n_loc, L, d]."""
    S = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, n_loc, L, d = x.shape
    n_total = n_loc * S
    rows_per_shard = n_loc // side
    # Grid distances are integers: a patch within Euclidean distance r is at
    # most floor(r) rows away (ceil would falsely reject workable configs
    # and ship a whole extra masked row per neighbor for fractional radii).
    halo_rows = min(int(math.floor(radius)), rows_per_shard)
    h = halo_rows * side  # halo size in patches
    scale = d ** -0.5

    q = x.astype(jnp.float32)
    k_loc = l2norm(q, axis=-1)
    v_loc = q

    # Non-periodic neighbor exchange: shard p's bottom rows become p+1's top
    # halo; p's top rows become p-1's bottom halo. Missing neighbors (grid
    # edges) arrive zero-filled and are masked below by global index.
    down_perm = [(i, i + 1) for i in range(S - 1)]
    up_perm = [(i + 1, i) for i in range(S - 1)]

    def exchange(t):
        top_halo = lax.ppermute(t[:, -h:], axis_name, down_perm)  # from p-1
        bot_halo = lax.ppermute(t[:, :h], axis_name, up_perm)  # from p+1
        return jnp.concatenate([top_halo, t, bot_halo], axis=1)

    if h > 0:
        k_ext = exchange(k_loc)  # [b, n_loc + 2h, L, d]
        v_ext = exchange(v_loc)
    else:
        # radius < 1: no cross-shard pairs are within reach (adjacent grid
        # rows are distance 1 apart), so skip the exchange entirely. The
        # h == 0 slice t[:, -0:] would otherwise select the WHOLE block and
        # mislabel a full neighbor copy with local global indices.
        k_ext, v_ext = k_loc, v_loc

    i_offset = my * n_loc
    j_offset = i_offset - h  # the extended block starts h patches earlier

    sim = (
        jnp.einsum("bild,bjld->blij", q, k_ext, preferred_element_type=jnp.float32)
        * scale
    )
    sim = _block_sim_masks(
        sim,
        i_offset,
        j_offset,
        n_loc,
        n_loc + 2 * h,
        attend_self=attend_self,
        side=side,
        radius=radius,
        n_total=n_total,
    )
    attn = jax.nn.softmax(sim, axis=-1)
    out = jnp.einsum("blij,bjld->blid", attn, v_ext, preferred_element_type=jnp.float32)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(x.dtype)


def make_halo_consensus(
    mesh,
    *,
    attend_self: bool,
    side: int,
    radius: float,
    axis_name: str = "seq",
):
    """Build a consensus_fn for the local-radius path; n sharded over
    `axis_name`. Validates the one-hop halo precondition at build time —
    the same predicate callers can pre-check via helpers.halo_supported."""
    seq = mesh.shape[axis_name]
    if not halo_supported(seq, side, radius):
        if radius <= 0:
            raise ValueError("halo consensus requires local_consensus_radius > 0")
        if side % seq != 0:
            raise ValueError(f"grid side {side} not divisible by seq axis {seq}")
        raise ValueError(
            f"radius {radius} needs {math.floor(radius)} halo rows but shards "
            f"only hold {side // seq}; use ring consensus instead"
        )
    fn = partial(
        halo_consensus_shard,
        axis_name=axis_name,
        attend_self=attend_self,
        side=side,
        radius=radius,
    )
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=jax.sharding.PartitionSpec(None, axis_name, None, None),
        out_specs=jax.sharding.PartitionSpec(None, axis_name, None, None),
        axis_names={axis_name},
    )
