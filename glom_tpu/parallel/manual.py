"""Fully-manual SPMD training path: Pallas kernels composed with DP x SP.

Round-1 limitation (VERDICT weak #6): the fused Pallas kernels were illegal
inside GSPMD-sharded regions (custom calls carry no partitioning rule), so
`use_pallas` evaporated exactly where perf matters — the distributed
configs. The TPU-native fix is NOT a partitioning rule per kernel but this
module: the ENTIRE loss runs inside ONE `shard_map` over ('data', 'seq'),
where every array is physically local and a Pallas call is plain per-device
work. Collectives are explicit and minimal:

  * DP   — batch sharded over 'data'; the gradient all-reduce appears
           automatically when shard_map transposes the replicated-in params
           (a psum of the per-shard cotangents) — the same collective GSPMD
           would have inserted, now riding the manual region.
  * SP   — the patch axis n sharded over 'seq'; consensus attention runs the
           existing per-shard ring / halo / ulysses bodies (ring.py /
           halo.py / ulysses.py), which were written exactly for this
           context (lax.ppermute / all_to_all over 'seq'). With seq=1 the
           fused consensus+update kernel runs whole.
  * loss — per-shard MSE over the local (batch-band x patch-band) block,
           pmean'd over both axes. Reconstruction compares PATCHES (the
           pixel set is identical to the reference's image-space MSE, so the
           value is exact — unpatchify would need an n all-gather for
           nothing).

  * TP   — the grouped-FFW hidden axis f sharded over 'model'
           (Megatron-style, same layout as sharding.ffw_specs): each rank
           runs the fused kernel on its [G, d, f/mp] / [G, f/mp, d] weight
           shards and ONE hand-written psum on the second matmul's output
           reconstructs the full FFW result. b2 is added in-kernel scaled
           by 1/mp so the psum reconstructs it exactly (mp is a power of
           two, so the scale is exact in bf16). Gradient correctness under
           check_vma=False was established empirically (scratch/tp_proto.py):
           a RAW lax.psum composes correctly with the shard_map transpose —
           partial dx cotangents get psum'd over 'model', sharded-weight
           cotangents stay local, replicated-param cotangents come out
           unscaled. No custom_vjp link functions needed.

Reference parity: the per-shard scan body is the same §3.2 contract as
models/core.py (same kernels, same 4-vs-3 divisor, same pos-emb placement);
parity is locked by tests/test_manual.py against the single-device dense
forward.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from glom_tpu.models.core import contribution_divisor
from glom_tpu.ops.patch import image_to_tokens, patchify
from glom_tpu.parallel.halo import halo_consensus_shard
from glom_tpu.parallel.ring import ring_consensus_shard
from glom_tpu.telemetry import counters as tele_counters
from glom_tpu.telemetry import diagnostics as diag
from glom_tpu.train.objectives import DenoiseParams, default_recon_index
from glom_tpu.train.trainer import TrainState, pinned_grad_accum
from glom_tpu.utils.config import GlomConfig, TrainConfig
from glom_tpu.utils.compat import array_vma, pcast_varying, shard_map

DATA_AXIS = "data"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"


def manual_supported(mesh, tp_axis: str = "hidden") -> bool:
    """The manual fused path covers DP x SP x hidden-TP. The EP-style
    'levels' TP shards the group axis with a different collective pattern
    and stays on the GSPMD path."""
    return mesh.shape.get(MODEL_AXIS, 1) == 1 or tp_axis == "hidden"


def shard_consensus_fn(cfg: GlomConfig, seq: int, sp_strategy: str):
    """Pick the per-shard consensus body ([b, n_loc, L, d] -> same) for the
    'seq'-manual region. None means seq is unsharded and the caller should
    use the fused consensus+update kernel instead.

    Resolution (auto + fallbacks + warnings) is runtime.effective_sp_strategy
    — the single policy source; this is construction only. 'none' with a
    sharded seq axis builds ring: the manual region's n-shards must
    communicate, and ring is the exact default mechanism."""
    from glom_tpu.parallel.runtime import effective_sp_strategy

    sp_strategy = effective_sp_strategy(cfg, seq, sp_strategy)
    if seq == 1:
        return None
    radius = float(cfg.local_consensus_radius)
    if sp_strategy == "ulysses":
        from glom_tpu.parallel.ulysses import ulysses_consensus_shard

        return partial(
            ulysses_consensus_shard,
            axis_name=SEQ_AXIS,
            attend_self=cfg.consensus_self,
            side=cfg.num_patches_side,
            radius=radius,
        )
    if sp_strategy == "halo":
        return partial(
            halo_consensus_shard,
            axis_name=SEQ_AXIS,
            attend_self=cfg.consensus_self,
            side=cfg.num_patches_side,
            radius=radius,
        )
    return partial(
        ring_consensus_shard,
        axis_name=SEQ_AXIS,
        attend_self=cfg.consensus_self,
        side=cfg.num_patches_side,
        radius=radius,
    )


def _use_loop_vjp(
    cfg: GlomConfig, b_loc: int, iters: int, remat: bool, dtype, interpret: bool
) -> bool:
    """Should this seq=1/mp=1 shard body dispatch to the whole-loop VJP
    (kernels/fused_loop.py) instead of scanning the per-op kernels? This
    is resolve_vjp_path — THE resolution source, including the
    GLOM_CONSENSUS_BWD A/B gate — at the SHARD-LOCAL batch: a DP run must
    get the same glue-free backward the single-chip flagship gets.
    interpret=True (CPU shard_map tests) bypasses only the platform
    check; the policy itself is never duplicated here."""
    from glom_tpu.models.core import resolve_vjp_path

    return (
        resolve_vjp_path(
            cfg, b_loc, iters,
            remat=remat, use_pallas=True, itemsize=dtype.itemsize,
            assume_on_tpu=interpret,
        )
        == "fused_loop"
    )


def _forward_local(
    glom_params,
    noised: jnp.ndarray,
    cfg: GlomConfig,
    *,
    iters: int,
    seq: int,
    mp: int,
    consensus_shard,
    remat: bool,
    use_pallas: bool,
    unroll: bool = False,
    levels0_lm: Optional[jnp.ndarray] = None,
    return_mode: str = "top",
    interpret: bool = False,
) -> jnp.ndarray:
    """Per-shard forward: local batch, local patch band, local FFW hidden
    shard (level-major carry, Pallas FFWs; fused consensus+update kernel
    when seq == 1, and the WHOLE-LOOP VJP when the shard-local shape
    admits it — see _use_loop_vjp). levels0_lm optionally carries in a
    [L, b_loc, n_loc, d] initial state (the temporal API). return_mode:
      'top'   — final top level [b_loc, n_loc, d] (the training loss path);
      'final' — full final carry [L, b_loc, n_loc, d];
      'all'   — all T+1 states [T+1, L, b_loc, n_loc, d] incl. the initial
                (reference return_all contract, T+1 states)."""
    from glom_tpu.kernels import fused_consensus_update
    from glom_tpu.kernels.grouped_mlp import fused_grouped_ffw_lm
    from glom_tpu.ops.ffw import grouped_ffw_lm

    ffw_lm = fused_grouped_ffw_lm if use_pallas else grouped_ffw_lm
    if mp > 1:
        # Megatron TP: this rank's weights cover f/mp hidden units; the
        # kernel output is a partial sum over f, completed by one psum.
        # b2 is added in-kernel, so scale it 1/mp (exact: mp is a power of
        # two) and let the psum reconstruct it. Raw psum composes correctly
        # with the shard_map transpose under check_vma=False — verified in
        # scratch/tp_proto.py (variant D) against dense-reference grads.
        inner_ffw, inv_mp = ffw_lm, 1.0 / mp

        def ffw_lm(p, x):
            p = p._replace(b2=p.b2 * jnp.asarray(inv_mp, p.b2.dtype))
            out = inner_ffw(p, x)
            # This is a WIRE-MOVING collective (full FFW activations over
            # 'model', every scan iteration — the scans below run under
            # scaled(iters) so the trace-time record prices every
            # execution), found unregistered by glom-lint's
            # collective-coverage pass: the drift reconciliation could
            # never see TP forward traffic. Recording only fires inside a
            # counters.recording() context, so no runtime change outside
            # the counting trace. NOTE: comm_volume_model prices the
            # gradient/update path only (no TP term), and the trainer's
            # counting trace can never reach this site today (manual x
            # zero>=1 degrades to zero 0 on model>1 meshes, see
            # runtime.py) — if a future route records a TP config, the
            # model needs a TP term FIRST or comm_model_drift becomes a
            # permanent false alarm. The per-execution pricing contract
            # is pinned by test_telemetry's TP counting test. Routed
            # through the shared timing wrapper (counters.timed_collective
            # — the capacity observatory's per-collective wall-time seam):
            # byte recording is unchanged, and a timing-enabled trace
            # additionally registers the site for the sampled re-dispatch.
            return tele_counters.timed_collective(
                "tp_ffw_psum", MODEL_AXIS, "reduce",
                tele_counters.ring_allreduce_bytes(out, mp),
                lambda o: lax.psum(o, MODEL_AXIS), out, collective="psum",
            )
    if consensus_shard is None and not use_pallas:
        raise ValueError(
            "seq=1 without use_pallas has no per-shard consensus body; pass "
            "one (make_manual_loss builds the dense composition for this case)"
        )

    L, d = cfg.levels, cfg.dim
    n, n_loc = cfg.num_patches, cfg.num_patches // seq

    # Patchify the full image, then slice this shard's patch band. The patch
    # grid is row-major, so a contiguous n-band is a contiguous row band —
    # the layout ring/halo assume. (Patchify+embed on the full image is
    # O(n * p^2 * c * d), noise vs one scan iteration; slicing after keeps
    # the code free of pixel-band geometry.)
    tokens = image_to_tokens(
        glom_params.token_embed, noised, cfg.patch_size
    )  # [b_loc, n, d]
    seq_idx = lax.axis_index(SEQ_AXIS)
    tokens_loc = lax.dynamic_slice_in_dim(tokens, seq_idx * n_loc, n_loc, axis=1)
    pos_loc = lax.dynamic_slice_in_dim(
        glom_params.pos_emb, seq_idx * n_loc, n_loc, axis=0
    )

    b_loc = tokens_loc.shape[0]
    tokens_lm = tokens_loc[None]  # [1, b_loc, n_loc, d]
    pos_lm = pos_loc[None, None]  # [1, 1, n_loc, d]
    if levels0_lm is not None:
        levels_lm = levels0_lm.astype(tokens_loc.dtype)
    else:
        levels_lm = jnp.broadcast_to(
            glom_params.init_levels[:, None, None], (L, b_loc, n_loc, d)
        ).astype(tokens_loc.dtype)
        # The initial carry is device-invariant (broadcast replicated
        # params) but the scan body's output varies over both mesh axes (it
        # consumes the local tokens); align the vma types up front (see
        # ring.py). Under check_vma=False the vma set is empty and pcast
        # must not run. (A carried-in levels0 is already sharded input —
        # already varying — and must NOT be pcast.)
        vma = array_vma(tokens_loc)
        if vma:
            levels_lm = pcast_varying(levels_lm, vma)
    divisor_lm = contribution_divisor(L, jnp.float32).reshape(L, 1, 1, 1)

    # seq=1 / mp=1 shards with an admissible local shape take the
    # hand-rolled whole-loop VJP — the same backward the single-chip
    # flagship trains on (slot carry, chained/unchained accumulators,
    # in-register cotangent combine) instead of the scan-autodiff path.
    # Composes with the data-axis shard_map transpose exactly like the
    # per-op custom_vjps: the loop emits per-shard cotangents; the params
    # psum comes from the shard_map transpose of the replicated in_spec.
    if (
        consensus_shard is None
        and mp == 1
        and use_pallas
        and return_mode in ("top", "final")
        and _use_loop_vjp(cfg, b_loc, iters, remat, tokens_loc.dtype, interpret)
    ):
        from glom_tpu.kernels.fused_loop import fused_glom_loop

        final = fused_glom_loop(
            glom_params.bottom_up, glom_params.top_down, pos_loc,
            tokens_loc, levels_lm, iters, cfg.num_patches_side,
            float(cfg.local_consensus_radius), cfg.consensus_self,
            interpret, remat,
        )
        return final if return_mode == "final" else final[-1]

    def body(carry, _):
        lv = carry
        bu_in = jnp.concatenate([tokens_lm, lv[:-1]], axis=0)
        bu = ffw_lm(
            glom_params.bottom_up, bu_in.reshape(L, b_loc * n_loc, d)
        ).reshape(L, b_loc, n_loc, d)
        td = ffw_lm(
            glom_params.top_down, (lv[1:] + pos_lm).reshape(L - 1, b_loc * n_loc, d)
        ).reshape(L - 1, b_loc, n_loc, d)
        if consensus_shard is None:
            new = fused_consensus_update(
                lv, bu, td,
                side=cfg.num_patches_side,
                radius=float(cfg.local_consensus_radius),
                attend_self=cfg.consensus_self,
            )
        else:
            cons = consensus_shard(jnp.transpose(lv, (1, 2, 0, 3)))
            cons_lm = jnp.transpose(cons, (2, 0, 1, 3))
            td_full = jnp.concatenate([td, jnp.zeros_like(td[:1])], axis=0)
            new = (
                (
                    lv.astype(jnp.float32)
                    + bu.astype(jnp.float32)
                    + td_full.astype(jnp.float32)
                    + cons_lm.astype(jnp.float32)
                )
                / divisor_lm
            ).astype(lv.dtype)
        return new, None

    if return_mode == "all":
        def body_ys(carry, _):
            new, _ = body(carry, _)
            return new, new
        if remat:
            body_ys = jax.checkpoint(body_ys)
        # scaled(iters): the body traces ONCE here but executes per scan
        # iteration — collective sites inside it (the TP psum) must price
        # every execution (same convention as the stage-2 microbatch hook).
        with tele_counters.scaled(iters):
            final, ys = lax.scan(
                body_ys, levels_lm, None, length=iters, unroll=unroll
            )
        return jnp.concatenate([levels_lm[None], ys], axis=0)  # [T+1, L, ...]
    if remat:
        body = jax.checkpoint(body)
    with tele_counters.scaled(iters):
        final, _ = lax.scan(body, levels_lm, None, length=iters, unroll=unroll)
    if return_mode == "final":
        return final  # [L, b_loc, n_loc, d]
    return final[-1]  # top level, [b_loc, n_loc, d]


def _build_local_loss(
    mesh,
    cfg: GlomConfig,
    tcfg: TrainConfig,
    *,
    sp_strategy: str = "none",
    interpret: bool = False,
):
    """The per-shard loss body both manual train steps share: returns
    (local_loss, seq, mp) where local_loss(params, img, noise) -> scalar is
    the mean over the LOCAL batch band (pmean'd over 'seq' so every data
    replica holds its full-image loss, NOT yet reduced over 'data').
    make_manual_loss pmeans it over 'data' and lets the shard_map
    transpose emit the grad psum; the ZeRO step differentiates it directly
    inside the region and writes its own reduce-scatter instead."""
    seq = mesh.shape[SEQ_AXIS]
    mp = mesh.shape.get(MODEL_AXIS, 1)
    T = tcfg.iters if tcfg.iters is not None else cfg.default_iters
    k = (
        tcfg.recon_iter_index
        if tcfg.recon_iter_index is not None
        else default_recon_index(T)
    )
    if not 1 <= k <= T:
        raise ValueError(f"recon_index {k} outside 1..{T}")
    compute_dtype = jnp.bfloat16 if tcfg.compute_dtype == "bfloat16" else None
    consensus_shard = shard_consensus_fn(cfg, seq, sp_strategy)
    use_pallas = tcfg.use_pallas

    # seq==1 with use_pallas=False has no kernel to fuse — the caller
    # (DistributedTrainer) only routes here when use_pallas is set, but keep
    # the plain-XLA composition correct for direct users/tests.
    if consensus_shard is None and not use_pallas:
        from glom_tpu.ops.consensus import build_local_mask, consensus_attention

        mask = build_local_mask(cfg.num_patches_side, cfg.local_consensus_radius)

        def dense_shard(x):  # [b, n_loc=n, L, d]
            return consensus_attention(
                x, attend_self=cfg.consensus_self, local_mask=mask
            )

        consensus_shard = dense_shard

    def loss_body(params: DenoiseParams, img: jnp.ndarray, noise: jnp.ndarray):
        glom_params = params.glom
        if compute_dtype is not None:
            glom_params = jax.tree_util.tree_map(
                lambda t: t.astype(compute_dtype), glom_params
            )
        noised = (img + noise).astype(
            compute_dtype if compute_dtype is not None else img.dtype
        )
        top = _forward_local(
            glom_params,
            noised,
            cfg,
            iters=k,
            seq=seq,
            mp=mp,
            consensus_shard=consensus_shard,
            remat=tcfg.remat,
            use_pallas=use_pallas,
            unroll=tcfg.scan_unroll,
            interpret=interpret,
        )  # [b_loc, n_loc, d]

        # Reconstruction + MSE in PATCH space: identical pixel set to the
        # reference's image-space MSE (patchify is a permutation), no
        # all-gather needed for the local band.
        recon = top.astype(img.dtype) @ params.to_pixels.w + params.to_pixels.b
        target = patchify(img, cfg.patch_size)  # [b_loc, n, p*p*c]
        n_loc = cfg.num_patches // seq
        seq_idx = lax.axis_index(SEQ_AXIS)
        target_loc = lax.dynamic_slice_in_dim(
            target, seq_idx * n_loc, n_loc, axis=1
        )
        local_mse = jnp.mean((target_loc - recon) ** 2)
        return lax.pmean(local_mse, SEQ_AXIS)

    return loss_body, seq, mp


def _manual_param_spec(mp: int):
    """in/out param spec for the manual regions: pre-sharded over 'model'
    on the hidden axis when TP is on (the same layout DistributedTrainer
    device_puts — sharding.denoise_param_specs — so no resharding at the
    boundary), replicated otherwise."""
    if mp > 1:
        from glom_tpu.parallel.sharding import denoise_param_specs

        return denoise_param_specs("hidden")
    return P()


def make_manual_loss(
    mesh,
    cfg: GlomConfig,
    tcfg: TrainConfig,
    *,
    sp_strategy: str = "none",
    interpret: bool = False,
):
    """Build loss(params, img, noise) -> scalar: the whole computation one
    shard_map over (data, seq, model). Differentiable; the params cotangent
    psum (the DP gradient all-reduce) comes from the shard_map transpose,
    and the TP psum on the FFW output is written by hand in the body."""
    local_loss, seq, mp = _build_local_loss(
        mesh, cfg, tcfg, sp_strategy=sp_strategy, interpret=interpret
    )

    def loss_body(params: DenoiseParams, img: jnp.ndarray, noise: jnp.ndarray):
        return lax.pmean(local_loss(params, img, noise), DATA_AXIS)

    batch_spec = P(DATA_AXIS)  # [b, c, H, W]; replicated over seq (sliced in-body)
    param_spec = _manual_param_spec(mp)
    return shard_map(
        loss_body,
        mesh=mesh,
        in_specs=(param_spec, batch_spec, batch_spec),
        out_specs=P(),
        # Fully manual — over EVERY mesh axis, including the size-1 'model'
        # axis. Leaving any axis auto keeps the body in GSPMD context, and
        # Mosaic (Pallas) custom calls refuse to lower there.
        # pallas_call's out_shape carries no vma type, which trips the
        # varying-axes checker when a kernel actually lowers (on TPU; the
        # CPU tests take the XLA fallbacks and never hit it). The pmean on
        # the loss makes the out_specs=P() replication correct by
        # construction; ring.py's pcast self-adapts (typeof(x).vma is empty
        # with the checker off).
        check_vma=False,
    )


def make_manual_forward(
    mesh,
    cfg: GlomConfig,
    *,
    iters: Optional[int] = None,
    sp_strategy: str = "none",
    compute_dtype=None,
    use_pallas: bool = True,
    return_all: bool = False,
    with_levels: bool = False,
    remat: bool = False,
):
    """Sharded INFERENCE through the fused kernels: glom_forward's contract
    (final [b, n, L, d], or all T+1 states with return_all) as one
    shard_map over (data, seq, model) — the path `Glom(mesh=...)` uses so
    the preserved API reaches the Pallas kernels under a mesh (round-2
    VERDICT weak #5: training got the manual fused region, inference
    didn't). with_levels=True compiles the temporal variant taking a
    [b, n, L, d] carried-in state sharded (data, seq)."""
    seq = mesh.shape[SEQ_AXIS]
    mp = mesh.shape.get(MODEL_AXIS, 1)
    T = iters if iters is not None else cfg.default_iters
    consensus_shard = shard_consensus_fn(cfg, seq, sp_strategy)
    if consensus_shard is None and not use_pallas:
        from glom_tpu.ops.consensus import build_local_mask, consensus_attention

        mask = build_local_mask(cfg.num_patches_side, cfg.local_consensus_radius)

        def consensus_shard(x):  # noqa: F811 - deliberate dense fallback
            return consensus_attention(
                x, attend_self=cfg.consensus_self, local_mask=mask
            )

    def fwd_body(glom_params, img, levels0):
        if compute_dtype is not None:
            glom_params = jax.tree_util.tree_map(
                lambda t: t.astype(compute_dtype), glom_params
            )
            img = img.astype(compute_dtype)
        levels0_lm = (
            None if levels0 is None else jnp.transpose(levels0, (2, 0, 1, 3))
        )
        out = _forward_local(
            glom_params,
            img,
            cfg,
            iters=T,
            seq=seq,
            mp=mp,
            consensus_shard=consensus_shard,
            remat=remat,
            use_pallas=use_pallas,
            levels0_lm=levels0_lm,
            return_mode="all" if return_all else "final",
        )
        # level-major -> reference layout [.., b, n, L, d]
        if return_all:
            return jnp.transpose(out, (0, 2, 3, 1, 4))
        return jnp.transpose(out, (1, 2, 0, 3))

    batch_spec = P(DATA_AXIS)
    if mp > 1:
        from glom_tpu.parallel.sharding import glom_param_specs

        param_spec = glom_param_specs("hidden")
    else:
        param_spec = P()
    lv_spec = P(DATA_AXIS, SEQ_AXIS)
    out_spec = P(None, DATA_AXIS, SEQ_AXIS) if return_all else lv_spec

    if with_levels:
        return shard_map(
            fwd_body,
            mesh=mesh,
            in_specs=(param_spec, batch_spec, lv_spec),
            out_specs=out_spec,
            check_vma=False,
        )
    return shard_map(
        lambda p, img: fwd_body(p, img, None),
        mesh=mesh,
        in_specs=(param_spec, batch_spec),
        out_specs=out_spec,
        check_vma=False,
    )


def make_manual_train_step(
    mesh,
    cfg: GlomConfig,
    tcfg: TrainConfig,
    optimizer: optax.GradientTransformation,
    *,
    sp_strategy: str = "none",
    with_grad_norm: bool = True,
    interpret: bool = False,
):
    """(state, img, rng) -> (state, metrics): the manual-region analog of
    train.trainer.make_train_step, same metrics contract (incl. the
    with_grad_norm fast variant for non-logging steps, and the telemetry
    scalars + NaN/Inf guard at tcfg.telemetry_level != "off" — "full"
    degrades to "scalars" here, see resolve_telemetry_level)."""
    if tcfg.compute_dtype not in ("float32", "bfloat16"):
        raise ValueError(
            f"compute_dtype={tcfg.compute_dtype!r}: must be 'float32' or 'bfloat16'"
        )
    accum = pinned_grad_accum(tcfg)
    if tcfg.batch_size % accum != 0:
        raise ValueError(
            f"grad_accum={accum} must divide batch_size={tcfg.batch_size}"
        )
    if (tcfg.batch_size // accum) % mesh.shape[DATA_AXIS] != 0:
        raise ValueError(
            f"microbatch {tcfg.batch_size // accum} not divisible "
            f"by data axis {mesh.shape[DATA_AXIS]}"
        )
    level = diag.resolve_telemetry_level(tcfg, supports_full=False)
    loss_fn = make_manual_loss(
        mesh, cfg, tcfg, sp_strategy=sp_strategy, interpret=interpret
    )

    def train_step(state: TrainState, img: jnp.ndarray, rng: jax.Array):
        noise_rng = jax.random.fold_in(rng, state.step)
        noise = tcfg.noise_std * jax.random.normal(noise_rng, img.shape, img.dtype)
        if accum > 1:
            from glom_tpu.train.trainer import accumulate_grads

            loss, grads = accumulate_grads(
                loss_fn, state.params, img, noise, accum
            )
        else:
            loss, grads = jax.value_and_grad(loss_fn)(state.params, img, noise)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss, "step": state.step}
        if with_grad_norm or level != "off":
            grad_norm = optax.global_norm(grads)
        if with_grad_norm:
            metrics["grad_norm"] = grad_norm
        if level != "off":
            # The grads/updates here are full replicated trees (the
            # shard_map transpose already reduced them), so the scalar
            # taps and the guard run OUTSIDE the manual region — same
            # fused-reduction cost as the GSPMD step's.
            taps = diag.scalar_taps(
                loss=loss, grad_norm=grad_norm, updates=updates, params=params
            )
            nonfinite = taps.pop("nonfinite")
            if tcfg.nonfinite_policy == "skip":
                params = diag.guard_update(nonfinite, params, state.params)
                opt_state = diag.guard_update(
                    nonfinite, opt_state, state.opt_state
                )
                metrics["skipped_nonfinite"] = nonfinite.astype(jnp.int32)
            metrics.update(taps)
            metrics["nonfinite_step"] = nonfinite.astype(jnp.int32)
        return TrainState(params, opt_state, state.step + 1), metrics

    return train_step


def _zero_shard_axes(zero_pspecs):
    """Param-shaped tree of shard-axis indices from the ZeRO spec tree:
    the position 'data' occupies in each leaf's PartitionSpec, or -1 for
    leaves that stay replicated (no dp-divisible free axis). -1 rather
    than None so the tree keeps its leaves under tree_map."""

    def axis_of(spec):
        for i, entry in enumerate(tuple(spec)):
            names = entry if isinstance(entry, tuple) else (entry,)
            if DATA_AXIS in names:
                return i
        return -1

    return jax.tree_util.tree_map(
        axis_of, zero_pspecs, is_leaf=lambda x: isinstance(x, P)
    )


def make_manual_zero_train_step(
    mesh,
    cfg: GlomConfig,
    tcfg: TrainConfig,
    optimizer: optax.GradientTransformation,
    *,
    zero_stage: int,
    zero_pspecs,
    opt_pspecs,
    sp_strategy: str = "none",
    with_grad_norm: bool = True,
    interpret: bool = False,
    quantized_reduce: Optional[bool] = None,
):
    """The EXPLICIT form of the ZeRO weight update (the GSPMD form lives in
    train.trainer.make_train_step): one shard_map over (data, seq, model)
    in which every collective of the schedule is written out, so the wire
    pattern is inspectable in the jaxpr rather than inferred from GSPMD:

      1. value_and_grad of the LOCAL loss inside the region — no shard_map
         transpose, hence no automatic grad psum to fight;
      2. `lax.psum` of the cotangents over 'seq' (params are replicated
         over the patch bands, each band contributes a partial);
      3. `lax.psum_scatter(..., scatter_dimension=leaf's zero axis,
         tiled=True) / dp` over 'data' — THE reduce-scatter: each replica
         leaves the reduction holding exactly its owned 1/dp shard
         (leaves with no dp-divisible axis take a plain pmean and stay
         replicated);
      4. optimizer.update on the shard triple (grad shard, moment shard
         from the sharded-in opt state, param shard sliced at
         axis_index('data') * shard_size — the ownership partition);
      5. `lax.all_gather(..., tiled=True)` of the updated shards over
         'data' back to the replicated params the next forward reads.

    Stage 2 moves step 3 inside the microbatch scan so the accumulator
    only ever holds the owned shard. tcfg.quantized_reduce inserts the
    EQuARX-style int8 wire emulation on each leaf's LOCAL contribution
    before it enters the reduction (one quantization hop).

    Requires model == 1: composing the ownership partition with TP-sharded
    weight shards is routed to the GSPMD form by DistributedTrainer."""
    if mesh.shape.get(MODEL_AXIS, 1) > 1:
        raise ValueError(
            "manual ZeRO step supports model == 1; the GSPMD path handles "
            "ZeRO x TP composition"
        )
    accum = pinned_grad_accum(tcfg)
    if tcfg.batch_size % accum != 0:
        raise ValueError(
            f"grad_accum={accum} must divide batch_size={tcfg.batch_size}"
        )
    dp = mesh.shape[DATA_AXIS]
    if (tcfg.batch_size // accum) % dp != 0:
        raise ValueError(
            f"microbatch {tcfg.batch_size // accum} not divisible "
            f"by data axis {dp}"
        )
    local_loss, seq, mp = _build_local_loss(
        mesh, cfg, tcfg, sp_strategy=sp_strategy, interpret=interpret
    )
    shard_axes = _zero_shard_axes(zero_pspecs)
    quantized = (
        bool(tcfg.quantized_reduce)
        if quantized_reduce is None
        else quantized_reduce
    )
    level = diag.resolve_telemetry_level(tcfg, supports_full=False)

    # The explicit collective pipeline, split so the telemetry hooks land
    # between its stages: seq pre-reduction -> one quantization wire hop
    # (with the error probe when it sees the FULL tree) -> per-leaf
    # scatter/pmean. Every site reports its measured per-replica ring wire
    # bytes to telemetry.counters (recorded once, at trace time, inside
    # DistributedTrainer's counting eval_shape — see counters.recording).

    def seq_reduce(grads):
        if seq <= 1:
            return grads

        def leaf(g):
            return tele_counters.timed_collective(
                "zero_seq_psum", SEQ_AXIS, "reduce",
                tele_counters.ring_allreduce_bytes(g, seq),
                lambda x: lax.psum(x, SEQ_AXIS), g, collective="psum",
            )

        return jax.tree_util.tree_map(leaf, grads)

    def quantize_tree(grads):
        from glom_tpu.parallel.quantized import quantize_dequantize

        return jax.tree_util.tree_map(quantize_dequantize, grads)

    def scatter_leaf(g, ax):
        if ax < 0:
            # No dp-divisible axis: the leaf stays replicated via a full
            # allreduce — a schedule detail comm_volume_model does NOT
            # price (it treats all of G as scattered), so the measured
            # counter is what keeps the drift honest.
            return tele_counters.timed_collective(
                "zero_pmean_fallback", DATA_AXIS, "reduce",
                tele_counters.ring_reduce_scatter_bytes(
                    g, dp, quantized=quantized
                ) * 2,
                lambda x: lax.pmean(x, DATA_AXIS), g, collective="pmean",
            )
        return tele_counters.timed_collective(
            "zero_psum_scatter", DATA_AXIS, "reduce",
            tele_counters.ring_reduce_scatter_bytes(g, dp, quantized=quantized),
            lambda x: lax.psum_scatter(
                x, DATA_AXIS, scatter_dimension=ax, tiled=True
            ) / dp,
            g, collective="psum_scatter", dim=ax,
        )

    def reduce_full(grads):
        """The whole-tree form (non-accumulated / post-accumulation):
        returns (g_shards, quant_rel_err or None)."""
        grads = seq_reduce(grads)
        qerr = None
        if quantized:
            dq = quantize_tree(grads)
            if level != "off":
                qerr = diag.quantization_error(grads, dq)
            grads = dq
        return (
            jax.tree_util.tree_map(scatter_leaf, grads, shard_axes),
            qerr,
        )

    def reduce_scatter_tree(grads):
        """The per-microbatch stage-2 hook: same pipeline, no probe (the
        hook's contract is tree -> tree; the per-microbatch error never
        sees the full accumulated gradient, so stamping it would claim a
        measurement that wasn't made)."""
        grads = seq_reduce(grads)
        if quantized:
            grads = quantize_tree(grads)
        return jax.tree_util.tree_map(scatter_leaf, grads, shard_axes)

    def shard_zeros(p, ax):
        if ax < 0:
            return jnp.zeros_like(p)
        shape = list(p.shape)
        shape[ax] //= dp
        return jnp.zeros(shape, p.dtype)

    def slice_shard(p, ax):
        if ax < 0:
            return p
        size = p.shape[ax] // dp
        return lax.dynamic_slice_in_dim(
            p, lax.axis_index(DATA_AXIS) * size, size, axis=ax
        )

    def gather_shard(p_shard, ax):
        if ax < 0:
            return p_shard
        return tele_counters.timed_collective(
            "zero_all_gather", DATA_AXIS, "gather",
            tele_counters.ring_all_gather_bytes(p_shard, dp),
            lambda x: lax.all_gather(x, DATA_AXIS, axis=ax, tiled=True),
            p_shard, collective="all_gather", dim=ax,
        )

    def sharded_grad_norm(g_shards):
        # sum-of-squares decomposes over the ownership partition: psum the
        # scattered leaves' local sums over 'data', count replicated leaves
        # once (identical on every replica).
        sq_scattered = jnp.zeros((), jnp.float32)
        sq_replicated = jnp.zeros((), jnp.float32)
        for g, ax in zip(
            jax.tree_util.tree_leaves(g_shards),
            jax.tree_util.tree_leaves(shard_axes),
        ):
            s = jnp.sum(jnp.square(g.astype(jnp.float32)))
            if ax < 0:
                sq_replicated = sq_replicated + s
            else:
                sq_scattered = sq_scattered + s
        return jnp.sqrt(lax.psum(sq_scattered, DATA_AXIS) + sq_replicated)

    # The quant-error probe exists only where the hop sees the full
    # accumulated gradient (reduce_full); the stage-2-with-accum corner
    # quantizes per microbatch inside the scan and stamps no error.
    probe_quant = (
        quantized and level != "off" and not (zero_stage >= 2 and accum > 1)
    )

    def update_body(params, opt_state, img, noise):
        qerr = None
        if accum > 1:
            # trainer.accumulate_grads on the LOCAL band — the strided
            # grouping applies per shard exactly as it does globally
            # (b_loc % accum == 0 is guaranteed by the checks above, so
            # local row j of microbatch i is global row k*b_loc + j with
            # the same i = j % accum). ZeRO-2 rides its stage-2 hook:
            # scatter each microbatch BEFORE accumulating, zeros at the
            # owned-shard shapes, so the buffer never holds a full leaf.
            from glom_tpu.train.trainer import accumulate_grads

            def scatter_microbatch(g):
                # One trace, `accum` executions: scale the measured
                # counters so they price the whole step's wire traffic.
                with tele_counters.scaled(accum):
                    return reduce_scatter_tree(g)

            gkw = (
                dict(
                    grad_transform=scatter_microbatch,
                    grad_init=lambda: jax.tree_util.tree_map(
                        shard_zeros, params, shard_axes
                    ),
                )
                if zero_stage >= 2
                else {}
            )
            loss_loc, grads = accumulate_grads(
                local_loss, params, img, noise, accum, **gkw
            )
            if zero_stage >= 2:
                g_shards = grads
            else:
                g_shards, qerr = reduce_full(grads)
        else:
            loss_loc, grads = jax.value_and_grad(local_loss)(params, img, noise)
            g_shards, qerr = reduce_full(grads)

        p_shards = jax.tree_util.tree_map(slice_shard, params, shard_axes)
        updates, new_opt = optimizer.update(g_shards, opt_state, p_shards)
        new_p_shards = optax.apply_updates(p_shards, updates)
        new_params = jax.tree_util.tree_map(
            gather_shard, new_p_shards, shard_axes
        )
        loss = lax.pmean(loss_loc, DATA_AXIS)
        metrics = {"loss": loss}
        if with_grad_norm or level != "off":
            # grad_norm is part of the scalars bundle on every path (it is
            # computed for the guard anyway): the fast-variant record must
            # carry the same keys here as on the GSPMD/manual steps.
            gnorm = sharded_grad_norm(g_shards)
            metrics["grad_norm"] = gnorm
        if level != "off":
            # In-region telemetry on the sharded triple: update norm via
            # the same ownership-partition decomposition as the grad norm;
            # param norm on the gathered (replicated) tree is collective-
            # free. The guard's where() runs on the gathered params and
            # the sharded opt state alike — the non-finite flag is built
            # from psum'd scalars, so it is replica-invariant.
            from glom_tpu.telemetry.diagnostics import nonfinite_flag

            metrics["update_norm"] = sharded_grad_norm(updates)
            metrics["param_norm"] = optax.global_norm(new_params)
            nonfinite = nonfinite_flag(loss, gnorm)
            if tcfg.nonfinite_policy == "skip":
                new_params = diag.guard_update(nonfinite, new_params, params)
                new_opt = diag.guard_update(nonfinite, new_opt, opt_state)
                metrics["skipped_nonfinite"] = nonfinite.astype(jnp.int32)
            metrics["nonfinite_step"] = nonfinite.astype(jnp.int32)
            if probe_quant:
                metrics["quant_rel_err"] = qerr
        return new_params, new_opt, metrics

    batch_spec = P(DATA_AXIS)
    param_spec = _manual_param_spec(mp)
    metric_keys = ["loss"]
    if with_grad_norm or level != "off":
        metric_keys.append("grad_norm")
    if level != "off":
        metric_keys += ["update_norm", "param_norm", "nonfinite_step"]
        if tcfg.nonfinite_policy == "skip":
            metric_keys.append("skipped_nonfinite")
        if probe_quant:
            metric_keys.append("quant_rel_err")
    update_sm = shard_map(
        update_body,
        mesh=mesh,
        in_specs=(param_spec, opt_pspecs, batch_spec, batch_spec),
        out_specs=(param_spec, opt_pspecs, {k: P() for k in metric_keys}),
        check_vma=False,
    )

    def train_step(state: TrainState, img: jnp.ndarray, rng: jax.Array):
        noise_rng = jax.random.fold_in(rng, state.step)
        noise = tcfg.noise_std * jax.random.normal(noise_rng, img.shape, img.dtype)
        new_params, new_opt, metrics = update_sm(
            state.params, state.opt_state, img, noise
        )
        metrics = dict(metrics, step=state.step)
        return TrainState(new_params, new_opt, state.step + 1), metrics

    return train_step
