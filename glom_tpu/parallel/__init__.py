"""Parallel runtime: mesh, shardings, and sequence-parallel consensus.

Strategy map (SURVEY.md §2.2 — everything here is absent in the reference):

  DP      sharding.batch_spec + GSPMD grad allreduce      (runtime.py)
  TP      sharding.ffw_specs('hidden') — Megatron-style    (sharding.py)
  EP-like sharding.ffw_specs('levels') — per-level groups  (sharding.py)
  SP      ring.py (exact ring attention over 'seq'),
          ulysses.py (all-to-all, L as heads),
          halo.py (local-radius neighbor exchange)
  PP      deliberately not provided: GLOM's L levels update
          SIMULTANEOUSLY each iteration (one scan step reads all levels and
          writes all levels), so there is no layer-sequential dependency to
          pipeline — a stage-over-levels pipeline would serialize what the
          hardware runs as one batched einsum. The EP-like 'levels' sharding
          above is the profitable way to split the L axis.
"""

from glom_tpu.parallel.halo import make_halo_consensus
from glom_tpu.parallel.manual import (
    make_manual_loss,
    make_manual_train_step,
    make_manual_zero_train_step,
    manual_supported,
)
from glom_tpu.parallel.mesh import initialize_multihost, make_mesh
from glom_tpu.parallel.ring import make_ring_consensus
from glom_tpu.parallel.runtime import (
    SP_STRATEGIES,
    DistributedTrainer,
    make_consensus_fn,
)
from glom_tpu.parallel.sharding import (
    batch_spec,
    denoise_param_specs,
    ffw_specs,
    glom_param_specs,
    levels_spec,
    opt_state_specs,
    to_named,
    zero_param_specs,
    zero_shard_axis,
)
from glom_tpu.parallel.ulysses import make_ulysses_consensus

__all__ = [
    "make_halo_consensus",
    "make_manual_loss",
    "make_manual_train_step",
    "make_manual_zero_train_step",
    "manual_supported",
    "initialize_multihost",
    "make_mesh",
    "make_ring_consensus",
    "SP_STRATEGIES",
    "DistributedTrainer",
    "make_consensus_fn",
    "batch_spec",
    "denoise_param_specs",
    "ffw_specs",
    "glom_param_specs",
    "levels_spec",
    "opt_state_specs",
    "zero_param_specs",
    "zero_shard_axis",
    "to_named",
    "make_ulysses_consensus",
]
