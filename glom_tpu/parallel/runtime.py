"""The distributed training runtime: mesh + shardings + pjit-ed train step.

This is the TPU-native replacement for the torch-DDP/NCCL layer the
reference never had (SURVEY.md §2.2): the data-parallel gradient allreduce,
the TP psum, and the SP ring/halo/all-to-all all ride ICI, emitted by XLA
from sharding annotations (GSPMD) or written explicitly in the shard_map
consensus ops.

Composition:
  * DP  — batch sharded on 'data'; XLA inserts the grad allreduce.
  * TP  — grouped-FFW hidden axis sharded on 'model' (sharding.py).
  * SP  — 'seq' axis is MANUAL: the consensus_fn built here is a shard_map
          region (ring/ulysses/halo) over 'seq' while 'data'/'model' stay
          automatic; the n axis of the level state is pinned to 'seq' by the
          shard_map in/out specs and flows through the scan carry.
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from glom_tpu.models.core import ConsensusFn
from glom_tpu.parallel.halo import make_halo_consensus
from glom_tpu.parallel.manual import make_manual_train_step, manual_supported
from glom_tpu.parallel.mesh import make_mesh
from glom_tpu.parallel.ring import make_ring_consensus
from glom_tpu.parallel.sharding import (
    batch_spec,
    denoise_param_specs,
    opt_state_specs,
    to_named,
    zero_param_specs,
)
from glom_tpu.parallel.ulysses import make_ulysses_consensus
from glom_tpu.telemetry import diagnostics as diag
from glom_tpu.train.trainer import (
    TrainState,
    ZeroShardings,
    create_train_state,
    fit_loop,
    make_train_step,
    pinned_grad_accum,
    resolve_quantized_reduce,
    resolve_zero_stage,
)
from glom_tpu.utils.config import GlomConfig, MeshConfig, TrainConfig
from glom_tpu.utils.helpers import halo_supported

SP_STRATEGIES = ("none", "ring", "ulysses", "halo", "auto")


# Ulysses-vs-ring mechanism (the model BEHIND the measured crossover, not a
# magic number): total attention FLOPs are identical (2 * n^2/seq * L * d per
# einsum either way) and so is collective volume (n*d*L/seq in, same out) —
# the difference is the per-level similarity WORKING SET. Ulysses runs dense
# full-row attention on L/seq levels, so its f32 similarity block is n^2 * 4
# bytes per level; while that streams through VMEM the big matmuls run at
# full MXU rate and Ulysses wins on granularity (fewer, larger matmuls, ONE
# softmax instead of seq-1 online-combine passes). Past the VMEM-resident
# scale the similarity spills to HBM-streamed tiles and the advantage
# inverts — ring's [n/seq, n/seq] chunks stay resident at any n. The
# measured table (results/sp_crossover.jsonl, v5e) brackets the flip
# between n=1024 (4MB sim, Ulysses >= ring at every seq) and n=4096 (64MB,
# ring wins ~2.1x at every seq); n^2 * 4 <= 16MB -> n <= 2048 encodes it.
# The model is d-, L-, and batch-independent: d scales only the
# linear-in-n k/v tiles, and L/seq and batch multiply the NUMBER of
# independent per-(batch, level) attention instances identically on both
# sides — each instance's resident similarity block is still n^2 * 4
# (instances stream through VMEM sequentially; total bytes touched grow
# with b but the per-instance working set that decides spill does not).
# The committed rows are B=1; bench_sp_crossover.py carries the pod
# (d=1024, L=12), L=6, and batched (B=8) shapes so every independence
# claim stays re-measurable. tests/test_parallel.py asserts this
# predicate against every measured row of the committed table.
_ULYSSES_SIM_BUDGET = 16 * 1024 * 1024


def ulysses_preferred(n: int) -> bool:
    """True when Ulysses' full-row similarity block is VMEM-scale (see the
    working-set model above) — the measured ring/Ulysses crossover.
    STRICT inequality: the committed table brackets the flip between
    n=1024 and n=4096, so the exactly-at-budget point n=2048 (16MB) is
    UNMEASURED — auto-selection keeps the prior ring behavior there until
    an sp_crossover row for n=2048 lands (ADVICE round 5, low)."""
    return n * n * 4 < _ULYSSES_SIM_BUDGET


def select_sp_strategy(cfg: GlomConfig, seq: int) -> str:
    """Resolve sp_strategy='auto': pick the SP mechanism from the config's
    geometry and the measured ring-vs-Ulysses crossover (the working-set
    model above; results/sp_crossover.jsonl):

      * local radius with one-hop-coverable shards -> halo (neighbor-row
        exchange only; the cheapest exact form, by construction);
      * global (or halo-impossible) small/mid n -> Ulysses when the levels
        axis divides the seq axis: measured 4.2x over ring at n=256/seq=8,
        2.0x at n=1024/seq=8, parity at n=1024/seq=2 (L plays the role of
        heads — the all-to-all trades n-sharding for exact L-sharding);
      * long rows -> ring: at n=4096 Ulysses loses 2.1x (each shard then
        runs FULL-n attention on L/seq levels, and the spilled similarity
        working set dwarfs the ring's ppermute overlap).
    """
    if seq <= 1:
        return "none"
    radius = float(cfg.local_consensus_radius)
    if radius > 0 and halo_supported(seq, cfg.num_patches_side, radius):
        return "halo"
    if cfg.levels % seq == 0 and ulysses_preferred(cfg.num_patches):
        return "ulysses"
    return "ring"


def effective_sp_strategy(cfg: GlomConfig, seq: int, strategy: str) -> str:
    """The strategy a config ACTUALLY runs — THE single source of the
    resolution policy (both consensus-fn builders and the trainers' metric
    logging call this, so a run can never train on a different collective
    pattern than its records claim): resolves 'auto' through the selector
    and applies the exactness fallbacks (impossible halo, indivisible
    Ulysses -> ring, which is exact for any geometry). Downgrades of an
    EXPLICITLY requested strategy warn; 'auto' resolves silently (picking
    is its job). Idempotent: re-resolving an already-effective strategy is
    a no-op, so the trainers' up-front resolve suppresses double warnings.
    """
    if strategy not in SP_STRATEGIES:
        raise ValueError(
            f"unknown SP strategy {strategy!r}; one of {SP_STRATEGIES}"
        )
    if strategy == "auto":
        return select_sp_strategy(cfg, seq)
    if seq <= 1:
        return "none"
    radius = float(cfg.local_consensus_radius)
    if strategy == "halo" and not halo_supported(
        seq, cfg.num_patches_side, radius
    ):
        # Halo is only the cheaper special case when one-hop neighbor rows
        # cover the radius; fall back instead of crashing the config
        # (BASELINE config 3: radius 7 on an 8-row grid, seq=2).
        warnings.warn(
            f"halo consensus unsupported (radius={radius}, "
            f"side={cfg.num_patches_side}, seq={seq}); falling back to "
            "ring consensus",
            stacklevel=3,
        )
        return "ring"
    if strategy == "ulysses" and cfg.levels % seq != 0:
        warnings.warn(
            f"ulysses needs levels ({cfg.levels}) divisible by the seq "
            f"axis ({seq}); using ring (identical result, different "
            "collectives)",
            stacklevel=3,
        )
        return "ring"
    return strategy


def make_consensus_fn(
    mesh, cfg: GlomConfig, strategy: str, axis_name: str = "seq"
) -> Optional[ConsensusFn]:
    """Build the sequence-parallel consensus op for `strategy`, or None for
    the dense/GSPMD default. Resolution (auto + fallbacks) happens in
    effective_sp_strategy — this is construction only."""
    strategy = effective_sp_strategy(cfg, mesh.shape[axis_name], strategy)
    if strategy == "none":
        return None
    if strategy == "ring":
        return make_ring_consensus(
            mesh,
            attend_self=cfg.consensus_self,
            side=cfg.num_patches_side,
            radius=float(cfg.local_consensus_radius),
            axis_name=axis_name,
        )
    if strategy == "ulysses":
        return make_ulysses_consensus(
            mesh,
            attend_self=cfg.consensus_self,
            side=cfg.num_patches_side,
            radius=float(cfg.local_consensus_radius),
            axis_name=axis_name,
        )
    return make_halo_consensus(
        mesh,
        attend_self=cfg.consensus_self,
        side=cfg.num_patches_side,
        radius=float(cfg.local_consensus_radius),
        axis_name=axis_name,
    )


def make_engine_meshes(
    scfg, n_engines: int, devices: Optional[list] = None
) -> list:
    """One serve mesh (or None for single-device engines) per engine
    replica: the device list partitions into contiguous
    (mesh_data * mesh_seq)-sized groups (parallel/mesh.py
    replica_device_groups), each group hosting one InferenceEngine behind
    the shared-admission batcher (multi-engine fan-out, docs/SERVING.md).
    Lives here because it is mesh + spec RESOLUTION, the seam ROADMAP
    item 5's unified runtime extracts — a new serve parallelism should
    land in one place, not per caller."""
    import jax as _jax

    from glom_tpu.parallel.mesh import replica_device_groups
    from glom_tpu.parallel.serve_mesh import make_serve_mesh

    if n_engines < 1:
        raise ValueError(f"n_engines {n_engines} must be >= 1")
    per = scfg.mesh_data * scfg.mesh_seq
    if per == 1:
        return [None] * n_engines
    devices = devices if devices is not None else _jax.devices()
    groups = replica_device_groups(devices, per)
    if len(groups) < n_engines:
        raise ValueError(
            f"{len(devices)} devices host only {len(groups)} "
            f"{per}-device engine replicas; {n_engines} requested"
        )
    return [make_serve_mesh(scfg, g) for g in groups[:n_engines]]


def engine_mesh_for(
    scfg, index: int, devices: Optional[list] = None
):
    """The mesh for ONE engine replica by fleet index — the elastic
    scale-out's device-group resolution (serve/elastic.py): a spawned
    replica takes the NEXT contiguous group the static partitioning
    would have given it, so a fleet that grew at runtime occupies
    exactly the devices `--engines N` would have. Raises (loudly — the
    autoscaler's spawn_rollback path) when the device pool has no group
    `index` left; returns None on the single-device route."""
    if index < 0:
        raise ValueError(f"index {index} must be >= 0")
    return make_engine_meshes(scfg, index + 1, devices=devices)[index]


class DistributedTrainer:
    """Sharded trainer over an explicit device mesh.

    `sp_strategy` selects how consensus attention is parallelized over the
    'seq' axis; 'none' leaves everything to GSPMD (which will all-gather k/v
    — correct, but the explicit ring/halo beat it at scale).
    """

    def __init__(
        self,
        cfg: GlomConfig,
        tcfg: TrainConfig,
        mesh_cfg: MeshConfig,
        *,
        sp_strategy: str = "none",
        tp_axis: str = "hidden",
        optimizer: Optional[optax.GradientTransformation] = None,
        metrics_writer=None,
        devices: Optional[list] = None,
    ):
        if tcfg.batch_size % mesh_cfg.data != 0:
            raise ValueError(
                f"batch {tcfg.batch_size} not divisible by data axis {mesh_cfg.data}"
            )
        accum_base = pinned_grad_accum(tcfg)
        if (
            accum_base > 1
            and (tcfg.batch_size // accum_base) % mesh_cfg.data != 0
        ):
            # Both step paths (GSPMD and manual) scan over microbatches;
            # an indivisible microbatch would silently pad/idle devices.
            raise ValueError(
                f"microbatch {tcfg.batch_size // accum_base} "
                f"(batch {tcfg.batch_size} / grad_accum {accum_base}) "
                f"not divisible by data axis {mesh_cfg.data}"
            )
        if cfg.num_patches % mesh_cfg.seq != 0:
            raise ValueError(
                f"patches {cfg.num_patches} not divisible by seq axis {mesh_cfg.seq}"
            )
        self.cfg = cfg
        self.tcfg = tcfg
        self.mesh_cfg = mesh_cfg
        self.mesh = make_mesh(mesh_cfg, devices)
        self.metrics_writer = metrics_writer
        # Resolve 'auto' and the exactness fallbacks ONCE, pass the
        # resolved mechanism everywhere, and report it in every metrics
        # record — a run must not train on a different collective pattern
        # than its logs claim (round-3 weak #6: the fallbacks only warned).
        self.sp_strategy = effective_sp_strategy(cfg, mesh_cfg.seq, sp_strategy)
        sp_strategy = self.sp_strategy

        # use_pallas routes through the fully-manual shard_map path (the
        # kernels are per-device-legal there), including hidden-axis TP
        # (Megatron psum hand-written in the manual body). Only the
        # EP-style 'levels' TP stays GSPMD-only.
        self.use_manual = bool(tcfg.use_pallas)
        from glom_tpu.utils.compat import HAS_PARTIAL_MANUAL

        if not self.use_manual and mesh_cfg.seq > 1 and not HAS_PARTIAL_MANUAL:
            # Old-jax fallback: the GSPMD step would nest a partial-manual
            # consensus shard_map (manual 'seq', auto 'data'/'model'),
            # which that jax line cannot partition (see compat.py). The
            # fully-manual region runs the identical per-shard bodies with
            # every collective explicit, so SP configs route there; with
            # use_pallas=False it composes the plain-XLA ops.
            self.use_manual = True
        if self.use_manual and not manual_supported(self.mesh, tp_axis):
            warnings.warn(
                "use_pallas=True with tp_axis='levels': the manual fused path "
                "implements hidden-axis TP only, and the fused kernels have no "
                "GSPMD partitioning rule for TP-sharded weights; falling back "
                "to the GSPMD path without Pallas",
                stacklevel=2,
            )
            self.use_manual = False
            # Clear the flag for the GSPMD step too — glom_forward would
            # otherwise emit Mosaic custom calls under TP-sharded weights,
            # exactly the illegal configuration this fallback avoids.
            tcfg = dataclasses.replace(tcfg, use_pallas=False)
            self.tcfg = tcfg

        consensus_fn = (
            None if self.use_manual else make_consensus_fn(self.mesh, cfg, sp_strategy)
        )

        # Telemetry level resolution ONCE the step path is known (same
        # discipline as sp_strategy: the stamped level is the resolved
        # one). The manual shard_map path has no aux channel for "full" —
        # degrade loudly here, then pass the RESOLVED level down so the
        # step builders' re-resolve is a silent no-op.
        self.telemetry_level = diag.resolve_telemetry_level(
            tcfg, supports_full=not self.use_manual
        )
        if self.telemetry_level != tcfg.telemetry_level:
            tcfg = dataclasses.replace(
                tcfg, telemetry_level=self.telemetry_level
            )
            self.tcfg = tcfg

        # Resolve the backward path for the metric records (round-4 weak
        # #3: the vjp dispatch must be as visible as the SP strategy). The
        # manual shard_map bodies never reach the whole-loop VJP; with a
        # seq-sharded consensus (manual OR GSPMD) the backward is the SP
        # collective op's own transpose — labeled 'scan_sharded'
        # consistently on both paths (the mechanism itself is in
        # sp_strategy).
        self.grad_accum = accum_base
        if self.use_manual and mesh_cfg.seq > 1:
            self.vjp_path = "scan_sharded"
        elif self.use_manual:
            from glom_tpu.models.core import resolve_vjp_path
            from glom_tpu.train.trainer import resolve_route_keys

            k, itemsize = resolve_route_keys(cfg, tcfg)
            # seq=1/mp=1 manual shards dispatch to the whole-loop VJP at
            # the shard-local batch when admissible (manual._use_loop_vjp
            # makes the same resolve_vjp_path call) — the label must
            # follow the dispatch; TP shards (mp>1) stay scan-only.
            self.vjp_path = resolve_vjp_path(
                cfg,
                tcfg.batch_size // accum_base // mesh_cfg.data,
                k,
                remat=tcfg.remat,
                use_pallas=True,
                itemsize=itemsize,
                scan_only=mesh_cfg.model > 1,
            )
        else:
            self.vjp_path = None  # filled from make_train_step in build()

        key = jax.random.PRNGKey(tcfg.seed)
        self.rng, init_key = jax.random.split(key)

        # ZeRO resolution (single source: resolve_zero_stage) BEFORE the
        # state layout is built — the stage decides the optimizer-state
        # sharding the train state is device_put into.
        self.zero_stage = resolve_zero_stage(tcfg, mesh_cfg.data)
        self.quantized_reduce = resolve_quantized_reduce(tcfg, mesh_cfg.data)
        if (
            self.zero_stage >= 1
            and self.use_manual
            and mesh_cfg.model > 1
        ):
            # The explicit manual ZeRO region does not compose the
            # ownership partition with TP-sharded weight shards; the GSPMD
            # form does, but mixing per-step paths would desync state
            # layout from step fn. Degrade loudly.
            warnings.warn(
                "zero_stage >= 1 on the manual (use_pallas) path supports "
                "model == 1 only; running this mesh with zero_stage=0 "
                "(replicated optimizer state)",
                stacklevel=2,
            )
            self.zero_stage = 0
        if self.quantized_reduce and self.use_manual and self.zero_stage == 0:
            # The plain manual step's DP grad reduction is the shard_map
            # transpose psum — there is no hook to quantize each local
            # contribution before it (the manual ZeRO step has one, and
            # the GSPMD step emulates the receive side). Degrade loudly
            # rather than stamp an emulation that didn't run.
            warnings.warn(
                "quantized_reduce on the manual path requires zero_stage "
                ">= 1 (the explicit reduce-scatter carries the emulation "
                "hook); running with exact f32 reduction",
                stacklevel=2,
            )
            self.quantized_reduce = False

        # Host-side init, then device_put into the sharded layout. (At true
        # pod scale you would jit the init with out_shardings instead; this
        # keeps the init path simple and testable.)
        state, self.optimizer = create_train_state(init_key, cfg, tcfg, optimizer)
        pspecs = denoise_param_specs(tp_axis)
        if self.zero_stage >= 1:
            # Optimizer moments live 1/dp per replica on each leaf's
            # zero_shard_axis; global SHAPES are unchanged, so checkpoints
            # restore across zero_stage / dp changes (test_resilience).
            zpspecs = zero_param_specs(state.params, mesh_cfg.data, tp_axis)
            opt_specs = opt_state_specs(state.opt_state, zpspecs)
        else:
            zpspecs = None
            opt_specs = opt_state_specs(state.opt_state, pspecs)
        state_specs = TrainState(
            params=pspecs,
            opt_state=opt_specs,
            step=P(),
        )
        self.state_shardings = to_named(self.mesh, state_specs)
        self.batch_sharding = NamedSharding(self.mesh, batch_spec())
        self.state = jax.device_put(state, self.state_shardings)
        self.zero_shardings = (
            None
            if zpspecs is None
            else ZeroShardings(
                grads=to_named(self.mesh, zpspecs),
                params=self.state_shardings.params,
            )
        )
        abstract_state = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype), state
        )

        def build(with_grad_norm):
            if self.use_manual and self.zero_stage >= 1:
                from glom_tpu.parallel.manual import make_manual_zero_train_step

                fn = make_manual_zero_train_step(
                    self.mesh, cfg, tcfg, self.optimizer,
                    zero_stage=self.zero_stage,
                    zero_pspecs=zpspecs,
                    opt_pspecs=opt_specs,
                    sp_strategy=sp_strategy,
                    with_grad_norm=with_grad_norm,
                    quantized_reduce=self.quantized_reduce,
                )
            elif self.use_manual:
                fn = make_manual_train_step(
                    self.mesh, cfg, tcfg, self.optimizer,
                    sp_strategy=sp_strategy, with_grad_norm=with_grad_norm,
                )
            else:
                # scan_only: the whole-loop Pallas custom_vjp has no GSPMD
                # partitioning rule, so this build must neither dispatch
                # it nor auto-split the batch chasing it — the single-chip
                # routing heuristics would otherwise evaluate against the
                # GLOBAL batch here (ADVICE round 5, medium).
                fn = make_train_step(
                    cfg, tcfg, self.optimizer, consensus_fn=consensus_fn,
                    with_grad_norm=with_grad_norm,
                    zero_stage=self.zero_stage,
                    zero_shardings=self.zero_shardings,
                    quantized_reduce=self.quantized_reduce,
                    scan_only=True,
                )
                # A GSPMD SP consensus_fn means the backward runs the
                # sharded op's transpose — same label as the manual SP
                # path, not the generic custom-consensus 'scan_dense'.
                self.vjp_path = (
                    "scan_sharded" if consensus_fn is not None else fn.vjp_path
                )
                self.grad_accum = fn.grad_accum
            self._raw_step = fn
            return jax.jit(
                fn,
                in_shardings=(self.state_shardings, self.batch_sharding, None),
                out_shardings=(self.state_shardings, None),
                donate_argnums=(0,),
            )

        self._step = build(True)
        self._step_fast = build(False)
        # Persistent across fit() calls: span 2+ of a checkpointed run is
        # warm, and its first steps are steady-state samples, not compiles.
        self._compile_tracker = set()

        # Static observability record, computed AFTER build() so the
        # comm-volume model prices the grad_accum the step actually runs
        # (GSPMD auto-accum can raise it). Pure analytics over abstract
        # shapes — recorded identically with or without a chip.
        from glom_tpu.utils.metrics import (
            comm_volume_model,
            live_bytes_model,
            tree_bytes_per_replica,
        )

        axis_sizes = dict(zip(self.mesh_cfg.axis_names, self.mesh_cfg.shape))
        grad_specs = (
            zpspecs if (self.zero_stage >= 2 and zpspecs is not None) else pspecs
        )
        mem = live_bytes_model(
            abstract_state.params,
            abstract_state.opt_state,
            axis_sizes=axis_sizes,
            param_specs=pspecs,
            opt_specs=opt_specs,
            grad_specs=grad_specs,
        )
        # Wire payload for the DP gradient path: the full (data-replicated)
        # grad bytes each replica contributes — model/seq sharding already
        # divided out, 'data' not (that division is what the collective does).
        wire_bytes = tree_bytes_per_replica(
            abstract_state.params, pspecs, axis_sizes
        )
        # Per-collective wall-time mode (docs/OBSERVABILITY.md, "Capacity
        # observatory"): resolved ONCE like telemetry_level and stamped.
        # Only the manual zero>=1 route has registered sites; everywhere
        # else the mode resolves to "off" (stamped — a record must never
        # claim a timing harness that didn't run). "full" degrades to
        # "sampled" loudly: the jit-on-first-call trainer has no AOT seam
        # for the io_callback brackets (the serve engine's has).
        from glom_tpu.telemetry.counters import resolve_collective_timing

        timing_sites_reachable = self.use_manual and self.zero_stage >= 1
        if timing_sites_reachable:
            self.collective_timing = resolve_collective_timing(
                tcfg.collective_timing,
                supports_full=False,
                path="the manual trainer",
            )
        else:
            resolve_collective_timing(tcfg.collective_timing)  # validate
            if tcfg.collective_timing != "off":
                warnings.warn(
                    "collective_timing has no registered sites on this "
                    "route (GSPMD, or manual zero_stage 0) — resolving "
                    "'off'; the stamped mode is the resolved one",
                    stacklevel=2,
                )
            self.collective_timing = "off"
        self.collective_sampler = None
        self._static_record = {
            "zero_stage": self.zero_stage,
            "quantized_reduce": self.quantized_reduce,
            "telemetry_level": self.telemetry_level,
            "collective_timing": self.collective_timing,
            **mem,
            **comm_volume_model(
                wire_bytes,
                wire_bytes,
                self.mesh_cfg.data,
                self.zero_stage,
                quantized=self.quantized_reduce,
                grad_accum=self.grad_accum,
            ),
        }

        # MEASURED collective counters (telemetry/counters.py): one
        # abstract trace of the step with the recording context active —
        # the manual ZeRO path's explicit psum/psum_scatter/all_gather
        # sites report their actual per-replica ring wire bytes, and the
        # measured-vs-modeled drift is stamped on every record (the model
        # silently diverging from the emitted collectives is itself the
        # bug telemetry exists to catch). Gated on telemetry_level (the
        # extra trace is not free) and on the path that HAS explicit
        # sites; GSPMD steps carry the model only.
        if (
            self.telemetry_level != "off" or self.collective_timing != "off"
        ) and timing_sites_reachable:
            from glom_tpu.telemetry.counters import (
                CollectiveCounters,
                comm_drift,
                recording,
            )

            counters = CollectiveCounters()
            abstract_batch = jax.ShapeDtypeStruct(
                (tcfg.batch_size, cfg.channels, cfg.image_size, cfg.image_size),
                jnp.float32,
            )
            with recording(counters):
                jax.eval_shape(
                    self._raw_step, abstract_state, abstract_batch,
                    jax.random.PRNGKey(0),
                )
            measured = counters.totals()
            self._static_record.update(measured)
            self._static_record.update(
                comm_drift(measured, self._static_record)
            )
            if self.collective_timing != "off":
                # The sampled-mode harness (telemetry/comm_time.py): the
                # counting trace just populated the site registry (site,
                # axis, shard-local shape, scatter/gather dim) — every
                # collective_timing_interval-th fit-loop logging boundary
                # re-dispatches each site as its own timed sub-graph and
                # stamps "collective_time" records with the α-β
                # comm_time_model drift (fit() wires the probe).
                from glom_tpu.telemetry.comm_time import (
                    CollectiveTimeSampler,
                )

                self.collective_sampler = CollectiveTimeSampler(
                    self.mesh,
                    counters.sites,
                    interval=tcfg.collective_timing_interval,
                )

        from glom_tpu.tracing.memory import model_live_bytes_total

        self._model_live_bytes = model_live_bytes_total(self._static_record)

    def step(self, batch: np.ndarray):
        # device_put on the host array shards directly host->devices in one
        # transfer (no staging of the full batch on device 0 first); a no-op
        # when the batch was already staged by prefetch_to_device.
        batch = jax.device_put(batch, self.batch_sharding)
        self.rng, step_rng = jax.random.split(self.rng)
        self.state, metrics = self._step(self.state, batch, step_rng)
        return self._annotate(metrics)

    def _annotate(self, metrics) -> dict:
        """Static routing facts attached OUTSIDE jit (strings can't ride
        the compiled metrics dict) — same record shape as Trainer's,
        including the watchdog backend state."""
        from glom_tpu.telemetry.watchdog import backend_record

        metrics = dict(metrics)
        metrics["sp_strategy"] = self.sp_strategy
        metrics["vjp_path"] = self.vjp_path
        metrics["grad_accum"] = self.grad_accum
        metrics.update(self._static_record)
        metrics.update(backend_record())
        return metrics

    def step_fast(self, batch: np.ndarray):
        """Non-logging iteration: no grad-norm sweep."""
        batch = jax.device_put(batch, self.batch_sharding)
        self.rng, step_rng = jax.random.split(self.rng)
        self.state, metrics = self._step_fast(self.state, batch, step_rng)
        return self._annotate(metrics)

    def _memory_record(self) -> dict:
        """Live HBM watermarks (device 0 of the mesh) reconciled against
        the analytic PER-REPLICA live-bytes model — the measured
        counterpart of the `*_bytes_per_replica` keys, same discipline as
        the collective counters' comm_model_drift."""
        from glom_tpu.tracing.memory import memory_record

        return memory_record(
            self._model_live_bytes, device=self.mesh.devices.flat[0]
        )

    def collective_time_records(self, *, force: bool = False) -> list:
        """Stamped "collective_time" rows from the sampled timing harness
        (empty off-mode, and between sampling intervals unless `force`).
        fit() drains this at every logging boundary; direct step() drivers
        (benches) call it themselves."""
        if self.collective_sampler is None:
            return []
        path = f"train-zero{self.zero_stage}"
        if force:
            from glom_tpu.telemetry.comm_time import collective_time_records

            return collective_time_records(
                self.collective_sampler.sample(), path=path, mode="sampled"
            )
        return self.collective_sampler.maybe_sample(path=path)

    def fit(
        self,
        data: Iterator,
        num_steps: int,
        *,
        log_every: int = 10,
        prefetch: int = 0,
        trace_capture=None,
    ) -> list[dict]:
        """prefetch > 0 stages that many upcoming batches SHARDED on their
        target devices from a background thread (the step's device_put then
        sees already-committed shards and is a no-op).

        CAUTION: the wrap is PER CALL — repeated fit(prefetch=N) over one
        shared iterator discards staged batches at every boundary; wrap
        once with data.prefetch_to_device for that pattern (see
        train/cli.py)."""
        if prefetch > 0:
            from glom_tpu.data import prefetch_to_device

            data = prefetch_to_device(
                data, size=prefetch, sharding=self.batch_sharding
            )
        return fit_loop(
            self.step,
            data,
            num_steps,
            log_every=log_every,
            metrics_writer=self.metrics_writer,
            step_fast=self.step_fast,
            compile_tracker=self._compile_tracker,
            trace_capture=trace_capture,
            memory_probe=self._memory_record,
            aux_records_probe=(
                self.collective_time_records
                if self.collective_sampler is not None else None
            ),
        )
