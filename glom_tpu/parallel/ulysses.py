"""Ulysses-style sequence parallelism for consensus attention.

Consensus attention is INDEPENDENT per level (sim is [b, L, n, n] with no
cross-level terms — reference :58), so the L axis plays exactly the role
heads play in Ulysses: an `all_to_all` trades n-sharding for L-sharding,
each shard runs the plain dense attention over the FULL patch axis for its
L/S levels, and a second all_to_all restores n-sharding. Exact (not an
approximation), two collectives per call, and the inner op is the
well-fused dense kernel.

Prefer this when L % S == 0 and n^2 * L/S fits in memory; prefer the ring
(ring.py) when n is huge or L is small/indivisible.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
from jax import lax

from glom_tpu.utils.compat import axis_size, shard_map
from glom_tpu.ops.consensus import consensus_attention


def ulysses_consensus_shard(
    x,
    *,
    axis_name: str,
    attend_self: bool,
    side: Optional[int] = None,
    radius: float = 0.0,
):
    """Per-shard body (under shard_map, n sharded over `axis_name`).

    x: [b, n_loc, L, d] -> [b, n_loc, L, d]; requires S | L.
    The local-radius mask (side, radius) is computed IN-GRAPH from iota
    inside the shard (ops.consensus.iota_local_mask) — no [n, n] host
    buffer is built at trace time or embedded per-shard as a constant
    (round-4 weak #5: the old local_mask= plumbing reintroduced the
    reference's O(n^2) init cost, reference :42-52, on this path).
    """
    S = axis_size(axis_name)
    L = x.shape[2]
    if L % S != 0:
        raise ValueError(f"Ulysses needs levels ({L}) divisible by mesh axis ({S})")
    # [b, n_loc, L, d] -> [b, n, L/S, d]: gather the patch axis, scatter levels
    y = lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)
    out = consensus_attention(
        y, attend_self=attend_self, side=side, radius=radius
    )
    # [b, n, L/S, d] -> [b, n_loc, L, d]
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)


def make_ulysses_consensus(
    mesh,
    *,
    attend_self: bool,
    side: Optional[int] = None,
    radius: float = 0.0,
    axis_name: str = "seq",
):
    """Build a consensus_fn: [b, n, L, d] -> [b, n, L, d], n sharded over
    `axis_name`. Drop-in for glom_forward(consensus_fn=...)."""
    fn = partial(
        ulysses_consensus_shard,
        axis_name=axis_name,
        attend_self=attend_self,
        side=side,
        radius=radius,
    )
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=jax.sharding.PartitionSpec(None, axis_name, None, None),
        out_specs=jax.sharding.PartitionSpec(None, axis_name, None, None),
        axis_names={axis_name},
    )
