"""Partition specs: how params, optimizer state, and batches lay out on the
mesh. GSPMD does the rest — annotate, and XLA inserts the collectives
(allreduce for DP grads, psum for the TP contraction) over ICI.

Tensor parallelism (TP) shards the grouped-FFW HIDDEN axis (Megatron-style
column-then-row): w1 [G, d, f] and b1 [G, f] shard f across 'model'; w2
[G, f, d] shards its f contraction axis, so XLA emits one psum per FFW on
the second matmul's output. Embeddings and init_levels stay replicated —
`d` appears inside consensus attention, and sharding it there would trade
one cheap psum for many.

Expert-parallel analog (SURVEY.md §2.2: EP n/a — no MoE in GLOM): the
closest structure is the per-level grouped FFW, whose G axis is expert-like
and shardable. `tp_axis="levels"` shards G instead of the hidden axis —
levels are fully independent in the FFWs, so this needs NO collective in
the FFW at all (the analog of expert dispatch is a static slice).
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from glom_tpu.ops.ffw import GroupedFFWParams
from glom_tpu.ops.patch import LinearParams
from glom_tpu.models.core import GlomParams
from glom_tpu.train.objectives import DenoiseParams


def ffw_specs(tp_axis: str = "hidden") -> GroupedFFWParams:
    if tp_axis == "hidden":
        return GroupedFFWParams(
            w1=P(None, None, "model"),
            b1=P(None, "model"),
            w2=P(None, "model", None),
            b2=P(None, None),
        )
    if tp_axis == "levels":  # EP-style: shard the independent level groups
        return GroupedFFWParams(
            w1=P("model", None, None),
            b1=P("model", None),
            w2=P("model", None, None),
            b2=P("model", None),
        )
    raise ValueError(f"tp_axis must be 'hidden' or 'levels', got {tp_axis!r}")


def glom_param_specs(tp_axis: str = "hidden") -> GlomParams:
    # In 'levels' (EP-style) mode only bottom_up (G = L) shards its group
    # axis; top_down has G = L - 1, coprime with L, so no mesh size divides
    # both — it shards its hidden axis instead.
    td_axis = "hidden" if tp_axis == "levels" else tp_axis
    return GlomParams(
        token_embed=LinearParams(w=P(None, None), b=P(None)),
        pos_emb=P(None, None),
        init_levels=P(None, None),
        bottom_up=ffw_specs(tp_axis),
        top_down=ffw_specs(td_axis),
    )


def denoise_param_specs(tp_axis: str = "hidden") -> DenoiseParams:
    return DenoiseParams(
        glom=glom_param_specs(tp_axis),
        to_pixels=LinearParams(w=P(None, None), b=P(None)),
    )


def batch_spec() -> P:
    """[b, c, H, W] image batches shard on the data axis."""
    return P("data", None, None, None)


def levels_spec() -> P:
    """[b, n, L, d] column state: batch on 'data', patch axis on 'seq'."""
    return P("data", "seq", None, None)


def zero_shard_axis(shape, base_spec: P, dp: int):
    """The axis a ZeRO update shards over 'data' for one param-shaped leaf:
    the LARGEST free axis (not already taken by the base TP spec) whose
    global dim divides by dp. None when no axis qualifies — that leaf's
    optimizer state stays replicated (and the memory model reports the
    achieved, not the ideal, savings). Largest-first maximizes the bytes
    actually sharded: at d=1024/mult=4 the hidden axis f=4096 shards even
    when 'model' took a different axis."""
    if dp <= 1:
        return None
    entries = tuple(base_spec) + (None,) * (len(shape) - len(tuple(base_spec)))
    best = None
    for ax, dim in enumerate(shape):
        if entries[ax] is None and dim % dp == 0:
            if best is None or dim > shape[best]:
                best = ax
    return best


def _zero_leaf_spec(shape, base_spec: P, dp: int) -> P:
    ax = zero_shard_axis(shape, base_spec, dp)
    if ax is None:
        return base_spec
    entries = list(tuple(base_spec) + (None,) * (len(shape) - len(tuple(base_spec))))
    entries[ax] = "data"
    return P(*entries)


def zero_param_specs(params: DenoiseParams, dp: int, tp_axis: str = "hidden") -> Any:
    """Param-shaped spec tree for the ZeRO-sharded layout: the base TP
    layout with 'data' added per leaf on its zero_shard_axis. Used for the
    optimizer-state moments, the reduce-scattered gradients, and the
    transient updates — everything that is param-shaped but owned 1/dp per
    replica. Params themselves keep the base (data-replicated) layout; the
    all-gather after the shard update is what restores it."""
    base = denoise_param_specs(tp_axis)
    return jax.tree_util.tree_map(
        lambda spec, arr: _zero_leaf_spec(np.shape(arr), spec, dp),
        base,
        params,
        is_leaf=lambda x: isinstance(x, P),
    )


def opt_state_specs(abstract_opt_state: Any, param_specs: DenoiseParams) -> Any:
    """Optimizer-state spec tree: moment buffers (DenoiseParams-shaped
    subtrees, e.g. Adam's mu/nu) follow the param layout; scalars (count)
    replicate."""

    def match(node):
        if isinstance(node, DenoiseParams):
            return param_specs
        return P()

    return jax.tree_util.tree_map(
        match, abstract_opt_state, is_leaf=lambda x: isinstance(x, DenoiseParams)
    )


def to_named(mesh: Mesh, spec_tree: Any) -> Any:
    """PartitionSpec tree -> NamedSharding tree."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )
