"""Reviewed-suppression baseline: the ratchet that lets the pass gate CI.

A baseline maps finding FINGERPRINTS (checker :: file :: enclosing symbol
:: rule key — deliberately line-free, so edits above a site don't churn
it) to accepted counts. The CI contract is exit-1-on-NEW-finding: a run
fails iff some fingerprint occurs more times than the baseline allows.
Stale entries (baselined findings that no longer occur) are reported as
warnings so the file ratchets DOWN over time; they never fail the run —
deleting dead suppressions must not block the fix that killed them.

Every entry carries the finding's message and a `reviewed` note field the
committer fills in — an unexplained baseline entry is exactly the silent
drift this pass exists to prevent, so __main__ refuses to accept entries
whose note is empty.
"""

from __future__ import annotations

import json
from collections import Counter
from pathlib import Path
from typing import Dict, List, Tuple

from glom_tpu.analysis.core import Finding

BASELINE_VERSION = 1


def load(path: str) -> dict:
    with open(path) as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or "suppressions" not in data:
        raise ValueError(f"{path}: not a glom-lint baseline (no 'suppressions')")
    return data


def counts(baseline: dict) -> Counter:
    out: Counter = Counter()
    for fp, entry in baseline.get("suppressions", {}).items():
        out[fp] = int(entry.get("count", 1)) if isinstance(entry, dict) else int(entry)
    return out


def unreviewed(baseline: dict) -> List[str]:
    """Fingerprints whose entry has no non-empty `reviewed` note."""
    bad = []
    for fp, entry in baseline.get("suppressions", {}).items():
        if not (isinstance(entry, dict) and str(entry.get("reviewed", "")).strip()):
            bad.append(fp)
    return sorted(bad)


def apply(
    findings: List[Finding], baseline: dict
) -> Tuple[List[Finding], List[str]]:
    """(new_findings, stale_fingerprints): findings beyond the baselined
    count per fingerprint are new; baselined fingerprints with no
    occurrences at all are stale."""
    allowed = counts(baseline)
    seen: Counter = Counter()
    new: List[Finding] = []
    for f in findings:
        seen[f.fingerprint] += 1
        if seen[f.fingerprint] > allowed.get(f.fingerprint, 0):
            new.append(f)
    stale = sorted(fp for fp in allowed if seen[fp] == 0)
    return new, stale


def prune(
    baseline: dict, findings: List[Finding]
) -> Tuple[dict, List[str]]:
    """(pruned_baseline, removed_fingerprints): drop suppressions whose
    fingerprint no longer occurs in `findings` AT ALL — the stale
    entries the apply() warnings have been nagging about. Entries with
    some occurrences keep their full count (count ratcheting is a
    manual review decision, not an automated one)."""
    seen = Counter(f.fingerprint for f in findings)
    supp = baseline.get("suppressions", {})
    removed = sorted(fp for fp in supp if seen[fp] == 0)
    out = dict(baseline)
    out["suppressions"] = {
        fp: entry for fp, entry in supp.items() if seen[fp] > 0
    }
    return out, removed


def build(findings: List[Finding], *, reviewed: str = "") -> dict:
    """Baseline dict accepting exactly the given findings. `reviewed` is
    written into every entry; entries with an empty note are rejected at
    load-enforcement time, so --write-baseline output must be annotated
    before it can gate CI."""
    supp: Dict[str, dict] = {}
    for f in findings:
        entry = supp.setdefault(
            f.fingerprint,
            {"count": 0, "message": f.message, "reviewed": reviewed},
        )
        entry["count"] += 1
    return {"version": BASELINE_VERSION, "suppressions": supp}


def write(findings: List[Finding], path: str, *, reviewed: str = "") -> dict:
    data = build(findings, reviewed=reviewed)
    Path(path).write_text(json.dumps(data, indent=2, sort_keys=True) + "\n")
    return data
