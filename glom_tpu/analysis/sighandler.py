"""signal-safety: code reachable from a signal handler must not acquire
non-reentrant locks or block.

THE PR 6 lesson, made static: a Python signal handler runs ON the main
thread, pausing it wherever it was — possibly inside a critical section,
HOLDING a lock. A handler path that then acquires that same
`threading.Lock` deadlocks the process at the exact moment (SIGTERM
grace window) it most needs to make progress; the measured instance was
the preemption save sharing the training loop's checkpoint-manager lock.
The shipped mitigations are the checker's exemption list:

  * `threading.RLock` is EXEMPT — the paused owner IS the handler's
    thread, so reacquisition succeeds (why tracing/flight.py's ring
    rides an RLock);
  * work moved to a spawned thread is NOT handler context — the checker
    does not follow `threading.Thread(target=...)` (the daemon-thread
    save is the PR 6 fix, not a violation) — but the handler's JOIN on
    that thread must be bounded: `.join()` with no timeout is flagged;
  * the blocking-IO denylist: `time.sleep`, `input`, `subprocess.*`,
    `socket.*`, and blocking `.get()`/`.put()` on queue-shaped
    receivers (`*_q` / `*queue*`) without a timeout/`block=False` —
    each an unbounded stall inside a bounded grace window. Plain local
    file writes are deliberately NOT listed: the flight dump must write
    its postmortem.

Handler discovery: functions registered via `signal.signal(SIG*, h)` —
`h` a local/nested function or a `self.<method>` — plus everything
reachable from them through intra-module calls (simple names via the
lexical scope chain, `self.<m>()` within the registering class).
Heuristic by design, like every checker here: cross-module calls are not
followed; the seeded fixture pair in tests/fixtures/signal_fixture.py
pins what IS caught.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from glom_tpu.analysis.astutil import (
    SCOPE_NODES,
    FuncInfo,
    call_name,
    dotted,
)
from glom_tpu.analysis.core import Checker, Context, Finding, SourceModule

# dotted-name prefixes that block unboundedly (or spawn blocking work)
BLOCKING_PREFIXES = {
    "subprocess.": "spawning/waiting on a subprocess blocks unboundedly",
    "socket.": "socket I/O blocks unboundedly",
}
BLOCKING_NAMES = {
    "time.sleep": "an unbounded stall inside a bounded grace window",
    "input": "blocks on stdin inside a signal handler",
}
_QUEUEISH_SUFFIXES = ("_q", "queue")


def _lock_kind(call: ast.Call) -> Optional[str]:
    """'lock' / 'rlock' when the call constructs a threading lock."""
    name = call_name(call) or ""
    leaf = name.split(".")[-1]
    if leaf == "Lock" and name in ("threading.Lock", "Lock"):
        return "lock"
    if leaf == "RLock" and name in ("threading.RLock", "RLock"):
        return "rlock"
    return None


def _queueish(receiver: Optional[str]) -> bool:
    """True when a dotted receiver looks like a queue (`self._q`,
    `work_queue`, ...) — the heuristic that keeps `.get()` on dicts and
    configs out of the findings."""
    if not receiver:
        return False
    leaf = receiver.split(".")[-1].lower()
    return leaf == "q" or any(leaf.endswith(s) for s in _QUEUEISH_SUFFIXES)


class SignalSafety(Checker):
    name = "signal-safety"
    description = (
        "no non-reentrant Lock acquisition or blocking IO reachable from "
        "a signal.signal-registered handler"
    )

    def check(self, module: SourceModule, ctx: Context) -> List[Finding]:
        handlers = self._handler_roots(module)
        if not handlers:
            return []
        locks = self._lock_table(module)
        methods = self._method_table(module)
        reached = self._reachable(module, handlers, methods)
        findings: List[Finding] = []
        for info in reached:
            findings.extend(self._check_function(module, info, locks))
        return findings

    # -- discovery -----------------------------------------------------------

    def _method_table(
        self, module: SourceModule
    ) -> Dict[Tuple[str, str], FuncInfo]:
        """(class qualname, method name) -> FuncInfo, for self-call
        resolution. Class qualname is the method qualname minus its leaf
        ('FlightRecorder.dump' -> 'FlightRecorder')."""
        table: Dict[Tuple[str, str], FuncInfo] = {}
        for info in module.index.functions.values():
            if "." in info.qualname:
                cls, leaf = info.qualname.rsplit(".", 1)
                table[(cls, leaf)] = info
        return table

    def _enclosing_class(self, info: FuncInfo) -> Optional[str]:
        """The class qualname a method (or its nested defs) belongs to:
        strip function leaves off the qualname until what remains names a
        known method's class. 'C.install.<locals>' nesting renders as
        'C.install._handler' here, so walking suffixes off finds 'C'."""
        parts = info.qualname.split(".")
        # everything but the leaf could be Class.method chains; take the
        # OUTERMOST segment group that is not itself a function name.
        return parts[0] if len(parts) > 1 else None

    def _handler_roots(self, module: SourceModule) -> List[FuncInfo]:
        roots: List[FuncInfo] = []
        scope_of: Dict[int, object] = {}
        owner_of: Dict[int, FuncInfo] = {}
        for info in module.index.functions.values():
            for node in info.body_nodes():
                scope_of[id(node)] = info.scope
                owner_of[id(node)] = info
        methods = self._method_table(module)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if (call_name(node) or "") != "signal.signal":
                continue
            if len(node.args) < 2:
                continue
            target = node.args[1]
            scope = scope_of.get(id(node), module.index.module_scope)
            resolved: Optional[FuncInfo] = None
            if isinstance(target, ast.Name):
                resolved = scope.resolve(target.id)
            elif isinstance(target, SCOPE_NODES):
                resolved = module.index.info_for(target)
            elif isinstance(target, ast.Attribute):
                recv = dotted(target.value)
                owner = owner_of.get(id(node))
                if recv == "self" and owner is not None:
                    cls = self._enclosing_class(owner)
                    if cls is not None:
                        resolved = methods.get((cls, target.attr))
            if resolved is not None:
                roots.append(resolved)
        return roots

    def _lock_table(self, module: SourceModule) -> Dict[str, str]:
        """name -> 'lock' | 'rlock'. Keys are both bare names (`lock =
        threading.Lock()`) and class-scoped attrs (`C.self._lock`) so a
        `with self._lock` in class C looks up 'C.self._lock'."""
        locks: Dict[str, str] = {}
        for info in module.index.functions.values():
            cls = self._enclosing_class(info)
            for node in info.body_nodes():
                if not isinstance(node, ast.Assign):
                    continue
                if not isinstance(node.value, ast.Call):
                    continue
                kind = _lock_kind(node.value)
                if kind is None:
                    continue
                for t in node.targets:
                    name = dotted(t)
                    if name is None:
                        continue
                    if name.startswith("self.") and cls is not None:
                        locks[f"{cls}.{name}"] = kind
                    else:
                        locks[name] = kind
        for node in module.tree.body:
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                kind = _lock_kind(node.value)
                if kind is not None:
                    for t in node.targets:
                        name = dotted(t)
                        if name is not None:
                            locks[name] = kind
        return locks

    def _reachable(
        self,
        module: SourceModule,
        roots: List[FuncInfo],
        methods: Dict[Tuple[str, str], FuncInfo],
    ) -> List[FuncInfo]:
        """BFS from the handler roots through intra-module calls: simple
        names via the lexical scope chain, `self.<m>()` via the method
        table. Thread targets are deliberately NOT edges (a spawned
        thread is not handler context — that is the sanctioned escape
        hatch, provided the join is bounded)."""
        reached: Dict[int, FuncInfo] = {}
        queue = list(roots)
        while queue:
            info = queue.pop()
            if id(info.node) in reached:
                continue
            reached[id(info.node)] = info
            cls = self._enclosing_class(info)
            for node in info.body_nodes():
                if not isinstance(node, ast.Call):
                    continue
                callee: Optional[FuncInfo] = None
                if isinstance(node.func, ast.Name):
                    callee = info.scope.resolve(node.func.id)
                elif isinstance(node.func, ast.Attribute):
                    recv = dotted(node.func.value)
                    if recv == "self" and cls is not None:
                        callee = methods.get((cls, node.func.attr))
                if callee is not None:
                    queue.append(callee)
        return list(reached.values())

    # -- per-function scan ---------------------------------------------------

    def _check_function(
        self, module: SourceModule, info: FuncInfo, locks: Dict[str, str]
    ) -> List[Finding]:
        findings: List[Finding] = []
        cls = self._enclosing_class(info)

        def lock_kind_of(expr: ast.AST) -> Optional[str]:
            name = dotted(expr)
            if name is None:
                return None
            if name.startswith("self.") and cls is not None:
                return locks.get(f"{cls}.{name}")
            return locks.get(name)

        def add(node, message, key):
            findings.append(
                Finding(
                    checker=self.name,
                    path=module.relpath,
                    line=node.lineno,
                    col=node.col_offset,
                    message=f"{message} (reachable from a signal handler)",
                    symbol=info.qualname,
                    key=key,
                )
            )

        for node in info.body_nodes():
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    expr = item.context_expr
                    if lock_kind_of(expr) == "lock":
                        add(
                            node,
                            f"`with {dotted(expr)}` acquires a NON-reentrant "
                            "threading.Lock — the paused main thread may "
                            "hold it and a paused owner never releases "
                            "(use RLock, or move the work to a bounded "
                            "worker thread)",
                            f"handler-lock-{dotted(expr)}",
                        )
            elif isinstance(node, ast.Call):
                name = call_name(node) or ""
                leaf = name.split(".")[-1]
                if leaf == "acquire" and isinstance(node.func, ast.Attribute):
                    if lock_kind_of(node.func.value) == "lock":
                        add(
                            node,
                            f"{dotted(node.func.value)}.acquire() on a "
                            "non-reentrant threading.Lock",
                            f"handler-lock-{dotted(node.func.value)}",
                        )
                    continue
                if name in BLOCKING_NAMES:
                    add(node, f"{name}(): {BLOCKING_NAMES[name]}",
                        f"handler-blocking-{name}")
                    continue
                matched = False
                for prefix, why in BLOCKING_PREFIXES.items():
                    if name.startswith(prefix):
                        add(node, f"{name}(): {why}",
                            f"handler-blocking-{prefix[:-1]}")
                        matched = True
                        break
                if matched:
                    continue
                if (
                    leaf == "join"
                    and isinstance(node.func, ast.Attribute)
                    and not node.args
                    and not any(k.arg == "timeout" for k in node.keywords)
                    and not _queueish(dotted(node.func.value))
                ):
                    # str.join always takes an argument; a zero-arg join
                    # is a thread join, and unbounded it stalls the grace
                    # window forever when the worker is wedged.
                    add(
                        node,
                        f"{dotted(node.func.value) or '<expr>'}.join() "
                        "without a timeout — an unbounded wait inside the "
                        "grace window",
                        "handler-join-unbounded",
                    )
                    continue
                blocking_shape = (
                    (leaf == "get" and not node.args)  # q.get(t) is bounded
                    or (leaf == "put" and len(node.args) == 1)
                )
                if (
                    leaf in ("get", "put")
                    and isinstance(node.func, ast.Attribute)
                    and _queueish(dotted(node.func.value))
                    and blocking_shape
                    and not any(
                        k.arg in ("timeout", "block") for k in node.keywords
                    )
                ):
                    add(
                        node,
                        f"blocking {dotted(node.func.value)}.{leaf}() — "
                        "pass timeout= (or use the _nowait form)",
                        f"handler-blocking-queue-{leaf}",
                    )
        return findings
