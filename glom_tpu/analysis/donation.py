"""donation-safety: never touch a buffer after handing it to a donated
dispatch.

`donate_argnums` tells XLA it may reuse the input's HBM for outputs — the
padded-batch reuse the serve engine leans on. The flip side: the moment
the dispatch is enqueued, the caller's array is INVALIDATED; a later read
raises `RuntimeError: Array has been deleted` only on the platforms where
donation actually resolves on (TPU), so CPU tests pass and the pod run
dies. PR 4's review caught exactly this shape of bug in engine.infer
(caller-held jax array passed through uncopied); this checker is the
static form.

Intra-function analysis:

  * a name bound from `jax.jit(f, donate_argnums=...)` (directly or via a
    `.lower(...).compile()` chain), or a local function decorated
    `@partial(jax.jit, donate_argnums=...)`, is a DONATING callable; a
    literal argnums spec pins the donated positions, an unresolvable spec
    conservatively donates every positional argument;
  * at each call of a donating callable, positional Name arguments in
    donated slots become dead buffers;
  * any later read of a dead name (before it is re-assigned) is a
    finding.

Branch structure is ignored (statement order by line); cross-function
flows (a compiled handle stashed in a dict and fetched elsewhere, as the
engine's memoization does) are out of reach — the runtime copy-guard in
engine.infer stays the defense there, and docs/ANALYSIS.md says so.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from glom_tpu.analysis.astutil import (
    FuncInfo,
    call_name,
    dotted,
    literal_int_tuple,
)
from glom_tpu.analysis.core import Checker, Context, Finding, SourceModule

ALL_POSITIONS = -1  # sentinel: unresolvable argnums — treat all as donated


def _jit_donation(call: ast.Call) -> Optional[object]:
    """Donated-position spec if `call` is a jit(...) with donation: a
    tuple of ints, ALL_POSITIONS, or None (no donation / not a jit)."""
    name = call_name(call) or ""
    if name.split(".")[-1] not in ("jit", "pjit"):
        return None
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            spec = literal_int_tuple(kw.value)
            if kw.arg == "donate_argnames":
                return ALL_POSITIONS  # names don't map to positions here
            return spec if spec is not None else ALL_POSITIONS
    return None


def _root_jit_call(node: ast.AST) -> Optional[ast.Call]:
    """Unwrap `jax.jit(...).lower(...).compile()` chains to the jit call."""
    while isinstance(node, ast.Call):
        func = node.func
        name = dotted(func) or ""
        if name.split(".")[-1] in ("jit", "pjit"):
            return node
        if isinstance(func, ast.Attribute) and func.attr in (
            "lower",
            "compile",
        ):
            node = func.value
            continue
        return None
    return None


class DonationSafety(Checker):
    name = "donation-safety"
    description = "no use of a caller-held array after a donated dispatch"

    def check(self, module: SourceModule, ctx: Context) -> List[Finding]:
        findings: List[Finding] = []
        for info in module.index.functions.values():
            findings.extend(self._check_function(module, info))
        return findings

    def _donating_names(self, info: FuncInfo) -> Dict[str, object]:
        """name -> donated-position spec for callables bound inside this
        function, plus sibling defs decorated with a donating jit."""
        donating: Dict[str, object] = {}
        for node in info.body_nodes():
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                jit_call = _root_jit_call(node.value)
                if jit_call is None:
                    continue
                spec = _jit_donation(jit_call)
                if spec is None:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        donating[t.id] = spec
        # decorated siblings / module-level defs resolvable from this scope
        scope = info.scope
        while scope is not None:
            for fname, finfo in scope.functions.items():
                for dec in getattr(finfo.node, "decorator_list", []):
                    if isinstance(dec, ast.Call):
                        inner = _root_jit_call(dec)
                        if inner is None and dotted(dec.func) in (
                            "partial",
                            "functools.partial",
                        ):
                            arg0 = dec.args[0] if dec.args else None
                            iname = dotted(arg0) if arg0 is not None else ""
                            if (iname or "").split(".")[-1] in ("jit", "pjit"):
                                spec = None
                                for kw in dec.keywords:
                                    if kw.arg in (
                                        "donate_argnums",
                                        "donate_argnames",
                                    ):
                                        lit = literal_int_tuple(kw.value)
                                        # () means "explicitly no
                                        # donation" — only an
                                        # UNRESOLVABLE spec goes
                                        # conservative
                                        spec = (
                                            lit
                                            if lit is not None
                                            else ALL_POSITIONS
                                        )
                                if spec is not None:
                                    donating.setdefault(fname, spec)
                        elif inner is not None:
                            spec = _jit_donation(inner)
                            if spec is not None:
                                donating.setdefault(fname, spec)
            scope = scope.parent
        return donating

    def _check_function(
        self, module: SourceModule, info: FuncInfo
    ) -> List[Finding]:
        donating = self._donating_names(info)
        if not donating:
            return []
        # events in line order: donations (name killed at line) and uses
        donations: List[Tuple[int, str, str]] = []  # (line, var, callee)
        rebinds: Dict[str, List[int]] = {}
        uses: List[Tuple[int, int, ast.Name]] = []
        for node in info.body_nodes():
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                spec = donating.get(node.func.id)
                if spec is not None:
                    for pos, arg in enumerate(node.args):
                        if isinstance(arg, ast.Name) and (
                            spec == ALL_POSITIONS or pos in spec
                        ):
                            donations.append(
                                (node.lineno, arg.id, node.func.id)
                            )
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Store):
                    rebinds.setdefault(node.id, []).append(node.lineno)
                elif isinstance(node.ctx, ast.Load):
                    uses.append((node.lineno, node.col_offset, node))

        findings: List[Finding] = []
        for dline, var, callee in donations:
            for uline, col, name in uses:
                if name.id != var or uline <= dline:
                    continue
                # a re-assignment between donation and use revives the name
                if any(dline <= r <= uline for r in rebinds.get(var, [])):
                    continue
                findings.append(
                    Finding(
                        checker=self.name,
                        path=module.relpath,
                        line=uline,
                        col=col,
                        message=(
                            f"{var!r} is read after being passed to donated "
                            f"dispatch {callee}(...) at line {dline} — the "
                            "buffer is invalidated on platforms where "
                            "donation resolves (TPU)"
                        ),
                        symbol=info.qualname,
                        key=f"use-after-donate-{var}",
                    )
                )
                break  # one finding per donation+name is enough
        return findings
