"""donation-safety: never touch a buffer after handing it to a donated
dispatch.

`donate_argnums` tells XLA it may reuse the input's HBM for outputs — the
padded-batch reuse the serve engine leans on. The flip side: the moment
the dispatch is enqueued, the caller's array is INVALIDATED; a later read
raises `RuntimeError: Array has been deleted` only on the platforms where
donation actually resolves on (TPU), so CPU tests pass and the pod run
dies. PR 4's review caught exactly this shape of bug in engine.infer
(caller-held jax array passed through uncopied); this checker is the
static form.

Intra-function analysis:

  * a name bound from `jax.jit(f, donate_argnums=...)` (directly or via a
    `.lower(...).compile()` chain), or a local function decorated
    `@partial(jax.jit, donate_argnums=...)`, is a DONATING callable; a
    literal argnums spec pins the donated positions, an unresolvable spec
    conservatively donates every positional argument;
  * at each call of a donating callable, positional Name arguments in
    donated slots become dead buffers;
  * any later read of a dead name (before it is re-assigned) is a
    finding.

Memoized-handle taint (intra-CLASS): the engine's real dispatch pattern —
`self._compiled[sig] = jax.jit(...).lower(...).compile()` in one method,
`fn = self._compile(...)` then `fn(imgs)` in another — was a PR 5 blind
spot: the donating callable crosses a method boundary through an
attribute, so the intra-function pass never saw the dispatch. The pass
now tracks, per class:

  * HANDLE ATTRS — `self.<attr>` / `self.<attr>[key]` assigned (in any
    method) from a donating jit chain, following the chain across local
    statements (`lowered = jax.jit(...).lower(...)` then
    `lowered.compile()`); a jit call whose kwargs arrive via `**splat`
    (the engine's `jit(fn, **jit_kw)`) is conservatively treated as
    donating EVERY positional argument on this path only — the direct
    intra-function rule is unchanged;
  * PROVIDER METHODS — methods that return a donating handle (a tainted
    local name, or a load of a handle attr), so
    `fn = self._compile(...)` taints `fn`;
  * at calls of a tainted name, a handle-attr load (`self._compiled[sig]
    (...)`, `self._step(...)`), the use-after-donation rule applies as
    in the intra-function case.

Aliased-pool dispatch pinning (ISSUE 16): with in-place pool aliasing
(ServeConfig.pool_aliasing) the pool's write-back DONATES the buffer on
its own seam — any dispatch still reading it must hold a read pin
(`PagedColumnPool.acquire_read()` / `release_read()`) so the seam falls
back to copy-on-write instead of invalidating the in-flight read. The
static form: a value obtained from a bare `.buffer()` call that flows
into a donating dispatch is a finding (`alias-unpinned-dispatch`) — the
fix is acquiring through the pin API, whose return value this rule
deliberately does not taint. Compile-time `.buffer()` reads (dtype /
shape probes that never reach a dispatch) stay clean.

Cross-MODULE handle flow (the project graph + its type layer): handle
attrs and provider methods are tabled GLOBALLY, keyed by class key
('module:Class'), and call sites resolve their receiver's type — an
annotated parameter, a constructor call, a typed `self.attr` from
`__init__`, a dict-of-handles subscript — so a provider defined in
serve/engine.py and dispatched from serve/batcher.py is the same
analysis as the intra-class case. `self` is just a typed receiver of
the enclosing class, which keeps the old intra-class behavior as the
degenerate case (and single-file runs unchanged: an unresolvable
receiver resolves to nothing).

`fn(*args)` at a donating call site is no longer skipped: the splat
makes donated POSITIONS unknowable, so the site itself is flagged
(`splat-at-donating-call`) — either unpack explicitly so the pass can
track the buffers, or pragma the site with the runtime guard that makes
it safe. Branch structure is still ignored (statement order by line).
The seeded acceptance pairs are tests/fixtures/donation_memo.py,
tests/fixtures/alias_pool.py, and tests/fixtures/xmod_donation.py.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from glom_tpu.analysis.astutil import (
    FuncInfo,
    call_name,
    dotted,
    literal_int_tuple,
)
from glom_tpu.analysis.core import Checker, Context, Finding, SourceModule

ALL_POSITIONS = -1  # sentinel: unresolvable argnums — treat all as donated


def _jit_donation(
    call: ast.Call, conservative_splat: bool = False
) -> Optional[object]:
    """Donated-position spec if `call` is a jit(...) with donation: a
    tuple of ints, ALL_POSITIONS, or None (no donation / not a jit).
    `conservative_splat=True` (the memoized-handle path only) treats a
    jit whose kwargs arrive via `**splat` as donating every position —
    the engine builds `jit(fn, **jit_kw)` with the donation inside the
    dict, invisible to a literal scan."""
    name = call_name(call) or ""
    if name.split(".")[-1] not in ("jit", "pjit"):
        return None
    saw_splat = False
    for kw in call.keywords:
        if kw.arg is None:
            saw_splat = True
            continue
        if kw.arg in ("donate_argnums", "donate_argnames"):
            spec = literal_int_tuple(kw.value)
            if kw.arg == "donate_argnames":
                return ALL_POSITIONS  # names don't map to positions here
            return spec if spec is not None else ALL_POSITIONS
    if saw_splat and conservative_splat:
        return ALL_POSITIONS
    return None


def _root_jit_call(node: ast.AST) -> Optional[ast.Call]:
    """Unwrap `jax.jit(...).lower(...).compile()` chains to the jit call."""
    while isinstance(node, ast.Call):
        func = node.func
        name = dotted(func) or ""
        if name.split(".")[-1] in ("jit", "pjit"):
            return node
        if isinstance(func, ast.Attribute) and func.attr in (
            "lower",
            "compile",
        ):
            node = func.value
            continue
        return None
    return None


def _method_class(info: FuncInfo) -> Optional[str]:
    """The class name when `info` is a method (qualname 'Cls.method',
    first parameter 'self'); None otherwise."""
    parts = info.qualname.split(".")
    if len(parts) < 2:
        return None
    params = info.params
    if not params or params[0] != "self":
        return None
    return parts[-2]


def _self_attr(node: ast.AST) -> Optional[str]:
    """'attr' for a bare `self.attr` expression."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _self_attr_subscript(node: ast.AST) -> Optional[str]:
    """'attr' for a `self.attr[key]` expression (the memo-dict shape)."""
    if isinstance(node, ast.Subscript):
        return _self_attr(node.value)
    return None


def _chain_spec(expr: ast.AST, known: Dict[str, object]) -> Optional[object]:
    """Donation spec of an expression that is (a chain off) a donating
    jit: `jax.jit(...)[.lower(...).compile()]` directly, or
    `name.lower(...)` / `name.compile()` where `name` is already known
    donating — the cross-STATEMENT half of the engine's AOT idiom."""
    node = expr
    while True:
        if isinstance(node, ast.Call):
            jit_call = _root_jit_call(node)
            if jit_call is not None:
                return _jit_donation(jit_call, conservative_splat=True)
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr in (
                "lower",
                "compile",
            ):
                node = func.value
                continue
            return None
        if isinstance(node, ast.Name):
            return known.get(node.id)
        return None


def _merge_spec(prev: Optional[object], spec: object) -> object:
    return spec if prev is None or prev == spec else ALL_POSITIONS


def _ordered(nodes) -> List[ast.AST]:
    return sorted(nodes, key=lambda n: getattr(n, "lineno", 0))


class DonationSafety(Checker):
    name = "donation-safety"
    description = "no use of a caller-held array after a donated dispatch"

    def check(self, module: SourceModule, ctx: Context) -> List[Finding]:
        handles = self._memo_handles(module)
        providers = self._providers(module, handles)
        xhandles, xproviders = self._project_tables(ctx)
        findings: List[Finding] = []
        for info in module.index.functions.values():
            findings.extend(
                self._check_function(
                    module,
                    info,
                    handles,
                    providers,
                    project=ctx.project,
                    xhandles=xhandles,
                    xproviders=xproviders,
                )
            )
        return findings

    def _project_tables(self, ctx: Context):
        """Global (class_key, attr) -> spec and (class_key, method) ->
        spec tables over every analyzed module, computed once per run —
        the cross-module half of the memoized-handle analysis."""
        key = "donation-safety:tables"
        if key in ctx.scratch:
            return ctx.scratch[key]
        xhandles: Dict[Tuple[str, str], object] = {}
        xproviders: Dict[Tuple[str, str], object] = {}
        project = ctx.project
        if project is not None:
            for mod in ctx.modules:
                minfo = project.info_of(mod)
                handles = self._memo_handles(mod)
                providers = self._providers(mod, handles)
                for (cls, attr), spec in handles.items():
                    if cls in minfo.classes:
                        xhandles[(project.class_key(minfo, cls), attr)] = spec
                for (cls, meth), spec in providers.items():
                    if cls in minfo.classes:
                        xproviders[(project.class_key(minfo, cls), meth)] = spec
        ctx.scratch[key] = (xhandles, xproviders)
        return ctx.scratch[key]

    def _memo_handles(self, module: SourceModule) -> Dict[Tuple[str, str], object]:
        """(class, attr) -> donation spec for `self.attr` / `self.attr[k]`
        targets assigned from a donating jit chain anywhere in the
        class — the memoized dispatch-handle table."""
        handles: Dict[Tuple[str, str], object] = {}
        for info in module.index.functions.values():
            cls = _method_class(info)
            if cls is None:
                continue
            known: Dict[str, object] = {}
            for stmt in _ordered(
                n for n in info.body_nodes() if isinstance(n, ast.Assign)
            ):
                spec = _chain_spec(stmt.value, known)
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        if spec is not None:
                            known[t.id] = spec
                        else:
                            known.pop(t.id, None)  # rebind clears taint
                        continue
                    attr = _self_attr_subscript(t) or _self_attr(t)
                    if attr is not None and spec is not None:
                        handles[(cls, attr)] = _merge_spec(
                            handles.get((cls, attr)), spec
                        )
        return handles

    def _providers(
        self,
        module: SourceModule,
        handles: Dict[Tuple[str, str], object],
    ) -> Dict[Tuple[str, str], object]:
        """(class, method) -> spec for methods that RETURN a donating
        handle (a tainted local, or a handle-attr load) — the engine's
        `_compile` shape, so `fn = self._compile(...)` taints `fn` at the
        caller."""
        providers: Dict[Tuple[str, str], object] = {}
        for info in module.index.functions.values():
            cls = _method_class(info)
            if cls is None:
                continue
            known: Dict[str, object] = {}
            for stmt in _ordered(
                n
                for n in info.body_nodes()
                if isinstance(n, (ast.Assign, ast.Return))
            ):
                if isinstance(stmt, ast.Assign):
                    spec = self._value_spec(stmt.value, known, cls, handles, {})
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            if spec is not None:
                                known[t.id] = spec
                            else:
                                known.pop(t.id, None)
                    continue
                spec = self._value_spec(stmt.value, known, cls, handles, {})
                if spec is not None:
                    method = info.qualname.split(".")[-1]
                    providers[(cls, method)] = _merge_spec(
                        providers.get((cls, method)), spec
                    )
        return providers

    @staticmethod
    def _value_spec(
        value: Optional[ast.AST],
        known: Dict[str, object],
        cls: Optional[str],
        handles: Dict[Tuple[str, str], object],
        providers: Dict[Tuple[str, str], object],
    ) -> Optional[object]:
        """Donation spec of a right-hand side / return value: a jit
        chain, a tainted name, a handle-attr load, or a provider call."""
        if value is None:
            return None
        spec = _chain_spec(value, known)
        if spec is not None:
            return spec
        if cls is not None:
            attr = _self_attr_subscript(value) or _self_attr(value)
            if attr is not None and (cls, attr) in handles:
                return handles[(cls, attr)]
            if isinstance(value, ast.Call):
                meth = _self_attr(value.func)
                if meth is not None and (cls, meth) in providers:
                    return providers[(cls, meth)]
        return None

    def _donating_names(self, info: FuncInfo) -> Dict[str, object]:
        """name -> donated-position spec for callables bound inside this
        function, plus sibling defs decorated with a donating jit."""
        donating: Dict[str, object] = {}
        for node in info.body_nodes():
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                jit_call = _root_jit_call(node.value)
                if jit_call is None:
                    continue
                spec = _jit_donation(jit_call)
                if spec is None:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        donating[t.id] = spec
        # decorated siblings / module-level defs resolvable from this scope
        scope = info.scope
        while scope is not None:
            for fname, finfo in scope.functions.items():
                for dec in getattr(finfo.node, "decorator_list", []):
                    if isinstance(dec, ast.Call):
                        inner = _root_jit_call(dec)
                        if inner is None and dotted(dec.func) in (
                            "partial",
                            "functools.partial",
                        ):
                            arg0 = dec.args[0] if dec.args else None
                            iname = dotted(arg0) if arg0 is not None else ""
                            if (iname or "").split(".")[-1] in ("jit", "pjit"):
                                spec = None
                                for kw in dec.keywords:
                                    if kw.arg in (
                                        "donate_argnums",
                                        "donate_argnames",
                                    ):
                                        lit = literal_int_tuple(kw.value)
                                        # () means "explicitly no
                                        # donation" — only an
                                        # UNRESOLVABLE spec goes
                                        # conservative
                                        spec = (
                                            lit
                                            if lit is not None
                                            else ALL_POSITIONS
                                        )
                                if spec is not None:
                                    donating.setdefault(fname, spec)
                        elif inner is not None:
                            spec = _jit_donation(inner)
                            if spec is not None:
                                donating.setdefault(fname, spec)
            scope = scope.parent
        return donating

    def _donating_env(
        self,
        module: SourceModule,
        info: FuncInfo,
        handles: Dict[Tuple[str, str], object],
        providers: Dict[Tuple[str, str], object],
        project,
        xhandles: Dict[Tuple[str, str], object],
        xproviders: Dict[Tuple[str, str], object],
        seed: Optional[Dict[str, object]] = None,
    ):
        """(donating-name map, receiver-type resolver, method class) for
        one function: jit-bound locals and decorated siblings, plus the
        memoized-handle taint pass — names bound from a handle-attr load
        or provider call become donating callables (`fn =
        self._compile(...)`), tracked in statement order so a rebind to
        something untainted clears the name (including a seeded one)."""
        cls = _method_class(info)
        donating: Dict[str, object] = dict(seed) if seed else {}
        donating.update(self._donating_names(info))
        # Typed-receiver resolution (cross-module): the project type
        # layer maps a receiver expression to a class key, so the global
        # handle/provider tables apply wherever the object travels.
        # `self` is seeded as a receiver of the enclosing class (also
        # visible by closure inside nested defs), which makes the
        # intra-class case a degenerate typed lookup too.
        rtype = None
        if project is not None and (xhandles or xproviders):
            rtype = project.receiver_resolver(module, info)

        if (
            donating
            or (cls is not None and (handles or providers))
            or rtype is not None
        ):
            for stmt in _ordered(
                n for n in info.body_nodes() if isinstance(n, ast.Assign)
            ):
                spec = self._value_spec(
                    stmt.value, donating, cls, handles, providers
                )
                if spec is None:
                    spec = self._xmod_value_spec(
                        stmt.value, rtype, xhandles, xproviders
                    )
                for t in stmt.targets:
                    if not isinstance(t, ast.Name):
                        continue
                    if spec is not None:
                        donating[t.id] = spec
                    else:
                        # Rebinding to a non-donating value clears the
                        # taint — `fn = plain_fn` after
                        # `fn = self._compile(...)` must not flag
                        # plain_fn's call sites.
                        donating.pop(t.id, None)
        return donating, rtype, cls

    @staticmethod
    def _xmod_value_spec(
        value: Optional[ast.AST],
        rtype,
        xhandles: Dict[Tuple[str, str], object],
        xproviders: Dict[Tuple[str, str], object],
    ) -> Optional[object]:
        """Spec of a value obtained through a TYPED receiver: a provider
        call (`eng._compile(...)` where `eng` resolves to engine.Engine),
        or a handle-attr load (`eng._step`, `eng._compiled[sig]`)."""
        if rtype is None or value is None:
            return None
        if isinstance(value, ast.Call) and isinstance(
            value.func, ast.Attribute
        ):
            t = rtype(value.func.value)
            if t is not None and t.cls is not None:
                spec = xproviders.get((t.cls, value.func.attr))
                if spec is not None:
                    return spec
        target = value
        if isinstance(target, ast.Subscript):
            target = target.value
        if isinstance(target, ast.Attribute):
            t = rtype(target.value)
            if t is not None and t.cls is not None:
                return xhandles.get((t.cls, target.attr))
        return None

    def _check_function(
        self,
        module: SourceModule,
        info: FuncInfo,
        handles: Optional[Dict[Tuple[str, str], object]] = None,
        providers: Optional[Dict[Tuple[str, str], object]] = None,
        project=None,
        xhandles: Optional[Dict[Tuple[str, str], object]] = None,
        xproviders: Optional[Dict[Tuple[str, str], object]] = None,
    ) -> List[Finding]:
        handles = handles or {}
        providers = providers or {}
        xhandles = xhandles or {}
        xproviders = xproviders or {}
        # Closure capture: a nested def dispatches through names bound in
        # its ENCLOSING function (the engine's retry `attempt()` calls the
        # `fn = self._compile(...)` the method bound outside it), so the
        # donating map is seeded from the enclosing chain, outermost
        # first; the local statement pass can still clear a seeded name
        # on rebind.
        chain: List[FuncInfo] = []
        scope = info.scope.parent
        while scope is not None:
            einfo = module.index.info_for(scope.node)
            if einfo is not None:
                chain.append(einfo)
            scope = scope.parent
        donating: Dict[str, object] = {}
        for einfo in reversed(chain):
            outer, _, _ = self._donating_env(
                module, einfo, handles, providers, project,
                xhandles, xproviders, seed=donating,
            )
            donating = outer
        donating, rtype, cls = self._donating_env(
            module, info, handles, providers, project,
            xhandles, xproviders, seed=donating,
        )
        has_handle_calls = (cls is not None and handles) or (
            rtype is not None and xhandles
        )
        if not donating and not has_handle_calls:
            return []
        # events in line order: donations (name killed at line) and uses
        donations: List[Tuple[int, str, str]] = []  # (line, var, callee)
        rebinds: Dict[str, List[int]] = {}
        uses: List[Tuple[int, int, ast.Name]] = []
        # Aliased-pool pinning: lines where a name was bound from a bare
        # `.buffer()` call (the unpinned read), keyed by name — compared
        # against the LATEST binding at each dispatch site, so a rebind
        # through acquire_read() clears the hazard.
        buffer_lines: Dict[str, set] = {}
        alias_hits: List[Tuple[int, int, str, str]] = []
        splat_hits: List[Tuple[int, int, str]] = []
        for node in info.body_nodes():
            if isinstance(node, ast.Assign) and (
                isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Attribute)
                and node.value.func.attr == "buffer"
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        buffer_lines.setdefault(t.id, set()).add(
                            node.lineno
                        )
            if isinstance(node, ast.Call):
                spec = callee = None
                if isinstance(node.func, ast.Name):
                    spec = donating.get(node.func.id)
                    callee = node.func.id
                elif cls is not None:
                    # Direct dispatch through the memo table:
                    # `self._compiled[sig](params, imgs)`.
                    attr = _self_attr_subscript(node.func)
                    if attr is not None and (cls, attr) in handles:
                        spec = handles[(cls, attr)]
                        callee = f"self.{attr}[...]"
                if spec is None and rtype is not None:
                    # Typed-receiver dispatch across modules:
                    # `eng._step(imgs)` / `self.engine._compiled[sig](x)`.
                    target = node.func
                    if isinstance(target, ast.Subscript):
                        target = target.value
                    if isinstance(target, ast.Attribute):
                        t = rtype(target.value)
                        if t is not None and t.cls is not None:
                            hspec = xhandles.get((t.cls, target.attr))
                            if hspec is not None:
                                spec = hspec
                                callee = dotted(target) or (
                                    f"<{t.cls}>.{target.attr}"
                                )
                if spec is not None:
                    for pos, arg in enumerate(node.args):
                        if isinstance(arg, ast.Name) and (
                            spec == ALL_POSITIONS or pos in spec
                        ):
                            donations.append((node.lineno, arg.id, callee))
                        elif isinstance(arg, ast.Starred) and (
                            spec == ALL_POSITIONS or spec
                        ):
                            splat_hits.append(
                                (node.lineno, arg.col_offset, callee)
                            )
                    for arg in node.args:
                        if (
                            isinstance(arg, ast.Call)
                            and isinstance(arg.func, ast.Attribute)
                            and arg.func.attr == "buffer"
                        ):
                            alias_hits.append(
                                (
                                    arg.lineno,
                                    arg.col_offset,
                                    "buffer()",
                                    callee,
                                )
                            )
                        elif isinstance(arg, ast.Name):
                            # membership filtered below — buffer_lines
                            # may not be complete yet mid-walk
                            alias_hits.append(
                                (node.lineno, arg.col_offset, arg.id, callee)
                            )
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Store):
                    rebinds.setdefault(node.id, []).append(node.lineno)
                elif isinstance(node.ctx, ast.Load):
                    uses.append((node.lineno, node.col_offset, node))

        findings: List[Finding] = []
        for line, col, callee in splat_hits:
            findings.append(
                Finding(
                    checker=self.name,
                    path=module.relpath,
                    line=line,
                    col=col,
                    message=(
                        f"*-splat at donating dispatch {callee}(...) — the "
                        "donated positions are unknowable statically, so "
                        "every splatted buffer may be invalidated; unpack "
                        "the arguments explicitly, or pragma the site with "
                        "the runtime guard that makes the reuse safe"
                    ),
                    symbol=info.qualname,
                    key="splat-at-donating-call",
                )
            )
        for line, col, what, callee in alias_hits:
            if what != "buffer()":
                if what not in buffer_lines:
                    continue
                # The latest binding at the dispatch site decides: a
                # rebind from acquire_read() (or anything else) between
                # the bare read and the dispatch clears the hazard.
                binds = [r for r in rebinds.get(what, []) if r <= line]
                if not binds or max(binds) not in buffer_lines[what]:
                    continue
            findings.append(
                Finding(
                    checker=self.name,
                    path=module.relpath,
                    line=line,
                    col=col,
                    message=(
                        f"{what} from a bare pool.buffer() flows into "
                        f"donating dispatch {callee}(...) without a read "
                        "pin — under pool aliasing the pool's donated "
                        "write-back can invalidate it mid-dispatch; "
                        "acquire via acquire_read()/release_read() so "
                        "the write seam falls back to copy-on-write"
                    ),
                    symbol=info.qualname,
                    key="alias-unpinned-dispatch",
                )
            )
        for dline, var, callee in donations:
            for uline, col, name in uses:
                if name.id != var or uline <= dline:
                    continue
                # a re-assignment between donation and use revives the name
                if any(dline <= r <= uline for r in rebinds.get(var, [])):
                    continue
                findings.append(
                    Finding(
                        checker=self.name,
                        path=module.relpath,
                        line=uline,
                        col=col,
                        message=(
                            f"{var!r} is read after being passed to donated "
                            f"dispatch {callee}(...) at line {dline} — the "
                            "buffer is invalidated on platforms where "
                            "donation resolves (TPU)"
                        ),
                        symbol=info.qualname,
                        key=f"use-after-donate-{var}",
                    )
                )
                break  # one finding per donation+name is enough
        return findings
