"""schema-emit: every stamped record speaks the registered schema.

The telemetry contract (telemetry/schema.py) is only as strong as its
call sites: a sink that stamps a kind the registry doesn't know produces
rows the linter rejects AFTER the run already happened — this checker
rejects them at review time. Rules, over calls to the stamping/emitting
family (sinks.emit, schema.stamp, serve.events.emit_serve/stamp_serve,
the private _emit helpers, and MetricsWriter-style .write with a literal
record):

  * a literal `kind` must exist in schema.KINDS (loaded from the real
    registry — import first, AST fallback over the scanned tree so the
    pass also works where the package isn't importable);
  * the UNMEASURED discipline: a record literal carrying an `error` key
    must carry `value: None` — NEVER 0 / 0.0 (the round-5 dead-zero rows
    this rule exists to keep extinct);
  * `kind="error"` with a record literal requires the `error` field the
    schema demands.

Non-literal kinds and records built away from the call site are skipped,
not guessed at — the runtime linter still owns those.
"""

from __future__ import annotations

import ast
from functools import lru_cache
from typing import List, Optional, Set

from glom_tpu.analysis.astutil import call_name, qualname_at
from glom_tpu.analysis.core import Checker, Context, Finding, SourceModule

# emit-family leaf name -> positional index of the `kind` argument
KIND_POSITION = {
    "emit": 1,
    "stamp": 1,
    "_emit": 1,
    "stamp_serve": 1,
    "emit_serve": 2,
}
# leaf name -> positional index of the record-dict argument
RECORD_POSITION = {
    "emit": 0,
    "stamp": 0,
    "_emit": 0,
    "stamp_serve": 0,
    "emit_serve": 1,
    "write": 0,
}

# Frozen fallback if neither the import nor the AST scan can find the
# registry (running the pass over a partial checkout): the v4 kinds.
_FALLBACK_KINDS = {
    "train_step", "bench", "watchdog", "anomaly", "summary", "note",
    "span", "error", "serve", "fault", "recovery",
}

# Serve events that are REQUEST-scoped and must stamp trace context on
# every v6 record (the schema registry owns the real list; this frozen
# fallback mirrors it for partial checkouts).
_FALLBACK_TRACE_EVENTS = (
    "dispatch", "continuation", "shed", "resolve", "engine_failover",
    "dispatch_error", "response",
)
_TRACE_KEYS = ("trace_id", "trace_ids")

# Serve events that are TENANT-scoped and must stamp the SLO class on
# every v11 record (null = classless is fine, absent is not — the same
# presence discipline as the trace keys).
_FALLBACK_CLASS_EVENTS = ("admit", "shed", "settle", "resolve")
_CLASS_KEY = "slo_class"


@lru_cache(maxsize=1)
def _load_trace_events() -> tuple:
    try:
        from glom_tpu.telemetry.schema import TRACE_REQUIRED_EVENTS

        return tuple(TRACE_REQUIRED_EVENTS)
    except Exception:
        return _FALLBACK_TRACE_EVENTS


@lru_cache(maxsize=1)
def _load_class_events() -> tuple:
    try:
        from glom_tpu.telemetry.schema import CLASS_REQUIRED_EVENTS

        return tuple(CLASS_REQUIRED_EVENTS)
    except Exception:
        return _FALLBACK_CLASS_EVENTS


def _load_kinds(ctx: Context) -> Set[str]:
    if ctx.kinds is not None:
        return ctx.kinds
    kinds: Optional[Set[str]] = None
    try:
        from glom_tpu.telemetry.schema import KINDS

        kinds = set(KINDS)
    except Exception:
        kinds = None
    if kinds is None:
        for mod in ctx.modules:
            if not mod.relpath.endswith("telemetry/schema.py"):
                continue
            for node in mod.tree.body:
                if (
                    isinstance(node, ast.Assign)
                    and any(
                        isinstance(t, ast.Name) and t.id == "KINDS"
                        for t in node.targets
                    )
                    and isinstance(node.value, ast.Dict)
                ):
                    kinds = {
                        k.value
                        for k in node.value.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                    }
    ctx.kinds = kinds if kinds else set(_FALLBACK_KINDS)
    return ctx.kinds


class SchemaEmit(Checker):
    name = "schema-emit"
    description = (
        "emit/stamp sites use registered kinds; UNMEASURED is null, not 0.0"
    )

    def check(self, module: SourceModule, ctx: Context) -> List[Finding]:
        kinds = _load_kinds(ctx)
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node) or ""
            leaf = name.split(".")[-1]
            if leaf not in RECORD_POSITION:
                continue
            symbol = qualname_at(module.parents, module.index, node)
            kind = self._kind_of(node, leaf)
            record = self._record_of(node, leaf)
            if leaf == "write" and (
                record is None or not self._has_key(record, "kind")
            ):
                # .write() matches broadly (files, sockets); only literal
                # records that stamp their own kind are in scope.
                continue

            def add(anchor, message, key):
                findings.append(
                    Finding(
                        checker=self.name,
                        path=module.relpath,
                        line=anchor.lineno,
                        col=anchor.col_offset,
                        message=message,
                        symbol=symbol,
                        key=key,
                    )
                )

            kind_value = None
            if kind is not None:
                kind_value = (
                    kind.value
                    if isinstance(kind, ast.Constant)
                    and isinstance(kind.value, str)
                    else None
                )
                if kind_value is not None and kind_value not in kinds:
                    add(
                        kind,
                        f"kind {kind_value!r} is not in the schema registry "
                        f"{sorted(kinds)} — the runtime linter will reject "
                        "every record this site writes",
                        "unknown-kind",
                    )
            if record is not None:
                # records may stamp kind inside the literal
                if kind_value is None:
                    inline = self._value_of(record, "kind")
                    if (
                        isinstance(inline, ast.Constant)
                        and isinstance(inline.value, str)
                    ):
                        kind_value = inline.value
                        if kind_value not in kinds:
                            add(
                                inline,
                                f"kind {kind_value!r} is not in the schema "
                                f"registry {sorted(kinds)}",
                                "unknown-kind",
                            )
                ev = self._value_of(record, "event")
                if (
                    kind_value in (None, "serve")
                    and isinstance(ev, ast.Constant)
                    and ev.value in _load_trace_events()
                    and not any(k is None for k in record.keys)  # **splat
                    and not any(
                        self._has_key(record, k) for k in _TRACE_KEYS
                    )
                ):
                    # The schema-v6 request-tracing contract, enforced at
                    # the emit site: a request-scoped serve event literal
                    # that stamps neither trace key (nor merges one in via
                    # a **splat) writes records that can never join their
                    # request's causal tree — the runtime linter will
                    # reject every one of them.
                    add(
                        ev,
                        f"serve event {ev.value!r} record stamps no trace "
                        f"context ({'/'.join(_TRACE_KEYS)}) — schema v6 "
                        "requires request-scoped serve records to carry "
                        "it (telemetry/tracectx.py; null = explicitly "
                        "untraced is fine, absent is not)",
                        "trace-context",
                    )
                if (
                    kind_value in (None, "serve")
                    and isinstance(ev, ast.Constant)
                    and ev.value in _load_class_events()
                    and not any(k is None for k in record.keys)  # **splat
                    and not self._has_key(record, _CLASS_KEY)
                ):
                    # The schema-v11 QoS contract, same discipline as the
                    # trace keys: a tenant-scoped serve event literal that
                    # stamps no slo_class (nor merges one via **splat)
                    # writes records no per-class rollup, weighted-regret
                    # audit, or class-scoped SLO rule can ever attribute.
                    add(
                        ev,
                        f"serve event {ev.value!r} record stamps no "
                        f"{_CLASS_KEY} — schema v11 requires tenant-scoped "
                        "serve records to carry it (serve/qos.py; null = "
                        "classless is fine, absent is not)",
                        "class-context",
                    )
                if self._has_key(record, "error"):
                    value = self._value_of(record, "value")
                    if (
                        isinstance(value, ast.Constant)
                        and isinstance(value.value, (int, float))
                        and not isinstance(value.value, bool)
                    ):
                        add(
                            value,
                            "UNMEASURED record (carries 'error') stamps "
                            f"value {value.value!r} — must be None: dead "
                            "zeros poison the bench trajectory and the "
                            "compare gate",
                            "unmeasured-zero",
                        )
                elif kind_value == "error":
                    add(
                        record,
                        "kind='error' record literal has no 'error' field "
                        "— the schema requires the machine-readable cause",
                        "error-missing-field",
                    )
        return findings

    @staticmethod
    def _kind_of(call: ast.Call, leaf: str) -> Optional[ast.AST]:
        for kw in call.keywords:
            if kw.arg == "kind":
                return kw.value
        idx = KIND_POSITION.get(leaf)
        if idx is not None and len(call.args) > idx:
            return call.args[idx]
        return None

    @staticmethod
    def _record_of(call: ast.Call, leaf: str) -> Optional[ast.Dict]:
        idx = RECORD_POSITION[leaf]
        node = call.args[idx] if len(call.args) > idx else None
        for kw in call.keywords:
            if kw.arg in ("rec", "record", "metrics"):
                node = kw.value
        return node if isinstance(node, ast.Dict) else None

    @staticmethod
    def _has_key(d: ast.Dict, key: str) -> bool:
        return any(
            isinstance(k, ast.Constant) and k.value == key for k in d.keys
        )

    @staticmethod
    def _value_of(d: ast.Dict, key: str) -> Optional[ast.AST]:
        for k, v in zip(d.keys, d.values):
            if isinstance(k, ast.Constant) and k.value == key:
                return v
        return None
