"""glom-lint core: findings, parsed modules, pragmas, and the run engine.

The framework mirrors the telemetry subsystem's design rules: pure stdlib
(the pass must run where jax is wedged — CI lint boxes, the hardware
queue's pre-flight), every finding machine-readable, and suppression is an
AUDITED act — either an inline pragma carrying a reason, or an entry in
the reviewed baseline file (analysis_baseline.json). Checkers are small
classes over `SourceModule`s; `run()` wires them together and applies the
pragma filter. Exit-code policy lives in __main__.

Pragma syntax (the reason is mandatory — an unexplained suppression is
itself a finding):

    x = lax.pmean(s, "data")  # glom-lint: ok[collective-coverage] scalar

    # glom-lint: ok[trace-purity] trace-time constant, not a tracer
    y = np.float32(0.5)

A pragma on its own line suppresses the NEXT line; a trailing pragma
suppresses its own line. `ok[*]` suppresses every checker on that line.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, List, Optional, Sequence, Set, Tuple

from glom_tpu.analysis.astutil import ModuleIndex, build_parent_map

_PRAGMA_RE = re.compile(r"#\s*glom-lint:\s*ok\[([\w*,\- ]+)\]\s*(.*)")


@dataclass
class Finding:
    """One violation. `key` is the rule-stable part of the fingerprint
    (no line numbers — baselines must survive unrelated edits above the
    site); `symbol` is the enclosing function qualname."""

    checker: str
    path: str  # repo-relative, '/'-separated
    line: int
    col: int
    message: str
    symbol: str = "<module>"
    key: str = ""

    @property
    def fingerprint(self) -> str:
        return f"{self.checker}::{self.path}::{self.symbol}::{self.key or self.message}"

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.checker}] {self.message}"


@dataclass
class Pragma:
    line: int
    checkers: Set[str]
    reason: str
    own_line: bool  # comment-only line: applies to the NEXT line
    used: bool = False


class SourceModule:
    """One parsed file: AST + parents + scope index + pragmas."""

    def __init__(self, path: Path, relpath: str, text: str):
        self.path = path
        self.relpath = relpath
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text)
        self.parents = build_parent_map(self.tree)
        self.index = ModuleIndex(self.tree)
        self.pragmas: List[Pragma] = self._parse_pragmas()

    def _parse_pragmas(self) -> List[Pragma]:
        """Pragmas come from REAL comment tokens only — a pragma-shaped
        string inside a docstring (this framework documents its own
        syntax) must not register as a live suppression."""
        out = []
        try:
            tokens = list(
                tokenize.generate_tokens(io.StringIO(self.text).readline)
            )
        except (tokenize.TokenError, IndentationError):  # pragma: no cover
            return out  # ast.parse succeeded, so this is near-unreachable
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _PRAGMA_RE.search(tok.string)
            if not m:
                continue
            i = tok.start[0]
            checkers = {c.strip() for c in m.group(1).split(",") if c.strip()}
            out.append(
                Pragma(
                    line=i,
                    checkers=checkers,
                    reason=m.group(2).strip(),
                    own_line=self.lines[i - 1].strip().startswith("#"),
                )
            )
        return out

    def suppressed(self, finding: Finding) -> bool:
        for p in self.pragmas:
            target = p.line + 1 if p.own_line else p.line
            if finding.line == target and (
                "*" in p.checkers or finding.checker in p.checkers
            ):
                p.used = True
                return True
        return False


@dataclass
class Context:
    """Cross-module facts the checkers share (built once per run)."""

    modules: List[SourceModule] = field(default_factory=list)
    # The whole-program layer (analysis/project.py): import graph,
    # cross-module symbol/call resolution, the type layer. Built once in
    # run() over the analyzed set; checkers that compute project-wide
    # results cache them keyed by id(self) (one Context = one run).
    project: Optional[object] = None
    # Scratch channel for project-wide per-checker caches and the
    # attestation debug trail the tests read.
    scratch: dict = field(default_factory=dict)
    # Mesh-axis vocabulary: values of module-level *_AXIS string constants
    # across the scanned tree, plus the MeshConfig.axis_names convention.
    axis_vocab: Set[str] = field(default_factory=lambda: {"data", "seq", "model"})
    # Modules (matched by relpath suffix) where every wire-moving
    # collective must be registered with telemetry.counters.
    registration_modules: Sequence[str] = (
        "parallel/manual.py",
        "parallel/quantized.py",
        "parallel/serve_mesh.py",
    )
    # kind registry for the schema-emit checker (filled by the checker on
    # first use: schema.py import, else AST fallback).
    kinds: Optional[Set[str]] = None


class Checker:
    """Base: subclasses set `name` and implement check(module, ctx)."""

    name = "base"
    description = ""

    def check(self, module: SourceModule, ctx: Context) -> List[Finding]:
        raise NotImplementedError


def collect_files(paths: Iterable[str]) -> List[Path]:
    files: List[Path] = []
    for p in paths:
        path = Path(p)
        if path.is_dir():
            files.extend(
                f
                for f in sorted(path.rglob("*.py"))
                if "__pycache__" not in f.parts
            )
        elif path.suffix == ".py":
            files.append(path)
    return files


def _relpath(path: Path) -> str:
    try:
        rel = path.resolve().relative_to(Path.cwd())
    except ValueError:
        rel = path
    return str(rel).replace("\\", "/")


def load_modules(
    paths: Iterable[str],
) -> Tuple[List[SourceModule], List[Finding]]:
    modules, errors = [], []
    for f in collect_files(paths):
        rel = _relpath(f)
        try:
            text = f.read_text()
            modules.append(SourceModule(f, rel, text))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            lineno = getattr(e, "lineno", 0) or 0
            errors.append(
                Finding(
                    checker="parse",
                    path=rel,
                    line=lineno,
                    col=0,
                    message=f"cannot parse: {e}",
                    key="parse-error",
                )
            )
    return modules, errors


def _collect_axis_vocab(modules: List[SourceModule], ctx: Context) -> None:
    for mod in modules:
        for node in mod.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            for target in node.targets:
                if (
                    isinstance(target, ast.Name)
                    and target.id.endswith("_AXIS")
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)
                ):
                    ctx.axis_vocab.add(node.value.value)


def default_checkers() -> List[Checker]:
    from glom_tpu.analysis.axisenv import AxisEnvironment
    from glom_tpu.analysis.collectives import CollectiveCoverage
    from glom_tpu.analysis.donation import DonationSafety
    from glom_tpu.analysis.lockset import LockOrder, Lockset
    from glom_tpu.analysis.purity import TracePurity
    from glom_tpu.analysis.schema_emit import SchemaEmit
    from glom_tpu.analysis.sighandler import SignalSafety

    return [
        CollectiveCoverage(),
        AxisEnvironment(),
        TracePurity(),
        DonationSafety(),
        SchemaEmit(),
        Lockset(),
        LockOrder(),
        SignalSafety(),
    ]


def run(
    paths: Iterable[str],
    *,
    select: Optional[Iterable[str]] = None,
    checkers: Optional[List[Checker]] = None,
    warnings: Optional[List[str]] = None,
    cache: Optional[object] = None,
    scratch: Optional[dict] = None,
) -> List[Finding]:
    """Run the pass; returns findings NOT suppressed by inline pragmas
    (baseline filtering is the caller's job — see baseline.apply).
    Includes a framework finding for any pragma without a reason, and for
    unparseable files. When `warnings` is given (and every checker ran —
    a partial --select can't judge), pragmas that suppressed nothing are
    reported into it so fixed-and-forgotten suppressions rot visibly,
    mirroring the baseline's stale-entry warnings.

    `cache` is an analysis/cache.py AnalysisCache: every file is still
    PARSED (the project graph needs the whole analyzed set), but files
    whose content-fingerprint closure is unchanged reuse their stored
    findings/warnings instead of re-running the checkers.

    `scratch`, when given, is used as the Context's scratch dict so
    callers (tests, tooling) can inspect the project-wide evidence the
    checkers record there — the lock-order acquisition edges, the
    axis-environment attestation trail."""
    from glom_tpu.analysis.project import ProjectGraph

    modules, findings = load_modules(paths)
    ctx = Context(modules=modules)
    if scratch is not None:
        ctx.scratch = scratch
    ctx.project = ProjectGraph(modules)
    _collect_axis_vocab(modules, ctx)
    active = checkers if checkers is not None else default_checkers()
    if select is not None:
        wanted = set(select)
        unknown = wanted - {c.name for c in active}
        if unknown:
            raise ValueError(f"unknown checkers: {sorted(unknown)}")
        active = [c for c in active if c.name in wanted]
    if cache is not None:
        cache.begin(ctx, active, select=select)
    for mod in modules:
        if cache is not None:
            hit = cache.lookup(mod)
            if hit is not None:
                mod_findings, mod_warnings = hit
                findings.extend(mod_findings)
                if warnings is not None:
                    warnings.extend(mod_warnings)
                continue
        mod_findings: List[Finding] = []
        mod_warnings: List[str] = []
        for checker in active:
            for f in checker.check(mod, ctx):
                if not mod.suppressed(f):
                    mod_findings.append(f)
        for p in mod.pragmas:
            if not p.reason:
                mod_findings.append(
                    Finding(
                        checker="pragma",
                        path=mod.relpath,
                        line=p.line,
                        col=0,
                        message="suppression without a reason (pragmas are "
                        "reviewed artifacts: say WHY the site is ok)",
                        key="missing-reason",
                    )
                )
            elif select is None and not p.used:
                mod_warnings.append(
                    f"{mod.relpath}:{p.line}: unused pragma "
                    f"ok[{','.join(sorted(p.checkers))}] — the finding it "
                    "suppressed no longer fires; delete it"
                )
        findings.extend(mod_findings)
        if warnings is not None:
            warnings.extend(mod_warnings)
        if cache is not None:
            cache.store(mod, mod_findings, mod_warnings)
    if cache is not None:
        cache.finish()
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.checker))
    return findings
