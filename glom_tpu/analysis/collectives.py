"""collective-coverage: every manual-path collective is axis-sound and
wire-accounted.

Two rules, both static mirrors of runtime invariants PR 1-2 established:

  1. AXIS NAMES (all scanned files): the axis argument of every
     psum / psum_scatter / pmean / all_gather / ppermute / all_to_all /
     axis_index call must resolve to a declared mesh axis — a module-level
     `*_AXIS` string constant, a literal in the mesh vocabulary
     (MeshConfig.axis_names), or an `axis`-named parameter threaded in by
     the caller (the ring/halo/ulysses bodies). A typo'd axis name fails
     at runtime only when that exact mesh shape is exercised — EQuARX and
     the Automatic Cross-Replica Sharding work both show manual collective
     schedules are where silent mismatches creep in, so the lint catches
     it on CPU.

  2. REGISTRATION (wire-accounted modules only — parallel/manual.py and
     parallel/quantized.py): every wire-moving collective
     (psum/psum_scatter/pmean/all_gather) call site must sit in a function
     that also calls telemetry.counters.record_collective — the static
     mirror of the runtime comm_model_drift reconciliation, which only
     catches an unregistered site when a live mesh traces the step.
     Scalar loss/metric collectives that are deliberately outside the
     wire model carry reviewed suppressions (see analysis_baseline.json).
"""

from __future__ import annotations

import ast
from typing import List, Optional

from glom_tpu.analysis.astutil import (
    call_name,
    enclosing_function,
    imported_collective_aliases,
    qualname_at,
)
from glom_tpu.analysis.core import Checker, Context, Finding, SourceModule

# collective -> positional index of the axis-name argument
AXIS_ARG = {
    "psum": 1,
    "pmean": 1,
    "pmax": 1,
    "pmin": 1,
    "psum_scatter": 1,
    "all_gather": 1,
    "all_to_all": 1,
    "ppermute": 1,
    "axis_index": 0,
}
# the wire-moving subset that must be record_collective-registered in the
# wire-accounted modules
WIRE_MOVING = {"psum", "psum_scatter", "pmean", "all_gather", "all_to_all"}
# Functions that count as registering the enclosing site with the measured
# counters: record_collective (byte-only, the PR 2 form) or the shared
# timing wrapper timed_collective (bytes + site registry + optional
# io_callback brackets — the capacity observatory's sanctioned route).
_REGISTERING = {"record_collective", "timed_collective"}
# Host clocks and callback primitives that mark a HAND-ROLLED timing
# harness when they share a function chain with a wire-moving collective:
# the trace-purity checker already bans bare host clocks in traced code,
# and the per-collective wall-time contract requires every timed site to
# route through counters.timed_collective (one wrapper = one clock
# discipline, one record shape, one lint surface).
_TIMING_PRIMITIVES = {
    "perf_counter", "monotonic", "perf_counter_ns", "monotonic_ns",
    "io_callback", "pure_callback", "callback",
}


def _collective_of(call: ast.Call, aliases: dict) -> Optional[str]:
    name = call_name(call)
    if name is None:
        return None
    parts = name.split(".")
    leaf = parts[-1]
    if leaf not in AXIS_ARG:
        return None
    if len(parts) == 1:
        # bare call: only a collective if imported from jax.lax
        return leaf if aliases.get(leaf) == leaf else None
    base = parts[-2]
    if base == "lax" or aliases.get(parts[0]) == "<laxmod>":
        return leaf
    return None


class CollectiveCoverage(Checker):
    name = "collective-coverage"
    description = (
        "manual-path collectives use declared mesh axes and are "
        "registered with telemetry.counters"
    )

    def check(self, module: SourceModule, ctx: Context) -> List[Finding]:
        aliases = imported_collective_aliases(module.tree)
        findings: List[Finding] = []
        registered_scope = any(
            module.relpath.endswith(suffix)
            for suffix in ctx.registration_modules
        )
        # Pre-collect: per function node, does it call a registering
        # function (record_collective / timed_collective), a timing
        # primitive, or the shared wrapper specifically? The wrapper takes
        # the collective as a LAMBDA, so membership checks walk the whole
        # enclosing-scope CHAIN (lambda -> function -> ...), not just the
        # innermost scope.
        records_in: set = set()
        timing_in: set = set()
        wrapper_in: set = set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                name = call_name(node)
                leaf = name.split(".")[-1] if name else None
                if leaf in _REGISTERING:
                    fn = enclosing_function(module.parents, node)
                    records_in.add(id(fn))
                    if leaf == "timed_collective":
                        wrapper_in.add(id(fn))
                if leaf in _TIMING_PRIMITIVES:
                    fn = enclosing_function(module.parents, node)
                    timing_in.add(id(fn))

        def scope_chain(node):
            """Every enclosing function/lambda of `node`, innermost
            first (module level terminates the chain)."""
            fn = enclosing_function(module.parents, node)
            while fn is not None:
                yield fn
                fn = enclosing_function(module.parents, fn)

        # Module-level string constants (for axis-arg resolution).
        consts = {}
        for node in module.tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Name)
                        and isinstance(node.value, ast.Constant)
                        and isinstance(node.value.value, str)
                    ):
                        consts[t.id] = node.value.value

        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            coll = _collective_of(node, aliases)
            if coll is None:
                continue
            symbol = qualname_at(module.parents, module.index, node)
            findings.extend(
                self._check_axis(module, ctx, node, coll, consts, symbol)
            )
            if registered_scope and coll in WIRE_MOVING:
                chain = list(scope_chain(node))
                chain_ids = {id(fn) for fn in chain}
                if not chain_ids & records_in:
                    findings.append(
                        Finding(
                            checker=self.name,
                            path=module.relpath,
                            line=node.lineno,
                            col=node.col_offset,
                            message=(
                                f"lax.{coll} site is not registered with "
                                "telemetry.counters.record_collective — "
                                "the measured wire bytes (and "
                                "comm_model_drift) silently omit it"
                            ),
                            symbol=symbol,
                            key=f"unregistered-{coll}",
                        )
                    )
                if chain_ids & timing_in and not chain_ids & wrapper_in:
                    # A registered site that hand-rolls its own clock or
                    # callback harness around the collective: the
                    # per-collective wall-time contract requires the ONE
                    # shared wrapper (counters.timed_collective), so
                    # every timed site shares a clock discipline, record
                    # shape, and purity audit — and the trace-purity
                    # checker's host-clock ban stays meaningful.
                    findings.append(
                        Finding(
                            checker=self.name,
                            path=module.relpath,
                            line=node.lineno,
                            col=node.col_offset,
                            message=(
                                f"lax.{coll} site is timed with a "
                                "hand-rolled clock/callback harness — "
                                "route the timing through "
                                "counters.timed_collective (the shared "
                                "timing wrapper; docs/OBSERVABILITY.md, "
                                "Capacity observatory)"
                            ),
                            symbol=symbol,
                            key=f"hand-rolled-timing-{coll}",
                        )
                    )
        return findings

    # -- axis resolution ----------------------------------------------------

    def _axis_node(self, call: ast.Call, coll: str) -> Optional[ast.AST]:
        for kw in call.keywords:
            if kw.arg == "axis_name":
                return kw.value
        idx = AXIS_ARG[coll]
        if len(call.args) > idx:
            return call.args[idx]
        return None

    def _axis_ok(
        self,
        node: ast.AST,
        ctx: Context,
        consts: dict,
        call: ast.Call,
        module: SourceModule,
    ) -> Optional[str]:
        """None when the axis resolves to a declared name; else a short
        reason string for the finding."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if node.value in ctx.axis_vocab:
                return None
            return (
                f"axis {node.value!r} is not a declared mesh axis "
                f"{sorted(ctx.axis_vocab)}"
            )
        if isinstance(node, (ast.Tuple, ast.List)):
            for elt in node.elts:
                reason = self._axis_ok(elt, ctx, consts, call, module)
                if reason:
                    return reason
            return None
        if isinstance(node, ast.Name):
            if node.id in consts:
                if consts[node.id] in ctx.axis_vocab:
                    return None
                return (
                    f"axis constant {node.id}={consts[node.id]!r} is not a "
                    f"declared mesh axis {sorted(ctx.axis_vocab)}"
                )
            # An axis threaded in by the caller: accept parameters whose
            # name says so (axis_name=SEQ_AXIS at the call sites is what
            # the vocabulary rule already checked).
            fn = enclosing_function(module.parents, node)
            while fn is not None:
                info = module.index.info_for(fn)
                if info is not None and node.id in info.params:
                    if "axis" in node.id:
                        return None
                    return (
                        f"axis comes from parameter {node.id!r} — rename it "
                        "to carry 'axis' so call sites are checkable, or "
                        "pass a declared axis constant"
                    )
                fn = enclosing_function(module.parents, fn)
            return f"axis name {node.id!r} is not statically resolvable"
        return "axis argument is not statically resolvable"

    def _check_axis(
        self,
        module: SourceModule,
        ctx: Context,
        call: ast.Call,
        coll: str,
        consts: dict,
        symbol: str,
    ) -> List[Finding]:
        axis = self._axis_node(call, coll)
        if axis is None:
            reason = f"lax.{coll} call has no axis argument"
        else:
            reason = self._axis_ok(axis, ctx, consts, call, module)
        if reason is None:
            return []
        return [
            Finding(
                checker=self.name,
                path=module.relpath,
                line=call.lineno,
                col=call.col_offset,
                message=f"lax.{coll}: {reason}",
                symbol=symbol,
                key=f"axis-{coll}",
            )
        ]
