"""project: the whole-program layer under the cross-module checkers.

Every checker used to analyze ONE module at a time, and the ROADMAP's
"Analysis depth" backlog listed the four blind spots that are all the
same blind spot: nothing could see across an import boundary. This
module is the shared fix — a project graph over the ANALYZED paths
(whole-program means "whole analyzed set": lint one file and you get
exactly the old per-module pass):

  * module naming + import tables: each parsed file becomes a dotted
    module name (the longest identifier suffix of its relpath, so the
    same file resolves whether the pass runs from the repo root or over
    a tmp fixture dir); `import x.y as z`, `from x import y as z`, and
    `from pkg import submodule` all land in per-module alias tables;
  * cross-module symbol resolution with RE-EXPORT chasing: resolving
    `trace` through `utils/profiling.py` (a pure `from tracing.capture
    import trace` shim) lands on the defining module, bounded and
    cycle-guarded;
  * a cross-module call graph: `resolve_call` takes a Call node and
    returns the (module, FuncInfo) it names — lexical scope first (the
    old intra-module behavior, unchanged), then the import tables for
    bare `from x import f` names and dotted `mod.f` references; a
    reverse index (`callers_of`) gives every analyzed call site of a
    function, which is what lets axis-environment follow a mesh from
    the runtime that builds it into the module whose shard_map binds
    it as an opaque parameter;
  * a light TYPE layer for first-order object references: parameter /
    return annotations (`-> Optional[ColumnCache]`), constructor calls,
    statement-order local flow, `self.attr` types inferred from
    `__init__`, and dict value types (`Dict[str, "PagedColumnPool"]`,
    `dict(pools)`, `self.pools[k]`) — enough to resolve the real
    batcher -> cache -> pool acquisition chain and the engine handle
    dispatched from the batcher, and nothing fancier: unresolvable
    stays None, the precision stance everywhere in this package.

Both directions of the import graph matter downstream: purity/donation/
lock-order facts flow from a module's IMPORTS (callee bodies), while
axis-environment attestation flows from its IMPORTERS (the caller owns
the mesh). `dep_closure` therefore hashes a file together with the
import closures of its whole importer cone — the soundness contract the
fingerprint cache (analysis/cache.py) is built on. Pure stdlib.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from glom_tpu.analysis.astutil import FuncInfo, call_name
from glom_tpu.analysis.core import SourceModule

# Bound on cross-module hops (symbol re-export chains, caller recursion,
# call-graph reach). Deep enough for every real chain in the repo
# (runtime -> manual -> helper is 2), small enough that a pathological
# import cycle can't wedge the pass.
MAX_DEPTH = 6


def module_name_of(relpath: str) -> str:
    """Dotted module name from a '/'-separated relpath: the LONGEST
    trailing run of identifier-shaped parts, so 'glom_tpu/serve/engine.py'
    is 'glom_tpu.serve.engine' from the repo root and a tmp-dir fixture
    ('/tmp/pytest-123/t0/xmod_util.py') still gets a resolvable suffix."""
    path = relpath[:-3] if relpath.endswith(".py") else relpath
    parts = path.replace("\\", "/").split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    kept: List[str] = []
    for part in reversed(parts):
        if part.isidentifier():
            kept.append(part)
        else:
            break
    return ".".join(reversed(kept)) if kept else "<unnamed>"


@dataclass
class TypeRef:
    """A statically-inferred object type: `cls` is a class key
    ('module.name:ClassName'); `dict_value` is the class key of a dict's
    VALUE type (the `self.pools[engine]` shape). Exactly one is set."""

    cls: Optional[str] = None
    dict_value: Optional[str] = None


class ModuleInfo:
    """One module's name + import tables + top-level class table."""

    def __init__(self, module: SourceModule):
        self.module = module
        self.name = module_name_of(module.relpath)
        # local alias -> module name as written ('import x.y as z')
        self.module_aliases: Dict[str, str] = {}
        # local name -> (module as written, original symbol)
        self.symbol_imports: Dict[str, Tuple[str, str]] = {}
        self.classes: Dict[str, ast.ClassDef] = {
            n.name: n
            for n in module.tree.body
            if isinstance(n, ast.ClassDef)
        }
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.module_aliases[a.asname or a.name] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                # Relative imports (level > 0) don't occur in this repo;
                # treating them as opaque keeps resolution honest.
                if node.level:
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.symbol_imports[a.asname or a.name] = (
                        node.module,
                        a.name,
                    )


class ProjectGraph:
    """Whole-program tables over the analyzed modules. Built once per
    run (core.run) and shared through Context.project."""

    def __init__(self, modules: List[SourceModule]):
        self.infos: Dict[str, ModuleInfo] = {}
        self.by_name: Dict[str, ModuleInfo] = {}
        for m in modules:
            info = ModuleInfo(m)
            self.infos[m.relpath] = info
            self.by_name.setdefault(info.name, info)
        self._imports: Dict[str, Set[str]] = {}
        self._importers: Dict[str, Set[str]] = {}
        self._build_import_edges()
        self._callers: Optional[Dict[int, List[Tuple[ModuleInfo, Optional[FuncInfo], ast.Call]]]] = None

    # -- module resolution ---------------------------------------------------

    def info_of(self, module: SourceModule) -> ModuleInfo:
        return self.infos[module.relpath]

    def resolve_module_name(self, written: str) -> Optional[ModuleInfo]:
        """Analyzed module for an import name as written. Exact dotted
        match first; else a unique suffix match in either direction (the
        analyzed names carry tmp-dir prefixes, or the written name
        carries package parts the analyzed root stripped). Ambiguity
        resolves to None — never guess."""
        info = self.by_name.get(written)
        if info is not None:
            return info
        cands = [
            i
            for i in self.by_name.values()
            if i.name.endswith("." + written) or written.endswith("." + i.name)
        ]
        return cands[0] if len(cands) == 1 else None

    # -- import graph --------------------------------------------------------

    def _build_import_edges(self) -> None:
        for rel, info in self.infos.items():
            edges: Set[str] = set()
            for written in info.module_aliases.values():
                target = self.resolve_module_name(written)
                if target is not None:
                    edges.add(target.module.relpath)
            for mod_written, sym in info.symbol_imports.values():
                target = self.resolve_module_name(mod_written)
                if target is None:
                    # `from pkg import submodule`
                    target = self.resolve_module_name(f"{mod_written}.{sym}")
                if target is not None:
                    edges.add(target.module.relpath)
            edges.discard(rel)
            self._imports[rel] = edges
            for e in edges:
                self._importers.setdefault(e, set()).add(rel)

    def imports_of(self, relpath: str) -> Set[str]:
        return self._imports.get(relpath, set())

    def importers_of(self, relpath: str) -> Set[str]:
        return self._importers.get(relpath, set())

    def _transitive(self, start: str, edges: Dict[str, Set[str]]) -> Set[str]:
        seen = {start}
        frontier = [start]
        while frontier:
            n = frontier.pop()
            for nxt in edges.get(n, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        return seen

    def dep_closure(self, relpath: str) -> Set[str]:
        """Every analyzed file whose content can influence THIS file's
        findings: the import closure of every module in this file's
        importer cone (itself included). Downstream facts (purity
        reachability, donation handles, lock acquisitions) flow along
        imports; attestation (axis-environment) flows from importers —
        and an importer's OWN resolution context is its import closure,
        hence the composed shape. This is the cache's soundness
        contract (analysis/cache.py)."""
        out: Set[str] = set()
        for up in self._transitive(relpath, self._importers):
            out |= self._transitive(up, self._imports)
        return out

    # -- symbol / function / class resolution --------------------------------

    def resolve_symbol(
        self, info: ModuleInfo, symbol: str, depth: int = MAX_DEPTH
    ) -> Optional[Tuple[ModuleInfo, str]]:
        """(defining module, name) for a top-level function/class symbol,
        chasing `from x import y [as z]` re-export shims (the
        utils/profiling.py shape), bounded and cycle-guarded."""
        seen: Set[Tuple[str, str]] = set()
        while depth > 0:
            key = (info.module.relpath, symbol)
            if key in seen:
                return None
            seen.add(key)
            if (
                symbol in info.module.index.module_scope.functions
                or symbol in info.classes
            ):
                return (info, symbol)
            imp = info.symbol_imports.get(symbol)
            if imp is None:
                return None
            target = self.resolve_module_name(imp[0])
            if target is None:
                return None
            info, symbol = target, imp[1]
            depth -= 1
        return None

    def resolve_function(
        self, module: SourceModule, dotted_name: str
    ) -> Optional[Tuple[ModuleInfo, FuncInfo]]:
        """(module, FuncInfo) for a bare imported name ('helper') or a
        module-qualified reference ('counters.timed_collective',
        'glom_tpu.utils.profiling.trace'); None for anything it cannot
        prove — locals, methods, third-party namespaces."""
        info = self.infos.get(module.relpath)
        if info is None:
            return None
        parts = dotted_name.split(".")
        if len(parts) == 1:
            resolved = self.resolve_symbol(info, parts[0])
        else:
            resolved = self._resolve_qualified(info, parts)
        if resolved is None:
            return None
        target, symbol = resolved
        fn = target.module.index.module_scope.functions.get(symbol)
        return (target, fn) if fn is not None else None

    def resolve_class(
        self, module: SourceModule, dotted_name: str
    ) -> Optional[Tuple[ModuleInfo, ast.ClassDef]]:
        info = self.infos.get(module.relpath)
        if info is None:
            return None
        parts = dotted_name.split(".")
        if len(parts) == 1:
            resolved = self.resolve_symbol(info, parts[0])
        else:
            resolved = self._resolve_qualified(info, parts)
        if resolved is None:
            return None
        target, symbol = resolved
        cls = target.classes.get(symbol)
        return (target, cls) if cls is not None else None

    def class_key(self, info: ModuleInfo, cls_name: str) -> str:
        return f"{info.name}:{cls_name}"

    def _resolve_qualified(
        self, info: ModuleInfo, parts: List[str]
    ) -> Optional[Tuple[ModuleInfo, str]]:
        """'alias[.sub...].symbol' through the module-alias table
        (longest alias prefix wins), or `from pkg import submod` +
        'submod.symbol'."""
        for i in range(len(parts) - 1, 0, -1):
            prefix = ".".join(parts[:i])
            written = info.module_aliases.get(prefix)
            if written is None and i == 1:
                imp = info.symbol_imports.get(parts[0])
                if imp is not None:
                    written = f"{imp[0]}.{imp[1]}"
            if written is None:
                continue
            rest = parts[i:]
            # the tail may cross submodules: alias='glom_tpu', rest =
            # ['telemetry', 'counters', 'record_collective']
            for j in range(len(rest) - 1, -1, -1):
                mod_written = ".".join([written] + rest[:j])
                target = self.resolve_module_name(mod_written)
                if target is not None and j == len(rest) - 1:
                    return self.resolve_symbol(target, rest[-1])
            return None
        return None

    # -- cross-module call graph ---------------------------------------------

    def resolve_call(
        self,
        module: SourceModule,
        caller: Optional[FuncInfo],
        call: ast.Call,
    ) -> Optional[Tuple[ModuleInfo, FuncInfo]]:
        """The analyzed function a Call names: lexical scope first (the
        unchanged intra-module rule), then the import tables."""
        name = call_name(call)
        if not name:
            return None
        if "." not in name:
            scope = (
                caller.scope if caller is not None else module.index.module_scope
            )
            intra = scope.resolve(name)
            if intra is not None:
                return (self.info_of(module), intra)
        if name.startswith("self."):
            return None  # method dispatch is the type layer's job
        return self.resolve_function(module, name)

    def callers_of(
        self, target: FuncInfo
    ) -> List[Tuple[ModuleInfo, Optional[FuncInfo], ast.Call]]:
        """Every analyzed call site resolving to `target`: (module,
        enclosing function or None for module level, the Call node)."""
        if self._callers is None:
            self._callers = {}
            for info in self.infos.values():
                mod = info.module
                for finfo in mod.index.functions.values():
                    for node in finfo.body_nodes():
                        if isinstance(node, ast.Call):
                            hit = self.resolve_call(mod, finfo, node)
                            if hit is not None:
                                self._callers.setdefault(
                                    id(hit[1].node), []
                                ).append((info, finfo, node))
                for node in self._module_level_nodes(mod):
                    if isinstance(node, ast.Call):
                        hit = self.resolve_call(mod, None, node)
                        if hit is not None:
                            self._callers.setdefault(
                                id(hit[1].node), []
                            ).append((info, None, node))
        return self._callers.get(id(target.node), [])

    @staticmethod
    def _module_level_nodes(mod: SourceModule):
        from glom_tpu.analysis.astutil import SCOPE_NODES

        stack: List[ast.AST] = list(mod.tree.body)
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, SCOPE_NODES):
                continue  # function/lambda bodies belong to their FuncInfo
            for child in ast.iter_child_nodes(node):
                stack.append(child)

    # -- the type layer -------------------------------------------------------

    def annotation_type(
        self, info: ModuleInfo, ann: Optional[ast.AST], depth: int = MAX_DEPTH
    ) -> Optional[TypeRef]:
        """TypeRef for an annotation expression: bare/imported class
        names, 'StringForward' constants, Optional/Final unwrap, Union
        with a single class member, Dict[...] value types."""
        if ann is None or depth <= 0:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
            return self.annotation_type(info, ann, depth - 1)
        if isinstance(ann, (ast.Name, ast.Attribute)):
            name = call_name(ast.Call(func=ann, args=[], keywords=[]))
            if name is None:
                return None
            hit = self.resolve_class(info.module, name)
            if hit is not None:
                return TypeRef(cls=self.class_key(hit[0], hit[1].name))
            return None
        if isinstance(ann, ast.Subscript):
            base = ann.value
            base_name = (
                base.attr if isinstance(base, ast.Attribute) else
                base.id if isinstance(base, ast.Name) else None
            )
            if base_name in ("Optional", "Final", "Annotated"):
                inner = ann.slice
                if base_name == "Annotated" and isinstance(inner, ast.Tuple):
                    inner = inner.elts[0] if inner.elts else None
                return self.annotation_type(info, inner, depth - 1)
            if base_name in ("Dict", "dict", "Mapping", "MutableMapping"):
                if isinstance(ann.slice, ast.Tuple) and len(ann.slice.elts) == 2:
                    value = self.annotation_type(
                        info, ann.slice.elts[1], depth - 1
                    )
                    if value is not None and value.cls is not None:
                        return TypeRef(dict_value=value.cls)
                return None
            if base_name == "Union":
                members = (
                    ann.slice.elts
                    if isinstance(ann.slice, ast.Tuple)
                    else [ann.slice]
                )
                hits = [
                    t
                    for t in (
                        self.annotation_type(info, m, depth - 1)
                        for m in members
                    )
                    if t is not None
                ]
                return hits[0] if len(hits) == 1 else None
        return None

    def expr_type(
        self,
        info: ModuleInfo,
        expr: Optional[ast.AST],
        local_types: Dict[str, TypeRef],
        depth: int = MAX_DEPTH,
    ) -> Optional[TypeRef]:
        """TypeRef of an expression under `local_types` (name -> type):
        constructor calls, calls of functions with class-resolving return
        annotations, `dict(x)` passthrough, conditional expressions with
        agreeing arms, `x[k]` on a dict-typed name."""
        if expr is None or depth <= 0:
            return None
        if isinstance(expr, ast.Name):
            return local_types.get(expr.id)
        if isinstance(expr, ast.IfExp):
            arms = [
                self.expr_type(info, a, local_types, depth - 1)
                for a in (expr.body, expr.orelse)
            ]
            arms = [a for a in arms if a is not None]
            if len(arms) == 1 or (len(arms) == 2 and arms[0] == arms[1]):
                return arms[0]
            return None
        if isinstance(expr, ast.BoolOp):
            arms = [
                self.expr_type(info, v, local_types, depth - 1)
                for v in expr.values
            ]
            arms = [a for a in arms if a is not None]
            return arms[0] if len(arms) == 1 else None
        if isinstance(expr, ast.Subscript):
            base = self.expr_type(info, expr.value, local_types, depth - 1)
            if base is not None and base.dict_value is not None:
                return TypeRef(cls=base.dict_value)
            return None
        if isinstance(expr, ast.Call):
            name = call_name(expr)
            if name is None:
                return None
            if name.split(".")[-1] == "dict" and len(expr.args) == 1:
                inner = self.expr_type(
                    info, expr.args[0], local_types, depth - 1
                )
                if inner is not None and inner.dict_value is not None:
                    return inner
                return None
            hit = self.resolve_class(info.module, name)
            if hit is not None:
                return TypeRef(cls=self.class_key(hit[0], hit[1].name))
            fn = self.resolve_function(info.module, name)
            if fn is not None:
                target_info, finfo = fn
                returns = getattr(finfo.node, "returns", None)
                return self.annotation_type(target_info, returns, depth - 1)
        return None

    def function_local_types(
        self, info: ModuleInfo, finfo: FuncInfo
    ) -> Dict[str, TypeRef]:
        """name -> TypeRef after one statement-order pass over a
        function: annotated parameters seed the map; assignments update
        it (unresolvable right-hand sides CLEAR the name — a rebind to
        an unknown must not keep the stale type)."""
        types: Dict[str, TypeRef] = {}
        node = finfo.node
        args = getattr(node, "args", None)
        if args is not None:
            for p in args.posonlyargs + args.args + args.kwonlyargs:
                t = self.annotation_type(info, p.annotation)
                if t is not None:
                    types[p.arg] = t
        stmts = [
            n
            for n in finfo.body_nodes()
            if isinstance(n, (ast.Assign, ast.AnnAssign))
        ]
        stmts.sort(key=lambda n: getattr(n, "lineno", 0))
        for stmt in stmts:
            if isinstance(stmt, ast.AnnAssign):
                targets = [stmt.target]
                t = self.annotation_type(info, stmt.annotation)
                if t is None:
                    t = self.expr_type(info, stmt.value, types)
            else:
                targets = stmt.targets
                t = self.expr_type(info, stmt.value, types)
            for target in targets:
                if isinstance(target, ast.Name):
                    if t is not None:
                        types[target.id] = t
                    else:
                        types.pop(target.id, None)
        return types

    def class_attr_types(
        self, info: ModuleInfo, cls: ast.ClassDef
    ) -> Dict[str, TypeRef]:
        """attr -> TypeRef for `self.attr = ...` assignments in
        __init__, with the ctor's annotated parameters and local flow in
        scope (the `self.cache = column_cache` shape, where
        column_cache was rebound from an annotated provider)."""
        init = next(
            (
                n
                for n in cls.body
                if isinstance(n, ast.FunctionDef) and n.name == "__init__"
            ),
            None,
        )
        if init is None:
            return {}
        finfo = info.module.index.info_for(init)
        if finfo is None:
            return {}
        local_types = self.function_local_types(info, finfo)
        out: Dict[str, TypeRef] = {}
        stmts = [
            n for n in finfo.body_nodes() if isinstance(n, (ast.Assign, ast.AnnAssign))
        ]
        stmts.sort(key=lambda n: getattr(n, "lineno", 0))
        for stmt in stmts:
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            )
            for target in targets:
                if (
                    isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                ):
                    t = None
                    if isinstance(stmt, ast.AnnAssign):
                        t = self.annotation_type(info, stmt.annotation)
                    if t is None:
                        t = self.expr_type(info, stmt.value, local_types)
                    if t is not None:
                        out.setdefault(target.attr, t)
        return out

    def receiver_resolver(self, module: SourceModule, finfo: FuncInfo):
        """A callable mapping a RECEIVER expression inside `finfo` to its
        TypeRef: annotated params and local flow, `self` as the enclosing
        class, typed `self.attr` from `__init__`, and dict subscripts
        (`self.pools[k]`). The shared resolution step under the
        cross-object donation and lock-order analyses."""
        minfo = self.info_of(module)
        local_types = self.function_local_types(minfo, finfo)
        own_cls = self.enclosing_class(module, finfo)
        self_types = (
            self.class_attr_types(minfo, own_cls)
            if own_cls is not None
            else {}
        )
        if own_cls is not None:
            local_types.setdefault(
                "self", TypeRef(cls=self.class_key(minfo, own_cls.name))
            )

        def rtype(expr: ast.AST) -> Optional[TypeRef]:
            t = self.expr_type(minfo, expr, local_types)
            if t is not None:
                return t
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
            ):
                return self_types.get(expr.attr)
            if isinstance(expr, ast.Subscript):
                base = rtype(expr.value)
                if base is not None and base.dict_value is not None:
                    return TypeRef(cls=base.dict_value)
            return None

        return rtype

    def enclosing_class(
        self, module: SourceModule, finfo: FuncInfo
    ) -> Optional[ast.ClassDef]:
        """The TOP-LEVEL class a function belongs to (methods and their
        nested defs — the qualname prefix), or None."""
        head = finfo.qualname.split(".")[0]
        info = self.infos.get(module.relpath)
        if info is None:
            return None
        return info.classes.get(head)
