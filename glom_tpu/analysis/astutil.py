"""Shared AST plumbing for the glom-lint checkers.

Everything here is deliberately SIMPLE static analysis: lexical scope
chains, dotted-name rendering, statement-order walks. The checkers trade
soundness for zero-dependency CPU-cheap checks that run in CI and as the
hardware queue's pre-flight — a miss is acceptable, a crash or a jax
import is not (the pass must run on a box where jax is broken, which is
exactly when you most want to lint the evidence trail). Pure stdlib.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
SCOPE_NODES = FUNC_NODES + (ast.Lambda,)


def dotted(node: ast.AST) -> Optional[str]:
    """Render a Name/Attribute chain as 'a.b.c'; None for anything with a
    non-name root (calls, subscripts)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted(call.func)


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class Scope:
    """One lexical scope (module or function) with its directly-defined
    functions; `resolve` walks the chain outward, so a nested body can
    call a sibling nested def or a module-level helper and the checkers
    follow it."""

    def __init__(self, node: ast.AST, parent: Optional["Scope"], qualname: str):
        self.node = node
        self.parent = parent
        self.qualname = qualname
        self.functions: Dict[str, "FuncInfo"] = {}

    def resolve(self, name: str) -> Optional["FuncInfo"]:
        scope: Optional[Scope] = self
        while scope is not None:
            fn = scope.functions.get(name)
            if fn is not None:
                return fn
            scope = scope.parent
        return None


class FuncInfo:
    """A function (or lambda) definition with its enclosing scope chain."""

    def __init__(self, node: ast.AST, scope: Scope, qualname: str):
        self.node = node
        self.scope = scope  # the scope the function DEFINES (for its body)
        self.qualname = qualname

    @property
    def params(self) -> List[str]:
        a = self.node.args
        names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names

    def body_nodes(self) -> Iterator[ast.AST]:
        """All nodes of this function's body, NOT descending into nested
        function/lambda bodies (those are their own FuncInfos)."""
        body = (
            [self.node.body]
            if isinstance(self.node, ast.Lambda)
            else list(self.node.body)
        )
        stack: List[ast.AST] = list(body)
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, SCOPE_NODES):
                # A nested def/lambda statement is visible, its body is
                # its own scope — including when the def is a DIRECT
                # statement of this body (that case used to leak, which
                # surfaced the moment cross-module reach met the
                # io_callback host-half idiom in telemetry/counters.py).
                continue
            stack.extend(ast.iter_child_nodes(node))


class ModuleIndex:
    """Scope tree + function table for one parsed module."""

    def __init__(self, tree: ast.Module):
        self.module_scope = Scope(tree, None, "<module>")
        self.functions: Dict[int, FuncInfo] = {}  # id(node) -> info
        self._index(tree, self.module_scope, "")

    def _index(self, node: ast.AST, scope: Scope, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, SCOPE_NODES):
                name = getattr(child, "name", "<lambda>")
                qual = f"{prefix}{name}" if prefix else name
                info = FuncInfo(child, Scope(child, scope, qual), qual)
                self.functions[id(child)] = info
                if name != "<lambda>":
                    scope.functions[name] = info
                self._index(child, info.scope, f"{qual}.")
            elif isinstance(child, ast.ClassDef):
                self._index(child, scope, f"{prefix}{child.name}.")
            else:
                self._index(child, scope, prefix)

    def info_for(self, node: ast.AST) -> Optional[FuncInfo]:
        return self.functions.get(id(node))


def enclosing_function(
    parents: Dict[int, ast.AST], node: ast.AST
) -> Optional[ast.AST]:
    """Innermost FunctionDef/Lambda containing `node` (None at module
    level). `parents` comes from build_parent_map."""
    cur = parents.get(id(node))
    while cur is not None:
        if isinstance(cur, SCOPE_NODES):
            return cur
        cur = parents.get(id(cur))
    return None


def build_parent_map(tree: ast.AST) -> Dict[int, ast.AST]:
    parents: Dict[int, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    return parents


def qualname_at(
    parents: Dict[int, ast.AST], index: ModuleIndex, node: ast.AST
) -> str:
    """Stable scope label for a finding: the qualname of the innermost
    enclosing function, or '<module>'."""
    fn = enclosing_function(parents, node)
    if fn is None:
        return "<module>"
    info = index.info_for(fn)
    return info.qualname if info is not None else getattr(fn, "name", "<lambda>")


def assigned_names(target: ast.AST) -> Iterator[str]:
    """Simple Name targets of an assignment (tuple targets unpacked)."""
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from assigned_names(elt)


def names_in(node: ast.AST) -> Iterator[ast.Name]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            yield sub


def imported_collective_aliases(tree: ast.Module) -> Dict[str, str]:
    """Map local alias -> canonical jax.lax symbol for collectives imported
    bare (`from jax.lax import psum as ps`) or via a lax module alias
    (`from jax import lax`, `import jax.lax as lax`)."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module in ("jax.lax", "jax._src.lax.parallel"):
                for a in node.names:
                    aliases[a.asname or a.name] = a.name
            elif node.module == "jax":
                for a in node.names:
                    if a.name == "lax":
                        aliases[(a.asname or "lax")] = "<laxmod>"
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.lax" and a.asname:
                    aliases[a.asname] = "<laxmod>"
    return aliases


def statement_line(node: ast.AST) -> int:
    return getattr(node, "lineno", 0)


def literal_int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """(1,) / 1 / () as a tuple of ints; None when not a literal."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, ast.Tuple):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
            else:
                return None
        return tuple(out)
    return None
