"""CLI: python -m glom_tpu.analysis [PATHS] [--baseline FILE].

Exit codes: 0 clean (or fully covered by the baseline), 1 new findings
(or an unreviewed baseline entry), 2 usage errors. Stale baseline
entries and unused pragmas are warnings — the ratchet tightens without
blocking the fix that made an entry stale.
"""

from __future__ import annotations

import sys
from pathlib import Path
from typing import List, Optional

from glom_tpu.analysis import baseline as baseline_mod
from glom_tpu.analysis.core import default_checkers, run

DEFAULT_BASELINE = "analysis_baseline.json"


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m glom_tpu.analysis",
        description="glom-lint: JAX-aware static analysis over the repo",
    )
    ap.add_argument(
        "paths", nargs="*", default=["glom_tpu"],
        help="files/directories to lint (default: glom_tpu)",
    )
    ap.add_argument(
        "--baseline", default=None,
        help=f"reviewed-suppression file (default: {DEFAULT_BASELINE} "
        "when it exists in the working directory)",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="ignore any baseline file: report every finding",
    )
    ap.add_argument(
        "--write-baseline", metavar="FILE", default=None,
        help="accept the current findings into FILE and exit 0 (annotate "
        "every entry's 'reviewed' note before committing — enforcement "
        "refuses unreviewed entries)",
    )
    ap.add_argument(
        "--prune-baseline", action="store_true",
        help="drop baseline entries that no longer fire. DRY RUN by "
        "default (prints what would be removed); add --apply to rewrite "
        "the baseline and leave a stamped removal list next to it",
    )
    ap.add_argument(
        "--apply", action="store_true",
        help="with --prune-baseline: actually rewrite the baseline file",
    )
    ap.add_argument(
        "--cache", metavar="FILE", default=None,
        help="per-file content-fingerprint cache: files whose import "
        "closure is unchanged reuse their stored findings (cross-module "
        "edits invalidate importers; corruption falls back to a full "
        "pass, loudly)",
    )
    ap.add_argument(
        "--select", default=None,
        help="comma-separated checker names to run (default: all)",
    )
    ap.add_argument(
        "--list-checkers", action="store_true",
        help="print the checker catalog and exit",
    )
    args = ap.parse_args(argv)

    if args.list_checkers:
        for c in default_checkers():
            print(f"{c.name:22s} {c.description}")
        return 0

    select = (
        [s.strip() for s in args.select.split(",") if s.strip()]
        if args.select
        else None
    )
    cache = None
    if args.cache:
        from glom_tpu.analysis.cache import AnalysisCache

        cache = AnalysisCache(args.cache)
    warnings: List[str] = []
    try:
        findings = run(
            args.paths, select=select, warnings=warnings, cache=cache
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    for w in warnings:
        print(f"warning: {w}")
    if cache is not None:
        print(cache.stats())

    if args.prune_baseline:
        if select is not None:
            print(
                "error: --prune-baseline needs a full run — a partial "
                "--select cannot judge staleness",
                file=sys.stderr,
            )
            return 2
        return _prune_baseline(args, findings)

    if args.write_baseline:
        baseline_mod.write(findings, args.write_baseline)
        print(
            f"wrote {len(findings)} finding(s) to {args.write_baseline}; "
            "fill in every entry's 'reviewed' note before committing"
        )
        return 0

    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline:
        if Path(DEFAULT_BASELINE).exists():
            baseline_path = DEFAULT_BASELINE
    rc = 0
    if baseline_path and not args.no_baseline:
        try:
            data = baseline_mod.load(baseline_path)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        bad = baseline_mod.unreviewed(data)
        if bad:
            rc = 1
            for fp in bad:
                print(
                    f"baseline entry without a 'reviewed' note: {fp}",
                    file=sys.stderr,
                )
        new, stale = baseline_mod.apply(findings, data)
        for fp in stale:
            print(f"warning: stale baseline entry (no longer fires): {fp}")
        n_suppressed = len(findings) - len(new)
        findings = new
        if n_suppressed:
            print(
                f"{n_suppressed} finding(s) suppressed by {baseline_path}"
            )

    for f in findings:
        print(f.render())
    if findings:
        print(
            f"\n{len(findings)} new finding(s). Fix them, pragma them "
            "(# glom-lint: ok[checker] reason), or review them into the "
            "baseline (--write-baseline; see docs/ANALYSIS.md).",
            file=sys.stderr,
        )
        rc = 1
    else:
        print("glom-lint: clean")
    return rc


def _prune_baseline(args, findings) -> int:
    """--prune-baseline: drop suppressions that no longer fire. Dry run
    unless --apply; --apply rewrites the baseline and writes
    <baseline>.removed.json — the stamped record of what was dropped and
    why it was once accepted (the entries keep their reviewed notes)."""
    import datetime
    import json

    baseline_path = args.baseline or DEFAULT_BASELINE
    try:
        data = baseline_mod.load(baseline_path)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    pruned, removed = baseline_mod.prune(data, findings)
    if not removed:
        print(f"{baseline_path}: no stale entries — nothing to prune")
        return 0
    for fp in removed:
        print(f"stale: {fp}")
    if not args.apply:
        print(
            f"dry run: {len(removed)} stale entr"
            f"{'y' if len(removed) == 1 else 'ies'} in {baseline_path}; "
            "re-run with --apply to rewrite it"
        )
        return 0
    removal_list = {
        "pruned_at": datetime.datetime.now(datetime.timezone.utc).isoformat(
            timespec="seconds"
        ),
        "baseline": baseline_path,
        "removed": {
            fp: data.get("suppressions", {}).get(fp) for fp in removed
        },
    }
    Path(baseline_path).write_text(
        json.dumps(pruned, indent=2, sort_keys=True) + "\n"
    )
    removal_path = f"{baseline_path}.removed.json"
    Path(removal_path).write_text(
        json.dumps(removal_list, indent=2, sort_keys=True) + "\n"
    )
    print(
        f"pruned {len(removed)} entr{'y' if len(removed) == 1 else 'ies'} "
        f"from {baseline_path}; removal list stamped at {removal_path}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
