"""glom-lint: JAX-aware static analysis for the framework's own hazards.

`python -m glom_tpu.analysis [PATHS] [--baseline FILE]` runs five
checkers grounded in invariants the repo otherwise enforces by
convention (docs/ANALYSIS.md has the catalog and the suppression
workflow):

    collective-coverage  manual-path collectives: declared mesh axes +
                         telemetry.counters registration
    trace-purity         no host side effects reachable from jit /
                         shard_map / while_loop bodies
    donation-safety      no use of a buffer after a donated dispatch
    schema-emit          emit/stamp sites use registered kinds;
                         UNMEASURED is null, never 0.0
    lockset              threaded-class shared attributes stay behind
                         their lock (runtime companion: tests/test_races)

Pure stdlib — the pass runs where jax is wedged, which is when the
evidence trail matters most. CI runs it as the `lint` job;
run_hw_queue.sh runs it as pre-flight step 0 so a hardware window can
never start on code with a known collective/schema violation.
"""

from glom_tpu.analysis.core import (
    Checker,
    Context,
    Finding,
    SourceModule,
    default_checkers,
    run,
)

__all__ = [
    "Checker",
    "Context",
    "Finding",
    "SourceModule",
    "default_checkers",
    "run",
]
