"""trace-purity: no host side effects inside functions jax will trace.

A `time.time()` or `print` inside a jitted body runs ONCE at trace time
and never again — the classic silent-wrongness bug: the code looks like it
measures/logs per step, and the compiled program does neither. Worse are
`np.*` calls and Python `float()`/`if` on tracer values, which either
crash at trace time on exactly the config that first exercises the path,
or silently bake a trace-time constant into the program.

The checker finds TRACED ENTRIES — functions handed to jax.jit / pjit /
shard_map / lax.scan / lax.while_loop / lax.cond / lax.switch /
lax.fori_loop / jax.checkpoint / jax.grad / jax.value_and_grad /
pl.pallas_call / custom_vjp.defvjp (decorator or call form) — walks the
intra-module call graph reachable from them, and inside that region flags:

  * host clocks (`time.*`), `print` (use jax.debug.print), `open`/`input`,
    host RNG (`random.*`);
  * `.item()` / `.tolist()` / `.block_until_ready()` / `jax.device_get`;
  * `np.*` calls whose arguments reference function parameters (numpy on
    tracers) — metadata reads (`x.shape`, `x.dtype`, ...) are exempt:
    host math on static shape info at trace time is pure and idiomatic;
  * Python `if`/`while` on values produced by jnp./lax. calls (branching
    on a tracer; `is None` config dispatch is exempt).

Reachability is WHOLE-PROGRAM (the project graph): a call inside the
traced region whose name resolves to a function in another ANALYZED
module — a bare `from utils import helper` name or a dotted
`counters.record_collective` reference, re-export shims chased — is
followed into that module, bounded by XMOD_DEPTH module crossings, and
a violation is reported at the helper's own file:line. Third-party
namespaces (jnp/lax/np) never resolve in the project graph, so they
stay trusted exactly as before; lint a single file and the pass is the
old per-module one. Branching on raw parameters is still not flagged
(config ints thread through the same signatures as tracers). The
seeded-violation tests in tests/test_analysis.py pin what IS caught;
tests/fixtures/xmod_purity.py is the cross-module pair.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from glom_tpu.analysis.astutil import (
    SCOPE_NODES,
    FuncInfo,
    call_name,
    dotted,
    names_in,
)
from glom_tpu.analysis.core import Checker, Context, Finding, SourceModule

# wrapper leaf-name -> positions of the traced-callable arguments
TRACED_ARG_POSITIONS = {
    "jit": (0,),
    "pjit": (0,),
    "shard_map": (0,),
    "checkpoint": (0,),
    "remat": (0,),
    "grad": (0,),
    "value_and_grad": (0,),
    "eval_shape": (0,),
    "scan": (0,),
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "cond": (1, 2),
    "switch": (1, 2, 3, 4, 5),
    "pallas_call": (0,),
    "custom_vjp": (0,),
    "custom_jvp": (0,),
    "defvjp": (0, 1),
    "defjvp": (0, 1),
}

BANNED_PREFIXES = {
    "time.": "host clock runs once at trace time, not per step",
    "random.": "host RNG is frozen at trace time (use jax.random)",
    "np.random.": "host RNG is frozen at trace time (use jax.random)",
    "numpy.random.": "host RNG is frozen at trace time (use jax.random)",
}
BANNED_NAMES = {
    "print": "runs at trace time only (use jax.debug.print)",
    "open": "host I/O inside a traced function",
    "input": "host I/O inside a traced function",
    "breakpoint": "host debugger inside a traced function",
}
BANNED_METHODS = {
    "item": "forces a device sync / fails on tracers",
    "tolist": "forces a device sync / fails on tracers",
    "block_until_ready": "host sync inside a traced function",
}
METADATA_ATTRS = {"shape", "dtype", "ndim", "size", "aval", "sharding"}
ARRAY_PREFIXES = ("jnp.", "jax.numpy.", "lax.", "jax.lax.")
# jnp functions that return host metadata (dtypes, dtype lattice facts),
# not arrays — a boolean built from them is a legitimate Python branch
# (the kernel-routing `fold`/`kernel_ok` idiom in kernels/grouped_mlp.py),
# not a tracer branch.
METADATA_FUNCS = {
    "result_type", "promote_types", "issubdtype", "can_cast", "dtype",
    "iinfo", "finfo", "ndim", "shape", "size",
}

# Module-boundary crossings the reachability BFS will follow. Every real
# chain in the repo is 1-2 deep (shard_map body -> telemetry helper);
# the bound keeps a pathological call web from turning the pass O(repo²).
XMOD_DEPTH = 4


def _unguarded_names(node: ast.AST) -> Set[str]:
    """Name ids referenced in `node` OUTSIDE metadata attribute reads."""
    out: Set[str] = set()

    def scan(n: ast.AST) -> None:
        if isinstance(n, ast.Attribute) and n.attr in METADATA_ATTRS:
            return  # the whole subtree is a host metadata read
        if isinstance(n, ast.Name):
            out.add(n.id)
        for child in ast.iter_child_nodes(n):
            scan(child)

    scan(node)
    return out


def _definite_source_names(node: ast.AST) -> Set[str]:
    """Names whose VALUE can flow into an assigned target as a tracer:
    everything outside metadata reads and outside the ARGUMENTS of
    non-array calls. A host helper handed a tracer returns whatever it
    returns — in this repo, dtype/shape kernel-routing booleans
    (`kernel_ok = _supported(params, x, tile_m)`) — not the tracer
    itself, so definiteness must not launder through it. Tracer METHODS
    (`x.astype(...)`) keep flowing: the receiver sits in the call's func
    chain, not its arguments."""
    out: Set[str] = set()

    def scan(n: ast.AST) -> None:
        if isinstance(n, ast.Attribute) and n.attr in METADATA_ATTRS:
            return
        if isinstance(n, ast.Name):
            out.add(n.id)
            return
        if isinstance(n, ast.Call) and not TracePurity._is_array_call(n):
            scan(n.func)
            return
        for child in ast.iter_child_nodes(n):
            scan(child)

    scan(node)
    return out


class TracePurity(Checker):
    name = "trace-purity"
    description = "host side effects inside jit/shard_map/while_loop bodies"

    def check(self, module: SourceModule, ctx: Context) -> List[Finding]:
        results = self._project_results(ctx)
        return list(results.get(module.relpath, []))

    def _project_results(
        self, ctx: Context
    ) -> Dict[str, List[Finding]]:
        """Whole-program reachability, computed once per run and sliced
        per module (findings land in the file that CONTAINS the impure
        site, which may not be the file that traces it)."""
        key = "trace-purity:results"
        if key in ctx.scratch:
            return ctx.scratch[key]
        # Worklist over reached functions. Taint is CALL-SITE AWARE: an
        # entry function's params are all possible tracers (jax owns the
        # call), but a helper's params are tainted only by the arguments
        # its reached callers actually pass tainted values into. Without
        # this, whole-program reach re-breaks the static-config idiom —
        # `build_local_mask(cfg.num_patches_side, ...)` builds a numpy
        # mask from plain ints, and all-params-tainted would flag its
        # np.meshgrid the moment any traced entry reaches it.
        reached: Dict[int, Tuple[SourceModule, FuncInfo, int]] = {}
        taint_in: Dict[int, Set[str]] = {}
        queue: List[int] = []

        def enqueue(
            mod: SourceModule, info: FuncInfo, depth: int, params: Set[str]
        ) -> None:
            fid = id(info.node)
            if fid not in reached:
                reached[fid] = (mod, info, depth)
                taint_in[fid] = set(params)
                queue.append(fid)
                return
            cur_mod, cur_info, cur_depth = reached[fid]
            changed = False
            if depth < cur_depth:
                reached[fid] = (cur_mod, cur_info, depth)
                changed = True
            if not params <= taint_in[fid]:
                taint_in[fid] |= params
                changed = True
            if changed:
                queue.append(fid)

        def all_params(info: FuncInfo) -> Set[str]:
            return {p for p in info.params if p not in ("self", "cls")}

        for mod in ctx.modules:
            for info in self._module_entries(mod):
                enqueue(mod, info, 0, all_params(info))
        while queue:
            fid = queue.pop()
            mod, info, depth = reached[fid]
            maybe, definite = self._taint(info, taint_in[fid])
            tainted = maybe | definite
            for node in info.body_nodes():
                if not isinstance(node, ast.Call):
                    continue
                callee_mod, callee, cdepth = None, None, depth
                if isinstance(node.func, ast.Name):
                    callee = info.scope.resolve(node.func.id)
                    if callee is not None:
                        callee_mod = mod
                if callee is None and depth < XMOD_DEPTH and ctx.project is not None:
                    name = call_name(node)
                    if name and not name.startswith("self."):
                        hit = ctx.project.resolve_function(mod, name)
                        if hit is not None:
                            callee_mod, callee = hit[0].module, hit[1]
                            cdepth = depth + 1
                if callee is not None:
                    enqueue(
                        callee_mod,
                        callee,
                        cdepth,
                        self._call_taint(node, callee, tainted),
                    )
                # nested traced wrappers inside a traced region: the
                # wrapped function is a fresh ENTRY (jax calls it), so its
                # params are all possible tracers.
                for target in self._traced_callables(node):
                    rmod, resolved, rdepth = None, None, depth
                    if isinstance(target, ast.Name):
                        resolved = info.scope.resolve(target.id)
                        rmod = mod
                        if resolved is None and ctx.project is not None:
                            hit = ctx.project.resolve_function(mod, target.id)
                            if hit is not None:
                                rmod, resolved = hit[0].module, hit[1]
                                rdepth = depth + 1
                    elif isinstance(target, SCOPE_NODES):
                        resolved = mod.index.info_for(target)
                        rmod = mod
                    if resolved is not None:
                        enqueue(rmod, resolved, rdepth, all_params(resolved))
        results: Dict[str, List[Finding]] = {}
        for fid, (mod, info, _depth) in reached.items():
            for f in self._check_function(mod, info, ctx, taint_in[fid]):
                results.setdefault(mod.relpath, []).append(f)
        ctx.scratch[key] = results
        return results

    def _call_taint(
        self, call: ast.Call, callee: FuncInfo, caller_tainted: Set[str]
    ) -> Set[str]:
        """Callee parameter names that receive a possibly-tracer value at
        this call site: arguments referencing a caller-tainted name outside
        metadata reads, or containing an array-producing jnp/lax call."""

        def arg_tainted(expr: ast.AST) -> bool:
            if any(
                isinstance(sub, ast.Call) and self._is_array_call(sub)
                for sub in ast.walk(expr)
            ):
                return True
            return bool(_unguarded_names(expr) & caller_tainted)

        a = callee.node.args
        pos = [p.arg for p in a.posonlyargs + a.args]
        out: Set[str] = set()
        i = 0
        for arg in call.args:
            if isinstance(arg, ast.Starred):
                if arg_tainted(arg):
                    # positions are unknowable from here on
                    out.update(pos[i:])
                    if a.vararg:
                        out.add(a.vararg.arg)
                i = len(pos)
                continue
            if arg_tainted(arg):
                if i < len(pos):
                    out.add(pos[i])
                elif a.vararg:
                    out.add(a.vararg.arg)
            i += 1
        kw_capable = set(pos) | {p.arg for p in a.kwonlyargs}
        for kw in call.keywords:
            if not arg_tainted(kw.value):
                continue
            if kw.arg is None:  # **splat: keys unknowable
                out.update(kw_capable)
                if a.kwarg:
                    out.add(a.kwarg.arg)
            elif kw.arg in kw_capable:
                out.add(kw.arg)
            elif a.kwarg:
                out.add(a.kwarg.arg)
        out.discard("self")
        out.discard("cls")
        return out

    # -- entry discovery + reachability --------------------------------------

    def _traced_callables(self, call: ast.Call) -> List[ast.AST]:
        name = call_name(call)
        if name is None:
            return []
        leaf = name.split(".")[-1]
        positions = TRACED_ARG_POSITIONS.get(leaf)
        if positions is None:
            return []
        out = []
        for idx in positions:
            if len(call.args) > idx:
                out.append(call.args[idx])
        for kw in call.keywords:
            if kw.arg in ("f", "fun", "body", "body_fun", "cond_fun", "kernel"):
                out.append(kw.value)
        return out

    def _module_entries(self, module: SourceModule) -> List[FuncInfo]:
        """TRACED ENTRIES of one module (decorator and call form) — the
        BFS over what they reach lives in _project_results."""
        entries: List[FuncInfo] = []

        def resolve_in(node: ast.AST, scope) -> Optional[FuncInfo]:
            if isinstance(node, ast.Name):
                return scope.resolve(node.id)
            if isinstance(node, SCOPE_NODES):
                return module.index.info_for(node)
            return None

        # decorator form
        for fn_id, info in module.index.functions.items():
            node = info.node
            for dec in getattr(node, "decorator_list", []):
                target = dec.func if isinstance(dec, ast.Call) else dec
                name = dotted(target)
                leaf = name.split(".")[-1] if name else None
                if leaf in TRACED_ARG_POSITIONS and leaf not in (
                    "defvjp", "defjvp"
                ):
                    entries.append(info)
                elif isinstance(dec, ast.Call) and dotted(dec.func) in (
                    "partial", "functools.partial"
                ):
                    inner = dec.args[0] if dec.args else None
                    iname = dotted(inner) if inner is not None else None
                    if iname and iname.split(".")[-1] in TRACED_ARG_POSITIONS:
                        entries.append(info)

        # call form: jit(f) / shard_map(body, ...) / lax.scan(body, ...)
        scope_of: Dict[int, object] = {}
        for info in module.index.functions.values():
            for node in info.body_nodes():
                scope_of[id(node)] = info.scope
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            scope = scope_of.get(id(node), module.index.module_scope)
            for target in self._traced_callables(node):
                resolved = resolve_in(target, scope)
                if resolved is not None:
                    entries.append(resolved)
        return entries

    # -- per-function effect scan --------------------------------------------

    @staticmethod
    def _is_array_call(sub: ast.Call) -> bool:
        name = call_name(sub) or ""
        if not name.startswith(ARRAY_PREFIXES):
            return False
        return name.split(".")[-1] not in METADATA_FUNCS

    def _taint(
        self, info: FuncInfo, seed_params: Optional[Set[str]] = None
    ) -> Tuple[Set[str], Set[str]]:
        """(maybe_tracer, definite_tracer) name sets, one forward pass.
        maybe: tainted parameters (all of them by default; the propagated
        call-site set when the caller supplies one) and anything derived
        from them. definite: values produced by jnp./lax. calls (and
        arithmetic on them)."""
        if seed_params is None:
            maybe = {p for p in info.params if p not in ("self", "cls")}
        else:
            maybe = set(seed_params)
        definite: Set[str] = set()
        for node in info.body_nodes():
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                value = node.value
                if value is None:
                    continue
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                # Metadata reads (x.shape[0], x.dtype, ...) produce host
                # ints, not tracers — they must not propagate taint, or
                # every shape-derived loop bound reads as a tracer branch.
                rhs_names = _unguarded_names(value)
                rhs_calls_array = any(
                    isinstance(sub, ast.Call) and self._is_array_call(sub)
                    for sub in ast.walk(value)
                )
                tainted = bool(rhs_names & maybe) or rhs_calls_array
                definite_rhs = rhs_calls_array or bool(
                    _definite_source_names(value) & definite
                )
                for t in targets:
                    for name in names_in(t):
                        if isinstance(name.ctx, ast.Store):
                            if tainted:
                                maybe.add(name.id)
                            if definite_rhs:
                                definite.add(name.id)
        return maybe, definite

    def _is_metadata_guarded(self, arg: ast.AST, tainted: Set[str]) -> bool:
        """True when every tainted Name in `arg` is only read through a
        metadata attribute (x.shape / x.dtype / ...)."""

        def scan(node: ast.AST) -> bool:  # returns "has unguarded taint"
            if isinstance(node, ast.Attribute) and node.attr in METADATA_ATTRS:
                return False  # whole subtree is metadata access
            if isinstance(node, ast.Name):
                return node.id in tainted
            return any(scan(c) for c in ast.iter_child_nodes(node))

        return not scan(arg)

    def _check_function(
        self,
        module: SourceModule,
        info: FuncInfo,
        ctx: Optional[Context] = None,
        tainted_params: Optional[Set[str]] = None,
    ) -> List[Finding]:
        findings: List[Finding] = []
        maybe, definite = self._taint(info, tainted_params)

        def resolve(name: str) -> Optional[FuncInfo]:
            hit = info.scope.resolve(name)
            if hit is not None:
                return hit
            if ctx is not None and ctx.project is not None:
                ph = ctx.project.resolve_function(module, name)
                if ph is not None:
                    return ph[1]
            return None

        def add(node, message, key):
            findings.append(
                Finding(
                    checker=self.name,
                    path=module.relpath,
                    line=node.lineno,
                    col=node.col_offset,
                    message=f"{message} (reachable from a traced entry)",
                    symbol=info.qualname,
                    key=key,
                )
            )

        for node in info.body_nodes():
            if isinstance(node, ast.Call):
                name = call_name(node) or ""
                leaf = name.split(".")[-1]
                if name in BANNED_NAMES:
                    add(node, f"{name}(): {BANNED_NAMES[name]}", f"host-{name}")
                    continue
                matched = False
                for prefix, why in BANNED_PREFIXES.items():
                    if name.startswith(prefix):
                        add(node, f"{name}(): {why}", f"host-{prefix[:-1]}")
                        matched = True
                        break
                if matched:
                    continue
                if leaf in BANNED_METHODS and isinstance(node.func, ast.Attribute):
                    add(
                        node,
                        f".{leaf}(): {BANNED_METHODS[leaf]}",
                        f"host-{leaf}",
                    )
                    continue
                if leaf == "device_get" and name.split(".")[0] == "jax":
                    add(node, "jax.device_get: host sync in traced code",
                        "host-device_get")
                    continue
                if name.startswith(("np.", "numpy.")) and not name.startswith(
                    ("np.random.", "numpy.random.")
                ):
                    for arg in list(node.args) + [k.value for k in node.keywords]:
                        if not self._is_metadata_guarded(arg, maybe):
                            add(
                                node,
                                f"{name}() on a value derived from function "
                                "parameters — numpy cannot consume tracers",
                                "np-on-tracer",
                            )
                            break
            elif isinstance(node, (ast.If, ast.While)):
                test = node.test
                if self._is_none_check(test, resolve):
                    continue
                if _unguarded_names(test) & definite:
                    add(
                        node,
                        "Python branch on a jnp/lax-produced value — the "
                        "branch is decided ONCE at trace time (use lax.cond "
                        "/ jnp.where)",
                        "tracer-branch",
                    )
        return findings

    @staticmethod
    def _is_none_check(test: ast.AST, resolve=None) -> bool:
        """`x is None` config dispatch, possibly spelled through a helper
        the repo defines (`if not exists(levels):` — utils' one-liner
        `def exists(x): return x is not None`). The helper is RESOLVED
        (lexically, then through the project graph) and its body checked,
        so only genuine none-check wrappers get the exemption."""
        if isinstance(test, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
        ):
            return True
        if isinstance(test, ast.BoolOp):
            return all(
                TracePurity._is_none_check(v, resolve) for v in test.values
            )
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return TracePurity._is_none_check(test.operand, resolve)
        if (
            isinstance(test, ast.Call)
            and isinstance(test.func, ast.Name)
            and resolve is not None
        ):
            helper = resolve(test.func.id)
            if helper is not None and TracePurity._returns_none_check(helper):
                return True
        return False

    @staticmethod
    def _returns_none_check(helper: FuncInfo) -> bool:
        node = helper.node
        if isinstance(node, ast.Lambda):
            body = node.body
        else:
            stmts = [
                s
                for s in node.body
                if not (
                    isinstance(s, ast.Expr)
                    and isinstance(s.value, ast.Constant)
                )
            ]
            if len(stmts) != 1 or not isinstance(stmts[0], ast.Return):
                return False
            body = stmts[0].value
        return (
            isinstance(body, ast.Compare)
            and all(isinstance(op, (ast.Is, ast.IsNot)) for op in body.ops)
        )
