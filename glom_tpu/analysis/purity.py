"""trace-purity: no host side effects inside functions jax will trace.

A `time.time()` or `print` inside a jitted body runs ONCE at trace time
and never again — the classic silent-wrongness bug: the code looks like it
measures/logs per step, and the compiled program does neither. Worse are
`np.*` calls and Python `float()`/`if` on tracer values, which either
crash at trace time on exactly the config that first exercises the path,
or silently bake a trace-time constant into the program.

The checker finds TRACED ENTRIES — functions handed to jax.jit / pjit /
shard_map / lax.scan / lax.while_loop / lax.cond / lax.switch /
lax.fori_loop / jax.checkpoint / jax.grad / jax.value_and_grad /
pl.pallas_call / custom_vjp.defvjp (decorator or call form) — walks the
intra-module call graph reachable from them, and inside that region flags:

  * host clocks (`time.*`), `print` (use jax.debug.print), `open`/`input`,
    host RNG (`random.*`);
  * `.item()` / `.tolist()` / `.block_until_ready()` / `jax.device_get`;
  * `np.*` calls whose arguments reference function parameters (numpy on
    tracers) — metadata reads (`x.shape`, `x.dtype`, ...) are exempt:
    host math on static shape info at trace time is pure and idiomatic;
  * Python `if`/`while` on values produced by jnp./lax. calls (branching
    on a tracer; `is None` config dispatch is exempt).

Heuristic by design: cross-module calls are not followed (jnp/lax/the
repo's own kernel helpers are trusted), and branching on raw parameters is
not flagged (config ints thread through the same signatures as tracers).
The seeded-violation tests in tests/test_analysis.py pin what IS caught.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from glom_tpu.analysis.astutil import (
    SCOPE_NODES,
    FuncInfo,
    call_name,
    dotted,
    names_in,
)
from glom_tpu.analysis.core import Checker, Context, Finding, SourceModule

# wrapper leaf-name -> positions of the traced-callable arguments
TRACED_ARG_POSITIONS = {
    "jit": (0,),
    "pjit": (0,),
    "shard_map": (0,),
    "checkpoint": (0,),
    "remat": (0,),
    "grad": (0,),
    "value_and_grad": (0,),
    "eval_shape": (0,),
    "scan": (0,),
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "cond": (1, 2),
    "switch": (1, 2, 3, 4, 5),
    "pallas_call": (0,),
    "custom_vjp": (0,),
    "custom_jvp": (0,),
    "defvjp": (0, 1),
    "defjvp": (0, 1),
}

BANNED_PREFIXES = {
    "time.": "host clock runs once at trace time, not per step",
    "random.": "host RNG is frozen at trace time (use jax.random)",
    "np.random.": "host RNG is frozen at trace time (use jax.random)",
    "numpy.random.": "host RNG is frozen at trace time (use jax.random)",
}
BANNED_NAMES = {
    "print": "runs at trace time only (use jax.debug.print)",
    "open": "host I/O inside a traced function",
    "input": "host I/O inside a traced function",
    "breakpoint": "host debugger inside a traced function",
}
BANNED_METHODS = {
    "item": "forces a device sync / fails on tracers",
    "tolist": "forces a device sync / fails on tracers",
    "block_until_ready": "host sync inside a traced function",
}
METADATA_ATTRS = {"shape", "dtype", "ndim", "size", "aval", "sharding"}
ARRAY_PREFIXES = ("jnp.", "jax.numpy.", "lax.", "jax.lax.")


def _unguarded_names(node: ast.AST) -> Set[str]:
    """Name ids referenced in `node` OUTSIDE metadata attribute reads."""
    out: Set[str] = set()

    def scan(n: ast.AST) -> None:
        if isinstance(n, ast.Attribute) and n.attr in METADATA_ATTRS:
            return  # the whole subtree is a host metadata read
        if isinstance(n, ast.Name):
            out.add(n.id)
        for child in ast.iter_child_nodes(n):
            scan(child)

    scan(node)
    return out


class TracePurity(Checker):
    name = "trace-purity"
    description = "host side effects inside jit/shard_map/while_loop bodies"

    def check(self, module: SourceModule, ctx: Context) -> List[Finding]:
        reached = self._reachable_traced(module)
        findings: List[Finding] = []
        for info in reached:
            findings.extend(self._check_function(module, info))
        return findings

    # -- entry discovery + reachability --------------------------------------

    def _traced_callables(self, call: ast.Call) -> List[ast.AST]:
        name = call_name(call)
        if name is None:
            return []
        leaf = name.split(".")[-1]
        positions = TRACED_ARG_POSITIONS.get(leaf)
        if positions is None:
            return []
        out = []
        for idx in positions:
            if len(call.args) > idx:
                out.append(call.args[idx])
        for kw in call.keywords:
            if kw.arg in ("f", "fun", "body", "body_fun", "cond_fun", "kernel"):
                out.append(kw.value)
        return out

    def _reachable_traced(self, module: SourceModule) -> List[FuncInfo]:
        """FuncInfos reachable from any traced entry, via intra-module
        simple-name calls (lexical scope chain)."""
        entries: List[FuncInfo] = []

        def resolve_in(node: ast.AST, scope) -> Optional[FuncInfo]:
            if isinstance(node, ast.Name):
                return scope.resolve(node.id)
            if isinstance(node, SCOPE_NODES):
                return module.index.info_for(node)
            return None

        # decorator form
        for fn_id, info in module.index.functions.items():
            node = info.node
            for dec in getattr(node, "decorator_list", []):
                target = dec.func if isinstance(dec, ast.Call) else dec
                name = dotted(target)
                leaf = name.split(".")[-1] if name else None
                if leaf in TRACED_ARG_POSITIONS and leaf not in (
                    "defvjp", "defjvp"
                ):
                    entries.append(info)
                elif isinstance(dec, ast.Call) and dotted(dec.func) in (
                    "partial", "functools.partial"
                ):
                    inner = dec.args[0] if dec.args else None
                    iname = dotted(inner) if inner is not None else None
                    if iname and iname.split(".")[-1] in TRACED_ARG_POSITIONS:
                        entries.append(info)

        # call form: jit(f) / shard_map(body, ...) / lax.scan(body, ...)
        scope_of: Dict[int, object] = {}
        for info in module.index.functions.values():
            for node in info.body_nodes():
                scope_of[id(node)] = info.scope
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            scope = scope_of.get(id(node), module.index.module_scope)
            for target in self._traced_callables(node):
                resolved = resolve_in(target, scope)
                if resolved is not None:
                    entries.append(resolved)

        # BFS through intra-module calls
        reached: Dict[int, FuncInfo] = {}
        queue = list(entries)
        while queue:
            info = queue.pop()
            if id(info.node) in reached:
                continue
            reached[id(info.node)] = info
            for node in info.body_nodes():
                if isinstance(node, ast.Call):
                    callee = None
                    if isinstance(node.func, ast.Name):
                        callee = info.scope.resolve(node.func.id)
                    if callee is not None:
                        queue.append(callee)
                    # nested traced wrappers inside a traced region
                    for target in self._traced_callables(node):
                        resolved = resolve_in(target, info.scope)
                        if resolved is not None:
                            queue.append(resolved)
        return list(reached.values())

    # -- per-function effect scan --------------------------------------------

    def _taint(self, info: FuncInfo) -> Tuple[Set[str], Set[str]]:
        """(maybe_tracer, definite_tracer) name sets, one forward pass.
        maybe: parameters and anything derived from them. definite: values
        produced by jnp./lax. calls (and arithmetic on them)."""
        maybe = {p for p in info.params if p not in ("self", "cls")}
        definite: Set[str] = set()
        for node in info.body_nodes():
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                value = node.value
                if value is None:
                    continue
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                # Metadata reads (x.shape[0], x.dtype, ...) produce host
                # ints, not tracers — they must not propagate taint, or
                # every shape-derived loop bound reads as a tracer branch.
                rhs_names = _unguarded_names(value)
                rhs_calls_array = any(
                    isinstance(sub, ast.Call)
                    and (call_name(sub) or "").startswith(ARRAY_PREFIXES)
                    for sub in ast.walk(value)
                )
                tainted = bool(rhs_names & maybe) or rhs_calls_array
                definite_rhs = rhs_calls_array or bool(rhs_names & definite)
                for t in targets:
                    for name in names_in(t):
                        if isinstance(name.ctx, ast.Store):
                            if tainted:
                                maybe.add(name.id)
                            if definite_rhs:
                                definite.add(name.id)
        return maybe, definite

    def _is_metadata_guarded(self, arg: ast.AST, tainted: Set[str]) -> bool:
        """True when every tainted Name in `arg` is only read through a
        metadata attribute (x.shape / x.dtype / ...)."""

        def scan(node: ast.AST) -> bool:  # returns "has unguarded taint"
            if isinstance(node, ast.Attribute) and node.attr in METADATA_ATTRS:
                return False  # whole subtree is metadata access
            if isinstance(node, ast.Name):
                return node.id in tainted
            return any(scan(c) for c in ast.iter_child_nodes(node))

        return not scan(arg)

    def _check_function(
        self, module: SourceModule, info: FuncInfo
    ) -> List[Finding]:
        findings: List[Finding] = []
        maybe, definite = self._taint(info)

        def add(node, message, key):
            findings.append(
                Finding(
                    checker=self.name,
                    path=module.relpath,
                    line=node.lineno,
                    col=node.col_offset,
                    message=f"{message} (reachable from a traced entry)",
                    symbol=info.qualname,
                    key=key,
                )
            )

        for node in info.body_nodes():
            if isinstance(node, ast.Call):
                name = call_name(node) or ""
                leaf = name.split(".")[-1]
                if name in BANNED_NAMES:
                    add(node, f"{name}(): {BANNED_NAMES[name]}", f"host-{name}")
                    continue
                matched = False
                for prefix, why in BANNED_PREFIXES.items():
                    if name.startswith(prefix):
                        add(node, f"{name}(): {why}", f"host-{prefix[:-1]}")
                        matched = True
                        break
                if matched:
                    continue
                if leaf in BANNED_METHODS and isinstance(node.func, ast.Attribute):
                    add(
                        node,
                        f".{leaf}(): {BANNED_METHODS[leaf]}",
                        f"host-{leaf}",
                    )
                    continue
                if leaf == "device_get" and name.split(".")[0] == "jax":
                    add(node, "jax.device_get: host sync in traced code",
                        "host-device_get")
                    continue
                if name.startswith(("np.", "numpy.")) and not name.startswith(
                    ("np.random.", "numpy.random.")
                ):
                    for arg in list(node.args) + [k.value for k in node.keywords]:
                        if not self._is_metadata_guarded(arg, maybe):
                            add(
                                node,
                                f"{name}() on a value derived from function "
                                "parameters — numpy cannot consume tracers",
                                "np-on-tracer",
                            )
                            break
            elif isinstance(node, (ast.If, ast.While)):
                test = node.test
                if self._is_none_check(test):
                    continue
                if _unguarded_names(test) & definite:
                    add(
                        node,
                        "Python branch on a jnp/lax-produced value — the "
                        "branch is decided ONCE at trace time (use lax.cond "
                        "/ jnp.where)",
                        "tracer-branch",
                    )
        return findings

    @staticmethod
    def _is_none_check(test: ast.AST) -> bool:
        if isinstance(test, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
        ):
            return True
        if isinstance(test, ast.BoolOp):
            return all(TracePurity._is_none_check(v) for v in test.values)
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return TracePurity._is_none_check(test.operand)
        return False
