"""Per-file content-fingerprint cache for the whole-program pass.

The project graph made every checker's result a function of MORE than
its own file: purity follows calls into a module's imports, donation
taints handles across them, lock-order composes acquisition graphs from
several classes' modules, and axis-environment attestation flows the
OTHER way — from the importers that own the mesh. A naive mtime cache
would happily serve stale findings across any of those edges, so the
key here is structural:

    entry(file) valid  iff  sha256(file) unchanged
                        AND sha256 of every file in dep_closure(file)
                            unchanged (project.ProjectGraph.dep_closure:
                            the import closure of the whole importer
                            cone — both directions, transitively)
                        AND the context fingerprint unchanged (analyzer
                            version, active checker set, the analyzed
                            file SET itself — adding a file can create
                            new cross-module reach without editing any
                            existing one)

What is cached is the FINAL per-file result — pragma-filtered findings
plus the unused-pragma warnings — so a hit skips the checkers entirely.
Corruption is never silent: an unreadable/mismatched cache file prints a
loud warning to stderr and the run degrades to a full pass (then
rewrites the cache). `stats()` reports hits/misses for the CLI line CI's
cold+warm timing assertion greps.
"""

from __future__ import annotations

import hashlib
import json
import sys
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from glom_tpu.analysis.core import Checker, Context, Finding, SourceModule

CACHE_VERSION = 1

_FINDING_FIELDS = ("checker", "path", "line", "col", "message", "symbol", "key")


def _sha(text: str) -> str:
    return hashlib.sha256(text.encode("utf-8")).hexdigest()


class AnalysisCache:
    """One --cache FILE: load on construction, consult per module during
    run(), write back on finish(). Deliberately inert when the run is
    partial (--select) — a partial pass must never overwrite full-pass
    entries."""

    def __init__(self, path: str):
        self.path = Path(path)
        self.enabled = True
        self.hits = 0
        self.misses = 0
        self.reused_files: List[str] = []
        self._old_entries: Dict[str, dict] = {}
        self._new_entries: Dict[str, dict] = {}
        self._dep_hash: Dict[str, str] = {}
        self._context_key = ""
        self._load_error: Optional[str] = None
        if self.path.exists():
            try:
                data = json.loads(self.path.read_text())
                if (
                    not isinstance(data, dict)
                    or data.get("version") != CACHE_VERSION
                    or not isinstance(data.get("entries"), dict)
                ):
                    raise ValueError("not a glom-lint cache (or wrong version)")
                self._old_entries = data["entries"]
                self._old_context = data.get("context", "")
            except (OSError, ValueError, json.JSONDecodeError) as e:
                self._load_error = str(e)
                self._old_entries = {}
                self._old_context = ""
                print(
                    f"warning: analysis cache {path} is unreadable ({e}) — "
                    "falling back to a FULL pass and rewriting it",
                    file=sys.stderr,
                )
        else:
            self._old_context = ""

    # -- run() hooks ----------------------------------------------------------

    def begin(
        self,
        ctx: Context,
        active: List[Checker],
        *,
        select=None,
    ) -> None:
        if select is not None:
            self.enabled = False
            return
        shas = {
            m.relpath: _sha(m.text) for m in ctx.modules
        }
        self._context_key = _sha(
            json.dumps(
                {
                    "cache_version": CACHE_VERSION,
                    "checkers": sorted(c.name for c in active),
                    "files": sorted(shas),  # the SET, not the contents
                },
                sort_keys=True,
            )
        )
        project = ctx.project
        for m in ctx.modules:
            closure = sorted(project.dep_closure(m.relpath))
            self._dep_hash[m.relpath] = _sha(
                json.dumps([[c, shas.get(c, "")] for c in closure])
            )
        if self._old_context != self._context_key:
            self._old_entries = {}

    def lookup(
        self, mod: SourceModule
    ) -> Optional[Tuple[List[Finding], List[str]]]:
        if not self.enabled:
            return None
        entry = self._old_entries.get(mod.relpath)
        dep = self._dep_hash.get(mod.relpath)
        if (
            entry is None
            or dep is None
            or entry.get("dep_hash") != dep
        ):
            self.misses += 1
            return None
        try:
            findings = [
                Finding(**{k: f[k] for k in _FINDING_FIELDS})
                for f in entry["findings"]
            ]
            warnings = [str(w) for w in entry.get("warnings", [])]
        except (KeyError, TypeError) as e:
            # A structurally-broken entry is corruption, not a miss to
            # hide: say so, re-analyze the file.
            print(
                f"warning: analysis cache entry for {mod.relpath} is "
                f"malformed ({e}) — re-analyzing",
                file=sys.stderr,
            )
            self.misses += 1
            return None
        self.hits += 1
        self.reused_files.append(mod.relpath)
        self._new_entries[mod.relpath] = entry
        return findings, warnings

    def store(
        self, mod: SourceModule, findings: List[Finding], warnings: List[str]
    ) -> None:
        if not self.enabled:
            return
        dep = self._dep_hash.get(mod.relpath)
        if dep is None:
            return
        self._new_entries[mod.relpath] = {
            "dep_hash": dep,
            "findings": [
                {k: getattr(f, k) for k in _FINDING_FIELDS} for f in findings
            ],
            "warnings": list(warnings),
        }

    def finish(self) -> None:
        if not self.enabled:
            return
        data = {
            "version": CACHE_VERSION,
            "context": self._context_key,
            "entries": self._new_entries,
        }
        try:
            self.path.write_text(json.dumps(data, sort_keys=True) + "\n")
        except OSError as e:  # pragma: no cover - disk-full/readonly paths
            print(
                f"warning: could not write analysis cache {self.path}: {e}",
                file=sys.stderr,
            )

    def stats(self) -> str:
        total = self.hits + self.misses
        kind = (
            "disabled (--select runs never cache)"
            if not self.enabled
            else "warm"
            if self.misses == 0 and total
            else "cold"
            if self.hits == 0
            else "mixed"
        )
        return f"cache: {self.hits}/{total} files reused ({kind})"
