"""lockset: shared mutable state in the threaded modules stays behind its
lock — and multi-lock classes acquire their locks in ONE order.

Four host-side threads share mutable objects with their callers —
DynamicBatcher's worker, BackendWatchdog's heartbeat loop, the prefetch
worker, the flight ring fed from every sink — and until this pass the
only guard was discipline. The checker infers, per class that OWNS a lock
(`self._lock = threading.Lock()/RLock()/Condition()` in __init__), which
attributes the lock protects, and flags the accesses that slip out:

  * INCONSISTENT GUARDING: an attribute accessed at least once inside a
    `with self.<lock>:` block must be accessed under it everywhere
    (outside __init__) — the one unlocked read of a counter the lock
    otherwise guards is the classic lost-update / torn-read site;
  * UNLOCKED SHARING: an attribute WRITTEN from thread-entry context (a
    method reachable from `threading.Thread(target=...)`) and accessed
    from non-entry (caller-facing) methods must be guarded somewhere —
    two threads, a mutation, and no lock is a race by construction.

Precision choices: attributes assigned only in __init__ are config
(exempt); attributes holding intrinsically thread-safe objects
(threading.Event/Lock/RLock/Condition/local, queue.Queue/SimpleQueue) are
exempt; a private method whose every intra-class call site is lock-held
inherits the held context (the watchdog's _record_transition pattern);
nested functions (the heartbeat `loop`) belong to their defining method.
The runtime companion is tests/test_races.py — the seeded interleaving
harness that catches what a static lockset cannot (orderings, not just
guards).

LOCK-ORDER CYCLES (the second checker here, `lock-order`): a class that
owns TWO OR MORE locks must acquire them in one global order — thread 1
holding A while waiting on B, thread 2 holding B while waiting on A, is a
deadlock by construction, and unlike a data race it hangs rather than
corrupts, so no runtime harness catches it until production does. The
checker builds the PROJECT's lock-acquisition graph over (class, lock)
nodes — an edge A -> B for every site that acquires B while holding A:
lexically, transitively through self-method calls, and through TYPED
receiver calls into other objects (the batcher holding its lock while
the cache it calls takes its own, which calls into the pool's — the
codebase's real three-class chain) — and flags every edge on a directed
cycle at its own acquisition site. The multi-engine DynamicBatcher
(serve/batcher.py) carries the first real two-lock pattern
(_engine_lock -> _counter_lock, documented at the top of that file);
this checker is what keeps a future edit from quietly adding the
reverse nesting, within a class or across the object graph. Remaining
blind spots: locks handed out through non-`with` acquire()/release()
pairs, and receivers the type layer cannot resolve. Self-edges
(re-acquiring a held lock) are not reported — RLock makes them legal
and the ctor-type distinction is one assignment away from invisible.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Set, Tuple

from glom_tpu.analysis.astutil import FUNC_NODES, call_name, dotted
from glom_tpu.analysis.core import Checker, Context, Finding, SourceModule

LOCK_TYPES = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
EXEMPT_TYPES = {
    "Event", "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue", "local",
    "Thread",
}
MUTATORS = {
    "append", "appendleft", "extend", "insert", "add", "update", "pop",
    "popleft", "remove", "discard", "clear", "setdefault", "set",
}


@dataclass
class Access:
    attr: str
    line: int
    col: int
    method: str  # display name ("start.loop" for nested funcs)
    unit: str    # ownership unit for entry analysis (the defining method)
    is_write: bool
    held: bool


class Lockset(Checker):
    name = "lockset"
    description = "shared attributes in threaded classes accessed under lock"

    def check(self, module: SourceModule, ctx: Context) -> List[Finding]:
        findings: List[Finding] = []
        for node in module.tree.body:
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(module, node))
        return findings

    # -- per-class analysis --------------------------------------------------

    def _check_class(
        self, module: SourceModule, cls: ast.ClassDef
    ) -> List[Finding]:
        methods = [n for n in cls.body if isinstance(n, FUNC_NODES)]
        init = next((m for m in methods if m.name == "__init__"), None)
        lock_attrs, exempt_attrs = self._classify_attrs(init)
        if not lock_attrs:
            return []  # a class that owns no lock has no lockset contract

        accesses: List[Access] = []
        entry_targets: Set[str] = set()   # units named as Thread targets
        calls: Dict[str, Set[str]] = {}   # unit -> self-methods it calls
        # method -> (caller unit, lexically lock-held) per call site; the
        # caller matters so heldness can propagate transitively (a method
        # called only from held methods is itself held)
        call_held: Dict[str, List[Tuple[str, bool]]] = {}

        for m in methods:
            self._scan_unit(
                m, m.name, m.name, lock_attrs, accesses, entry_targets,
                calls, call_held,
            )

        init_written = {a.attr for a in accesses if a.method == "__init__"}
        later_written = {
            a.attr
            for a in accesses
            if a.is_write and a.method != "__init__"
        }
        config_attrs = init_written - later_written

        # fixpoint: a private method whose every call site is lock-held —
        # lexically, or because the calling method is itself held —
        # inherits the held context (watchdog's _record_transition chain)
        held_methods: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for m in methods:
                name = m.name
                if name in held_methods or not name.startswith("_"):
                    continue
                if name in ("__init__",):
                    continue
                sites = call_held.get(name, [])
                if sites and all(
                    held or caller in held_methods for caller, held in sites
                ):
                    held_methods.add(name)
                    changed = True

        # entry-reachable units (thread side)
        entry_units: Set[str] = set(entry_targets)
        frontier = list(entry_targets)
        while frontier:
            unit = frontier.pop()
            for callee in calls.get(unit, ()):
                if callee not in entry_units:
                    entry_units.add(callee)
                    frontier.append(callee)

        findings: List[Finding] = []
        method_names = {m.name for m in methods}
        by_attr: Dict[str, List[Access]] = {}
        for a in accesses:
            if a.method == "__init__":
                continue
            if a.attr in lock_attrs or a.attr in exempt_attrs:
                continue
            if a.attr in config_attrs or a.attr in method_names:
                continue
            eff_held = a.held or a.method in held_methods
            by_attr.setdefault(a.attr, []).append(
                Access(a.attr, a.line, a.col, a.method, a.unit,
                       a.is_write, eff_held)
            )

        for attr, accs in sorted(by_attr.items()):
            guarded = any(a.held for a in accs)
            if guarded:
                for a in accs:
                    if not a.held:
                        findings.append(
                            Finding(
                                checker=self.name,
                                path=module.relpath,
                                line=a.line,
                                col=a.col,
                                message=(
                                    f"{cls.name}.{attr} is lock-guarded "
                                    "elsewhere but accessed without the "
                                    f"lock in {a.method}() — torn read / "
                                    "lost update"
                                ),
                                symbol=f"{cls.name}.{a.method}",
                                key=f"unguarded-{attr}",
                            )
                        )
            else:
                entry_writes = [
                    a for a in accs if a.is_write and a.unit in entry_units
                ]
                other_side = [a for a in accs if a.unit not in entry_units]
                if entry_writes and other_side:
                    a = entry_writes[0]
                    findings.append(
                        Finding(
                            checker=self.name,
                            path=module.relpath,
                            line=a.line,
                            col=a.col,
                            message=(
                                f"{cls.name}.{attr} is mutated from the "
                                f"worker thread ({a.method}()) and accessed "
                                "from caller-facing methods "
                                f"({', '.join(sorted({o.method for o in other_side}))}) "
                                "with no lock anywhere — unsynchronized "
                                "sharing"
                            ),
                            symbol=f"{cls.name}.{a.method}",
                            key=f"unlocked-shared-{attr}",
                        )
                    )
        return findings

    # -- helpers -------------------------------------------------------------

    def _classify_attrs(self, init) -> Tuple[Set[str], Set[str]]:
        lock_attrs: Set[str] = set()
        exempt: Set[str] = set()
        if init is None:
            return lock_attrs, exempt
        for node in ast.walk(init):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            ctor = (call_name(node.value) or "").split(".")[-1]
            for t in node.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    if ctor in LOCK_TYPES:
                        lock_attrs.add(t.attr)
                    elif ctor in EXEMPT_TYPES:
                        exempt.add(t.attr)
        return lock_attrs, exempt

    def _scan_unit(
        self,
        fn,
        display: str,
        unit: str,
        lock_attrs: Set[str],
        accesses: List[Access],
        entry_targets: Set[str],
        calls: Dict[str, Set[str]],
        call_held: Dict[str, List[Tuple[str, bool]]],
    ) -> None:
        """Collect accesses/calls in one function body; recurse into
        nested defs as their own display names but the same ownership
        unit handling (a nested func named as a Thread target becomes its
        own entry unit)."""

        def is_lock_with(item: ast.withitem) -> bool:
            d = dotted(item.context_expr)
            return bool(
                d
                and d.startswith("self.")
                and d.split(".")[1] in lock_attrs
            )

        def walk(node: ast.AST, held: bool) -> None:
            if isinstance(node, ast.With):
                now_held = held or any(is_lock_with(i) for i in node.items)
                for child in node.body:
                    walk(child, now_held)
                return
            if isinstance(node, FUNC_NODES) and node is not fn:
                nested_name = f"{display}.{node.name}"
                self._scan_unit(
                    node, nested_name, nested_name, lock_attrs, accesses,
                    entry_targets, calls, call_held,
                )
                # the nested unit is callable from its definer
                calls.setdefault(unit, set()).add(nested_name)
                return
            if isinstance(node, ast.Call):
                name = call_name(node) or ""
                leaf = name.split(".")[-1]
                if leaf == "Thread":
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target = dotted(kw.value)
                            if target and target.startswith("self."):
                                entry_targets.add(target.split(".", 1)[1])
                            elif target:
                                # nested function target: qualify with the
                                # defining unit's name
                                entry_targets.add(f"{display}.{target}")
                if name.startswith("self.") and name.count(".") == 1:
                    callee = name.split(".")[1]
                    calls.setdefault(unit, set()).add(callee)
                    call_held.setdefault(callee, []).append((unit, held))
                # mutation through an attribute: self.x.append(...) — ONE
                # write access; skip the func subtree so the inner
                # `self.x` Attribute isn't double-counted as a read, and
                # walk only the argument expressions
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in MUTATORS
                    and isinstance(node.func.value, ast.Attribute)
                    and isinstance(node.func.value.value, ast.Name)
                    and node.func.value.value.id == "self"
                ):
                    accesses.append(
                        Access(
                            node.func.value.attr, node.lineno,
                            node.col_offset, display, unit, True, held,
                        )
                    )
                    for child in list(node.args) + [
                        kw.value for kw in node.keywords
                    ]:
                        walk(child, held)
                    return
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                is_write = isinstance(node.ctx, (ast.Store, ast.Del))
                accesses.append(
                    Access(
                        node.attr, node.lineno, node.col_offset, display,
                        unit, is_write, held,
                    )
                )
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        for stmt in fn.body:
            walk(stmt, False)


class _ClassScan:
    """One lock-owning class's acquisition facts."""

    def __init__(self, module: SourceModule, cls: ast.ClassDef, ckey: str):
        self.module = module
        self.cls_name = cls.name
        self.ckey = ckey
        # unit -> [(held frozenset of own lock attrs, lock attr, line)]
        self.direct: Dict[str, List[Tuple[frozenset, str, int]]] = {}
        # unit -> [(callee unit, held, line)] for self-method calls
        self.intra_calls: Dict[str, List[Tuple[str, frozenset, int]]] = {}
        # unit -> [(callee class key, callee method, held, line)] for
        # typed-receiver calls into OTHER objects' methods
        self.ext_calls: Dict[str, List[Tuple[str, str, frozenset, int]]] = {}


class LockOrder(Checker):
    """Directed-cycle detection over the PROJECT's lock-acquisition graph.

    Nodes are (class, lock attribute) pairs across every analyzed module;
    edges are "acquires B while holding A" — lexically, transitively
    through self-method calls, and through TYPED receiver calls into
    other objects (`with self._lock: self.cache.lookup(...)` where
    lookup takes the cache's own lock adds the cross-OBJECT edge, and the
    cache's pool calls extend the chain). Single-lock classes
    participate: one lock cannot conflict with itself, but it can sit in
    the middle of a batcher -> cache -> pool chain. A cycle anywhere in
    the composed graph deadlocks the moment two threads interleave, and
    every edge on one is flagged at its own acquisition site's
    file:line. Remaining blind spots: locks handed out through
    non-`with` acquire()/release() pairs, and receivers the type layer
    cannot resolve (untyped dynamic dispatch). Self-edges (re-acquiring
    a held lock) are not reported — RLock makes them legal and the
    ctor-type distinction is one assignment away from invisible.
    """

    name = "lock-order"
    description = (
        "locks acquire in one global order across objects "
        "(a cycle in the acquisition graph is a deadlock by construction)"
    )

    def check(self, module: SourceModule, ctx: Context) -> List[Finding]:
        results = self._project_results(ctx)
        return list(results.get(module.relpath, []))

    def _project_results(self, ctx: Context) -> Dict[str, List[Finding]]:
        key = "lock-order:results"
        if key in ctx.scratch:
            return ctx.scratch[key]
        project = ctx.project
        if project is None:
            from glom_tpu.analysis.project import ProjectGraph

            project = ProjectGraph(ctx.modules)
        scans: Dict[str, _ClassScan] = {}
        lock_attrs_of: Dict[str, Set[str]] = {}
        for mod in ctx.modules:
            minfo = project.info_of(mod)
            for node in mod.tree.body:
                if not isinstance(node, ast.ClassDef):
                    continue
                methods = [n for n in node.body if isinstance(n, FUNC_NODES)]
                init = next(
                    (m for m in methods if m.name == "__init__"), None
                )
                locks, _ = Lockset()._classify_attrs(init)
                if not locks:
                    continue
                ckey = project.class_key(minfo, node.name)
                lock_attrs_of[ckey] = locks
                scans[ckey] = self._scan_class(
                    mod, node, ckey, locks, project
                )
        # Global fixpoint: GA[(ckey, unit)] = every (class key, lock)
        # node the unit acquires — directly, through self-calls, or
        # through typed calls into other classes' methods.
        ga: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
        for ckey, scan in scans.items():
            units = (
                set(scan.direct) | set(scan.intra_calls) | set(scan.ext_calls)
            )
            for unit in units:
                ga[(ckey, unit)] = {
                    (ckey, lock)
                    for _, lock, _ in scan.direct.get(unit, ())
                }
        changed = True
        while changed:
            changed = False
            for ckey, scan in scans.items():
                for unit, sites in scan.intra_calls.items():
                    for callee, _, _ in sites:
                        s = ga.get((ckey, callee))
                        if s and not s <= ga[(ckey, unit)]:
                            ga[(ckey, unit)] |= s
                            changed = True
                for unit, sites in scan.ext_calls.items():
                    for dkey, meth, _, _ in sites:
                        s = ga.get((dkey, meth))
                        if s and not s <= ga[(ckey, unit)]:
                            ga[(ckey, unit)] |= s
                            changed = True
        # The acquisition graph over (class, lock) nodes, one witness
        # site per edge (first seen, deterministic scan order).
        Node = Tuple[str, str]
        edges: Dict[Tuple[Node, Node], Tuple[str, str, str, int]] = {}

        def add_edge(na: Node, nb: Node, scan: _ClassScan, unit: str, line: int) -> None:
            if na != nb:
                edges.setdefault(
                    (na, nb),
                    (scan.module.relpath, scan.cls_name, unit, line),
                )

        for ckey, scan in scans.items():
            for unit, sites in scan.direct.items():
                for held, lock, line in sites:
                    for a in sorted(held):
                        add_edge((ckey, a), (ckey, lock), scan, unit, line)
            for unit, sites in scan.intra_calls.items():
                for callee, held, line in sites:
                    if not held:
                        continue
                    for nb in sorted(ga.get((ckey, callee), ())):
                        for a in sorted(held):
                            add_edge((ckey, a), nb, scan, unit, line)
            for unit, sites in scan.ext_calls.items():
                for dkey, meth, held, line in sites:
                    if not held:
                        continue
                    for nb in sorted(ga.get((dkey, meth), ())):
                        for a in sorted(held):
                            add_edge((ckey, a), nb, scan, unit, line)

        adj: Dict[Node, Set[Node]] = {}
        for na, nb in edges:
            adj.setdefault(na, set()).add(nb)

        def reaches(src: Node, dst: Node) -> bool:
            seen, frontier = {src}, [src]
            while frontier:
                n = frontier.pop()
                for nxt in adj.get(n, ()):
                    if nxt == dst:
                        return True
                    if nxt not in seen:
                        seen.add(nxt)
                        frontier.append(nxt)
            return False

        cls_name_of = {ckey: s.cls_name for ckey, s in scans.items()}

        def render(node: Node, home: str) -> str:
            ckey, attr = node
            if ckey == home:
                return attr  # intra-class names (and fingerprints) stay bare
            return f"{cls_name_of.get(ckey, ckey)}.{attr}"

        results: Dict[str, List[Finding]] = {}
        for (na, nb), (relpath, cls_name, unit, line) in sorted(
            edges.items(), key=lambda kv: (kv[1][0], kv[1][3], kv[0])
        ):
            if not reaches(nb, na):
                continue
            home = na[0]
            ra, rb = render(na, home), render(nb, home)
            back = edges.get((nb, na))
            where = (
                f"the reverse order is taken in {back[1]}.{back[2]}() at "
                f"{back[0]}:{back[3]}" if back else
                "the reverse order is reachable through another edge"
            )
            results.setdefault(relpath, []).append(
                Finding(
                    checker=self.name,
                    path=relpath,
                    line=line,
                    col=0,
                    message=(
                        f"{cls_name} acquires {rb} while holding {ra} "
                        f"here, but {where} — a lock-order cycle "
                        "deadlocks the moment two threads interleave"
                    ),
                    symbol=f"{cls_name}.{unit}",
                    key=f"lock-order-{ra}-{rb}",
                )
            )
        # The attested graph, readable node names — what the tests (and
        # anyone debugging a chain) inspect.
        ctx.scratch["lock-order:edges"] = {
            (
                f"{cls_name_of.get(na[0], na[0])}.{na[1]}",
                f"{cls_name_of.get(nb[0], nb[0])}.{nb[1]}",
            ): (w[0], w[3])
            for (na, nb), w in edges.items()
        }
        ctx.scratch[key] = results
        return results

    def _scan_class(
        self,
        module: SourceModule,
        cls: ast.ClassDef,
        ckey: str,
        lock_attrs: Set[str],
        project,
    ) -> _ClassScan:
        scan = _ClassScan(module, cls, ckey)

        def scan_fn(fn, unit: str) -> None:
            scan.direct.setdefault(unit, [])
            scan.intra_calls.setdefault(unit, [])
            scan.ext_calls.setdefault(unit, [])
            finfo = module.index.info_for(fn)
            rtype = (
                project.receiver_resolver(module, finfo)
                if finfo is not None
                else None
            )

            def locks_of(with_node: ast.With) -> List[str]:
                out = []
                for item in with_node.items:
                    d = dotted(item.context_expr)
                    if d and d.startswith("self."):
                        attr = d.split(".")[1]
                        if attr in lock_attrs:
                            out.append(attr)
                return out

            def walk(node: ast.AST, held: frozenset) -> None:
                if isinstance(node, ast.With):
                    now = set(held)
                    for lock in locks_of(node):
                        if lock not in now:
                            scan.direct[unit].append(
                                (frozenset(now), lock, node.lineno)
                            )
                            now.add(lock)
                    for child in node.body:
                        walk(child, frozenset(now))
                    return
                if isinstance(node, FUNC_NODES) and node is not fn:
                    # Nested defs run later under an unknown held-set;
                    # scan them as their own unit reachable from here.
                    nested = f"{unit}.{node.name}"
                    scan_fn(node, nested)
                    scan.intra_calls[unit].append((nested, held, node.lineno))
                    return
                if isinstance(node, ast.Call):
                    name = call_name(node) or ""
                    if name.startswith("self.") and name.count(".") == 1:
                        scan.intra_calls[unit].append(
                            (name.split(".")[1], held, node.lineno)
                        )
                    elif rtype is not None and isinstance(
                        node.func, ast.Attribute
                    ):
                        # A method call on SOMETHING — resolve the
                        # receiver's type; an unresolvable receiver
                        # contributes nothing (precision stance).
                        t = rtype(node.func.value)
                        if t is not None and t.cls is not None:
                            scan.ext_calls[unit].append(
                                (t.cls, node.func.attr, held, node.lineno)
                            )
                for child in ast.iter_child_nodes(node):
                    walk(child, held)

            for stmt in fn.body:
                walk(stmt, frozenset())

        for m in cls.body:
            if isinstance(m, FUNC_NODES):
                scan_fn(m, m.name)
        return scan
