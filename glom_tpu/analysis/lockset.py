"""lockset: shared mutable state in the threaded modules stays behind its
lock — and multi-lock classes acquire their locks in ONE order.

Four host-side threads share mutable objects with their callers —
DynamicBatcher's worker, BackendWatchdog's heartbeat loop, the prefetch
worker, the flight ring fed from every sink — and until this pass the
only guard was discipline. The checker infers, per class that OWNS a lock
(`self._lock = threading.Lock()/RLock()/Condition()` in __init__), which
attributes the lock protects, and flags the accesses that slip out:

  * INCONSISTENT GUARDING: an attribute accessed at least once inside a
    `with self.<lock>:` block must be accessed under it everywhere
    (outside __init__) — the one unlocked read of a counter the lock
    otherwise guards is the classic lost-update / torn-read site;
  * UNLOCKED SHARING: an attribute WRITTEN from thread-entry context (a
    method reachable from `threading.Thread(target=...)`) and accessed
    from non-entry (caller-facing) methods must be guarded somewhere —
    two threads, a mutation, and no lock is a race by construction.

Precision choices: attributes assigned only in __init__ are config
(exempt); attributes holding intrinsically thread-safe objects
(threading.Event/Lock/RLock/Condition/local, queue.Queue/SimpleQueue) are
exempt; a private method whose every intra-class call site is lock-held
inherits the held context (the watchdog's _record_transition pattern);
nested functions (the heartbeat `loop`) belong to their defining method.
The runtime companion is tests/test_races.py — the seeded interleaving
harness that catches what a static lockset cannot (orderings, not just
guards).

LOCK-ORDER CYCLES (the second checker here, `lock-order`): a class that
owns TWO OR MORE locks must acquire them in one global order — thread 1
holding A while waiting on B, thread 2 holding B while waiting on A, is a
deadlock by construction, and unlike a data race it hangs rather than
corrupts, so no runtime harness catches it until production does. The
checker builds the class's lock-acquisition graph — an edge A -> B for
every site that acquires B while (lexically, or transitively through
self-method calls) holding A — and flags every edge on a directed cycle.
The multi-engine DynamicBatcher (serve/batcher.py) carries the codebase's
first real two-lock pattern (_engine_lock -> _counter_lock, documented at
the top of that file); this checker is what keeps a future edit from
quietly adding the reverse nesting. Blind spots, by design: orders across
DIFFERENT objects' locks (attr names are per-class), and locks handed out
through non-`with` acquire()/release() pairs. Self-edges (re-acquiring a
held lock) are not reported — RLock makes them legal and the ctor-type
distinction is one assignment away from invisible.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Set, Tuple

from glom_tpu.analysis.astutil import FUNC_NODES, call_name, dotted
from glom_tpu.analysis.core import Checker, Context, Finding, SourceModule

LOCK_TYPES = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
EXEMPT_TYPES = {
    "Event", "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue", "local",
    "Thread",
}
MUTATORS = {
    "append", "appendleft", "extend", "insert", "add", "update", "pop",
    "popleft", "remove", "discard", "clear", "setdefault", "set",
}


@dataclass
class Access:
    attr: str
    line: int
    col: int
    method: str  # display name ("start.loop" for nested funcs)
    unit: str    # ownership unit for entry analysis (the defining method)
    is_write: bool
    held: bool


class Lockset(Checker):
    name = "lockset"
    description = "shared attributes in threaded classes accessed under lock"

    def check(self, module: SourceModule, ctx: Context) -> List[Finding]:
        findings: List[Finding] = []
        for node in module.tree.body:
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(module, node))
        return findings

    # -- per-class analysis --------------------------------------------------

    def _check_class(
        self, module: SourceModule, cls: ast.ClassDef
    ) -> List[Finding]:
        methods = [n for n in cls.body if isinstance(n, FUNC_NODES)]
        init = next((m for m in methods if m.name == "__init__"), None)
        lock_attrs, exempt_attrs = self._classify_attrs(init)
        if not lock_attrs:
            return []  # a class that owns no lock has no lockset contract

        accesses: List[Access] = []
        entry_targets: Set[str] = set()   # units named as Thread targets
        calls: Dict[str, Set[str]] = {}   # unit -> self-methods it calls
        # method -> (caller unit, lexically lock-held) per call site; the
        # caller matters so heldness can propagate transitively (a method
        # called only from held methods is itself held)
        call_held: Dict[str, List[Tuple[str, bool]]] = {}

        for m in methods:
            self._scan_unit(
                m, m.name, m.name, lock_attrs, accesses, entry_targets,
                calls, call_held,
            )

        init_written = {a.attr for a in accesses if a.method == "__init__"}
        later_written = {
            a.attr
            for a in accesses
            if a.is_write and a.method != "__init__"
        }
        config_attrs = init_written - later_written

        # fixpoint: a private method whose every call site is lock-held —
        # lexically, or because the calling method is itself held —
        # inherits the held context (watchdog's _record_transition chain)
        held_methods: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for m in methods:
                name = m.name
                if name in held_methods or not name.startswith("_"):
                    continue
                if name in ("__init__",):
                    continue
                sites = call_held.get(name, [])
                if sites and all(
                    held or caller in held_methods for caller, held in sites
                ):
                    held_methods.add(name)
                    changed = True

        # entry-reachable units (thread side)
        entry_units: Set[str] = set(entry_targets)
        frontier = list(entry_targets)
        while frontier:
            unit = frontier.pop()
            for callee in calls.get(unit, ()):
                if callee not in entry_units:
                    entry_units.add(callee)
                    frontier.append(callee)

        findings: List[Finding] = []
        method_names = {m.name for m in methods}
        by_attr: Dict[str, List[Access]] = {}
        for a in accesses:
            if a.method == "__init__":
                continue
            if a.attr in lock_attrs or a.attr in exempt_attrs:
                continue
            if a.attr in config_attrs or a.attr in method_names:
                continue
            eff_held = a.held or a.method in held_methods
            by_attr.setdefault(a.attr, []).append(
                Access(a.attr, a.line, a.col, a.method, a.unit,
                       a.is_write, eff_held)
            )

        for attr, accs in sorted(by_attr.items()):
            guarded = any(a.held for a in accs)
            if guarded:
                for a in accs:
                    if not a.held:
                        findings.append(
                            Finding(
                                checker=self.name,
                                path=module.relpath,
                                line=a.line,
                                col=a.col,
                                message=(
                                    f"{cls.name}.{attr} is lock-guarded "
                                    "elsewhere but accessed without the "
                                    f"lock in {a.method}() — torn read / "
                                    "lost update"
                                ),
                                symbol=f"{cls.name}.{a.method}",
                                key=f"unguarded-{attr}",
                            )
                        )
            else:
                entry_writes = [
                    a for a in accs if a.is_write and a.unit in entry_units
                ]
                other_side = [a for a in accs if a.unit not in entry_units]
                if entry_writes and other_side:
                    a = entry_writes[0]
                    findings.append(
                        Finding(
                            checker=self.name,
                            path=module.relpath,
                            line=a.line,
                            col=a.col,
                            message=(
                                f"{cls.name}.{attr} is mutated from the "
                                f"worker thread ({a.method}()) and accessed "
                                "from caller-facing methods "
                                f"({', '.join(sorted({o.method for o in other_side}))}) "
                                "with no lock anywhere — unsynchronized "
                                "sharing"
                            ),
                            symbol=f"{cls.name}.{a.method}",
                            key=f"unlocked-shared-{attr}",
                        )
                    )
        return findings

    # -- helpers -------------------------------------------------------------

    def _classify_attrs(self, init) -> Tuple[Set[str], Set[str]]:
        lock_attrs: Set[str] = set()
        exempt: Set[str] = set()
        if init is None:
            return lock_attrs, exempt
        for node in ast.walk(init):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            ctor = (call_name(node.value) or "").split(".")[-1]
            for t in node.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    if ctor in LOCK_TYPES:
                        lock_attrs.add(t.attr)
                    elif ctor in EXEMPT_TYPES:
                        exempt.add(t.attr)
        return lock_attrs, exempt

    def _scan_unit(
        self,
        fn,
        display: str,
        unit: str,
        lock_attrs: Set[str],
        accesses: List[Access],
        entry_targets: Set[str],
        calls: Dict[str, Set[str]],
        call_held: Dict[str, List[Tuple[str, bool]]],
    ) -> None:
        """Collect accesses/calls in one function body; recurse into
        nested defs as their own display names but the same ownership
        unit handling (a nested func named as a Thread target becomes its
        own entry unit)."""

        def is_lock_with(item: ast.withitem) -> bool:
            d = dotted(item.context_expr)
            return bool(
                d
                and d.startswith("self.")
                and d.split(".")[1] in lock_attrs
            )

        def walk(node: ast.AST, held: bool) -> None:
            if isinstance(node, ast.With):
                now_held = held or any(is_lock_with(i) for i in node.items)
                for child in node.body:
                    walk(child, now_held)
                return
            if isinstance(node, FUNC_NODES) and node is not fn:
                nested_name = f"{display}.{node.name}"
                self._scan_unit(
                    node, nested_name, nested_name, lock_attrs, accesses,
                    entry_targets, calls, call_held,
                )
                # the nested unit is callable from its definer
                calls.setdefault(unit, set()).add(nested_name)
                return
            if isinstance(node, ast.Call):
                name = call_name(node) or ""
                leaf = name.split(".")[-1]
                if leaf == "Thread":
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target = dotted(kw.value)
                            if target and target.startswith("self."):
                                entry_targets.add(target.split(".", 1)[1])
                            elif target:
                                # nested function target: qualify with the
                                # defining unit's name
                                entry_targets.add(f"{display}.{target}")
                if name.startswith("self.") and name.count(".") == 1:
                    callee = name.split(".")[1]
                    calls.setdefault(unit, set()).add(callee)
                    call_held.setdefault(callee, []).append((unit, held))
                # mutation through an attribute: self.x.append(...) — ONE
                # write access; skip the func subtree so the inner
                # `self.x` Attribute isn't double-counted as a read, and
                # walk only the argument expressions
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in MUTATORS
                    and isinstance(node.func.value, ast.Attribute)
                    and isinstance(node.func.value.value, ast.Name)
                    and node.func.value.value.id == "self"
                ):
                    accesses.append(
                        Access(
                            node.func.value.attr, node.lineno,
                            node.col_offset, display, unit, True, held,
                        )
                    )
                    for child in list(node.args) + [
                        kw.value for kw in node.keywords
                    ]:
                        walk(child, held)
                    return
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                is_write = isinstance(node.ctx, (ast.Store, ast.Del))
                accesses.append(
                    Access(
                        node.attr, node.lineno, node.col_offset, display,
                        unit, is_write, held,
                    )
                )
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        for stmt in fn.body:
            walk(stmt, False)


class LockOrder(Checker):
    """Directed-cycle detection over a class's lock-acquisition order."""

    name = "lock-order"
    description = (
        "multi-lock classes acquire their locks in one global order "
        "(a cycle in the acquisition graph is a deadlock by construction)"
    )

    def check(self, module: SourceModule, ctx: Context) -> List[Finding]:
        findings: List[Finding] = []
        for node in module.tree.body:
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(module, node))
        return findings

    def _check_class(
        self, module: SourceModule, cls: ast.ClassDef
    ) -> List[Finding]:
        methods = [n for n in cls.body if isinstance(n, FUNC_NODES)]
        init = next((m for m in methods if m.name == "__init__"), None)
        lock_attrs, _ = Lockset()._classify_attrs(init)
        if len(lock_attrs) < 2:
            return []  # one lock cannot order-conflict with itself

        # Per method: direct acquisitions (held-set at the acquire, lock,
        # line), self-calls (callee, held-set at the call, line), and the
        # set of locks acquired anywhere in the body.
        direct: Dict[str, List[Tuple[frozenset, str, int]]] = {}
        calls: Dict[str, List[Tuple[str, frozenset, int]]] = {}
        acquires: Dict[str, Set[str]] = {}

        def scan(fn, unit: str) -> None:
            direct.setdefault(unit, [])
            calls.setdefault(unit, [])
            acquires.setdefault(unit, set())

            def locks_of(with_node: ast.With) -> List[str]:
                out = []
                for item in with_node.items:
                    d = dotted(item.context_expr)
                    if d and d.startswith("self."):
                        attr = d.split(".")[1]
                        if attr in lock_attrs:
                            out.append(attr)
                return out

            def walk(node: ast.AST, held: frozenset) -> None:
                if isinstance(node, ast.With):
                    now = set(held)
                    for lock in locks_of(node):
                        if lock not in now:
                            direct[unit].append(
                                (frozenset(now), lock, node.lineno)
                            )
                            acquires[unit].add(lock)
                            now.add(lock)
                    for child in node.body:
                        walk(child, frozenset(now))
                    return
                if isinstance(node, FUNC_NODES) and node is not fn:
                    # Nested defs run later under an unknown held-set;
                    # scan them as their own unit reachable from here.
                    nested = f"{unit}.{node.name}"
                    scan(node, nested)
                    calls[unit].append((nested, held, node.lineno))
                    return
                if isinstance(node, ast.Call):
                    name = call_name(node) or ""
                    if name.startswith("self.") and name.count(".") == 1:
                        calls[unit].append(
                            (name.split(".")[1], held, node.lineno)
                        )
                for child in ast.iter_child_nodes(node):
                    walk(child, held)

            for stmt in fn.body:
                walk(stmt, frozenset())

        for m in methods:
            scan(m, m.name)

        # Fixpoint: locks a method acquires TRANSITIVELY through
        # self-calls (so `with A: self.helper()` where helper takes B
        # contributes the A -> B edge).
        changed = True
        while changed:
            changed = False
            for unit, sites in calls.items():
                for callee, _, _ in sites:
                    extra = acquires.get(callee, set()) - acquires[unit]
                    if extra:
                        acquires[unit] |= extra
                        changed = True

        # The acquisition graph: held -> acquired, with one witness line
        # per edge (first seen, deterministic scan order).
        edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

        def add_edge(a: str, b: str, unit: str, line: int) -> None:
            if a != b:
                edges.setdefault((a, b), (unit, line))

        for unit, sites in direct.items():
            for held, lock, line in sites:
                for a in sorted(held):
                    add_edge(a, lock, unit, line)
        for unit, sites in calls.items():
            for callee, held, line in sites:
                if not held:
                    continue
                for b in sorted(acquires.get(callee, ())):
                    for a in sorted(held):
                        add_edge(a, b, unit, line)

        # Every edge that lies on a directed cycle is a finding: compute
        # reachability and keep (a, b) where b reaches a.
        adj: Dict[str, Set[str]] = {}
        for a, b in edges:
            adj.setdefault(a, set()).add(b)

        def reaches(src: str, dst: str) -> bool:
            seen, frontier = {src}, [src]
            while frontier:
                n = frontier.pop()
                for nxt in adj.get(n, ()):
                    if nxt == dst:
                        return True
                    if nxt not in seen:
                        seen.add(nxt)
                        frontier.append(nxt)
            return False

        findings: List[Finding] = []
        for (a, b), (unit, line) in sorted(
            edges.items(), key=lambda kv: (kv[1][1], kv[0])
        ):
            if reaches(b, a):
                back = edges.get((b, a))
                where = (
                    f"the reverse order is taken in {back[0]}() line "
                    f"{back[1]}" if back else
                    "the reverse order is reachable through another edge"
                )
                findings.append(
                    Finding(
                        checker=self.name,
                        path=module.relpath,
                        line=line,
                        col=0,
                        message=(
                            f"{cls.name} acquires {b} while holding {a} "
                            f"here, but {where} — a lock-order cycle "
                            "deadlocks the moment two threads interleave"
                        ),
                        symbol=f"{cls.name}.{unit}",
                        key=f"lock-order-{a}-{b}",
                    )
                )
        return findings
