"""axis-environment: a collective's axis name must exist in the enclosing
shard_map's mesh.

The collective-coverage checker (analysis/collectives.py) validates axis
names against the GLOBAL vocabulary — every `*_AXIS` constant in the
scanned tree. That misses a subtler bug: a psum over 'model' inside a
shard_map whose mesh only declares ('data', 'seq') uses a perfectly
vocabulary-legal axis that DOES NOT EXIST in its own environment, and
fails only at runtime, only when that exact mesh shape traces. The paged
serve gathers (parallel/serve_mesh.py) are exactly where this bites: the
serve mesh is ('data', 'seq') while the training mesh also carries
'model', so a copy-pasted training collective is one axis name away from
a trace-time explosion the lint should catch on CPU.

Environment resolution (static, conservative — unresolvable skips, never
guesses). The flagging environment must be ATTESTED by a MeshConfig
construction, because PartitionSpec literals alone are a lower bound (an
axis can exist in the mesh without sharding any input):

  * a `mesh=` argument whose value (directly or via one local/module
    assignment) contains a literal `MeshConfig(data=..., seq=...)` call
    — the keyword names ARE the sharded-axis INTENT (a MeshConfig mesh
    physically carries all of axis_names, but a collective over an axis
    the config never sized is dead wire the lint should question); or,
    failing that,
  * the mesh VALUE followed through the PROJECT-WIDE flow graph
    (analysis/project.py), any combination of these hops:
      - an opaque parameter, followed back to every analyzed caller
        (cross-module, via ProjectGraph.callers_of) — the UNION of the
        callers' attested axes, with ONE unresolvable caller poisoning
        the whole attestation (never guess);
      - a parameter or `self.attr` whose ANNOTATION resolves to
        MeshConfig — attests the full axis tuple {data, seq, model}
        (MeshConfig.axis_names is unconditionally all three; only a
        visible ctor can narrow intent below that);
      - `self.attr`, chased to the enclosing class's `__init__`
        assignments (every assignment must attest; union);
      - a factory call `make_mesh(cfg, ...)` whose callee's matched
        parameter is annotated MeshConfig — recurses into the argument
        expression at the call site (the trainer/runtime shape:
        `self.mesh = make_mesh(mesh_cfg, devices)` with
        `mesh_cfg: MeshConfig` on the ctor);
    this is what finally attests the training shard bodies, whose mesh
    is built two modules away from the shard_map site; or, failing that,
  * the MODULE-WIDE union of every MeshConfig axis keyword in the file
    (a module that only ever builds (data, seq) meshes — the serve mesh
    — never legally runs a 'model' collective);
  * PartitionSpec axes from in_specs/out_specs (following one level of
    local-variable indirection, `batch_spec = P(DATA_AXIS)`) UNION into
    the environment but never attest it on their own.

A shard_map with no attested environment is SKIPPED — precision stance:
this checker only fires when it can prove the axis absent. Known residual
blind spot: a call site the resolver cannot see AT ALL (a function-valued
variable, method dispatch it cannot type) is missed rather than poisoned,
so a missed caller with a WIDER mesh could over-flag — every such flag is
a reviewable claim with file:line, and the pragma/baseline channel is the
escape hatch. Collectives are checked through the body's
intra-module call graph, both direct lax.* sites and axis names threaded
through `*axis*`-named parameters of local helpers (the
`_psum_wire(x, SEQ_AXIS, k)` idiom). Each site's attestation (source and
axes) is recorded in ctx.scratch['axis-environment:attested'].
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from glom_tpu.analysis.astutil import (
    call_name,
    const_str,
    enclosing_function,
    imported_collective_aliases,
)
from glom_tpu.analysis.collectives import AXIS_ARG, _collective_of
from glom_tpu.analysis.core import Checker, Context, Finding, SourceModule

# MeshConfig keyword names that declare axes (num_slices is a layout
# knob, not an axis — parallel/mesh.py).
_MESH_AXIS_KW = {"data", "seq", "model"}

# Mesh-flow hop budget (decremented at EVERY helper transition, so the
# real trainer chain — site param -> intra caller -> cross-module caller
# -> self.attr -> __init__ factory -> annotated ctor param — costs nine).
# Cycles are cut by the `seen` guards; this only bounds pathological
# non-cyclic chains.
_FLOW_DEPTH = 16


def _local_assignments(fn_node: Optional[ast.AST], tree: ast.Module):
    """name -> assigned expression, function-local first then module
    level (one level of indirection is all the spec idiom uses)."""
    out: Dict[str, ast.AST] = {}
    scopes = []
    if fn_node is not None:
        scopes.append(ast.iter_child_nodes(fn_node))
    scopes.append(iter(tree.body))
    for body in scopes:
        for node in body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id not in out:
                        out[t.id] = node.value
    return out


def _spec_axes(
    node: ast.AST,
    consts: Dict[str, str],
    assigns,
    _seen: Optional[Set[str]] = None,
) -> Set[str]:
    """Axis names in a PartitionSpec expression subtree, following Name
    references (spec variables like `lv_spec = P(DATA_AXIS, SEQ_AXIS)`)
    through the assignment map (cycle-guarded)."""
    seen = _seen if _seen is not None else set()
    axes: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            name = call_name(sub)
            if name and name.split(".")[-1] in ("P", "PartitionSpec"):
                for arg in sub.args:
                    for leaf in ast.walk(arg):
                        s = const_str(leaf)
                        if s is not None:
                            axes.add(s)
                        elif (
                            isinstance(leaf, ast.Name)
                            and leaf.id in consts
                        ):
                            axes.add(consts[leaf.id])
        elif isinstance(sub, ast.Name) and sub.id not in seen:
            seen.add(sub.id)
            target = assigns.get(sub.id)
            if target is not None:
                axes |= _spec_axes(target, consts, assigns, seen)
    return axes


def _mesh_axes(node: Optional[ast.AST], assigns) -> Set[str]:
    """Axis names provable from a mesh= argument: a MeshConfig(...) call
    in the argument's (or its assignment's) subtree declares its keyword
    names as axes."""
    if node is None:
        return set()
    if isinstance(node, ast.Name):
        node = assigns.get(node.id)
        if node is None:
            return set()
    axes: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            name = call_name(sub)
            if name and name.split(".")[-1] == "MeshConfig":
                for kw in sub.keywords:
                    if kw.arg in _MESH_AXIS_KW:
                        axes.add(kw.arg)
    return axes


class AxisEnvironment(Checker):
    name = "axis-environment"
    description = (
        "collectives inside a shard_map use axis names that exist in "
        "THAT shard_map's mesh (not just the global vocabulary)"
    )

    def check(self, module: SourceModule, ctx: Context) -> List[Finding]:
        aliases = imported_collective_aliases(module.tree)
        consts: Dict[str, str] = {}
        for node in module.tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    s = const_str(node.value)
                    if isinstance(t, ast.Name) and s is not None:
                        consts[t.id] = s
        # Module-wide attestation: every MeshConfig axis keyword in the
        # file (the fallback environment when a site's mesh= argument is
        # an opaque parameter).
        module_mesh_axes: Set[str] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                name = call_name(node)
                if name and name.split(".")[-1] == "MeshConfig":
                    for kw in node.keywords:
                        if kw.arg in _MESH_AXIS_KW:
                            module_mesh_axes.add(kw.arg)
        findings: List[Finding] = []
        seen = set()
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if not name or name.split(".")[-1] != "shard_map":
                continue
            for f in self._check_shard_map(
                module, node, aliases, consts, module_mesh_axes, ctx
            ):
                # A helper reached from several shard_map sites yields
                # one finding per site — identical claims dedup.
                fp = (f.line, f.col, f.key, f.message)
                if fp not in seen:
                    seen.add(fp)
                    findings.append(f)
        return findings

    # -- one shard_map site -------------------------------------------------

    def _check_shard_map(
        self,
        module: SourceModule,
        call: ast.Call,
        aliases: dict,
        consts: Dict[str, str],
        module_mesh_axes: Set[str],
        ctx: Context,
    ) -> List[Finding]:
        enclosing = enclosing_function(module.parents, call)
        assigns = _local_assignments(enclosing, module.tree)
        spec_env: Set[str] = set()
        mesh_arg = None
        for kw in call.keywords:
            if kw.arg in ("in_specs", "out_specs"):
                spec_env |= _spec_axes(kw.value, consts, assigns)
            elif kw.arg == "mesh":
                mesh_arg = kw.value
        attested = _mesh_axes(mesh_arg, assigns)
        how = "ctor"
        if not attested:
            # Opaque mesh value: follow it through the PROJECT flow graph
            # — callers (cross-module), MeshConfig annotations, __init__
            # attribute assignments, mesh-factory calls. Flow-specific
            # axes beat the module union (a file can build both a
            # (data, seq) serve mesh and a 'model'-carrying training
            # mesh; the union would attest the wrong environment for
            # both).
            finfo = (
                module.index.info_for(enclosing)
                if enclosing is not None
                else None
            )
            attested = self._attest_value(
                self._project(ctx, module), module, finfo, mesh_arg,
                assigns, _FLOW_DEPTH, set(),
            )
            how = "flow"
        if not attested:
            attested = module_mesh_axes
            how = "module-union"
        trail = ctx.scratch.setdefault("axis-environment:attested", [])
        trail.append(
            (
                module.relpath,
                call.lineno,
                how if attested else "unattested",
                tuple(sorted(attested)),
            )
        )
        if not attested:
            return []  # opaque environment: skip, never guess
        env = attested | spec_env
        body = call.args[0] if call.args else None
        funcs = self._reachable(module, enclosing, body)
        findings: List[Finding] = []
        for info in funcs:
            for sub in info.body_nodes():
                if not isinstance(sub, ast.Call):
                    continue
                findings.extend(
                    self._check_call(
                        module, sub, aliases, consts, env, info
                    )
                )
        return findings

    # -- mesh-flow attestation (project-wide) --------------------------------

    @staticmethod
    def _project(ctx: Context, module: SourceModule):
        if ctx.project is not None:
            return ctx.project
        from glom_tpu.analysis.project import ProjectGraph

        return ProjectGraph(ctx.modules or [module])

    @staticmethod
    def _is_meshconfig(tref) -> bool:
        return (
            tref is not None
            and tref.cls is not None
            and tref.cls.split(":")[-1] == "MeshConfig"
        )

    def _attest_value(
        self, project, module, finfo, expr, assigns, depth, seen
    ) -> Set[str]:
        """Axes provable for a mesh-valued EXPRESSION in the context of
        (module, finfo): literal MeshConfig keywords first (intent), then
        local assignment chasing, MeshConfig-annotated parameters (full
        axis tuple), caller attestation for opaque parameters,
        `self.attr` via the enclosing class's __init__, and
        MeshConfig-annotated factory calls. Empty set = cannot prove."""
        if expr is None or depth <= 0:
            return set()
        got = _mesh_axes(expr, assigns)
        if got:
            return got
        if isinstance(expr, ast.Name):
            bound = assigns.get(expr.id)
            if bound is not None:
                key = ("v", module.relpath, id(bound))
                if key in seen:
                    return set()
                seen.add(key)
                got = self._attest_value(
                    project, module, finfo, bound, assigns, depth - 1, seen
                )
                if got:
                    return got
            if finfo is not None and expr.id in finfo.params:
                if self._param_is_meshconfig(project, module, finfo, expr.id):
                    return set(_MESH_AXIS_KW)
                return self._attest_param(
                    project, module, finfo, expr.id, depth - 1, seen
                )
            return set()
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and finfo is not None
        ):
            return self._attest_self_attr(
                project, module, finfo, expr.attr, depth - 1, seen
            )
        if isinstance(expr, ast.Call):
            return self._attest_factory(
                project, module, finfo, expr, assigns, depth - 1, seen
            )
        return set()

    @staticmethod
    def _param_is_meshconfig(project, module, finfo, param: str) -> bool:
        """The parameter's own annotation resolves to MeshConfig — the
        full axis tuple is then structural (MeshConfig.axis_names is
        unconditionally ('data', 'seq', 'model')), no ctor needed."""
        a = getattr(finfo.node, "args", None)
        if a is None:
            return False
        minfo = project.info_of(module)
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            if p.arg == param:
                return AxisEnvironment._is_meshconfig(
                    project.annotation_type(minfo, p.annotation)
                )
        return False

    def _attest_param(
        self, project, module, finfo, param: str, depth, seen
    ) -> Set[str]:
        """Axes provable by following an opaque mesh PARAMETER back to
        every ANALYZED caller that binds it — cross-module, via the
        project call graph. Attests only when at least one caller is
        found AND every found caller's argument attests (through its own
        flow, bounded recursion); any unresolvable caller returns the
        empty set, the precision stance everywhere in this checker."""
        if depth <= 0:
            return set()
        key = ("p", module.relpath, finfo.qualname, param)
        if key in seen:
            return set()  # recursion never adds evidence
        seen.add(key)
        a = getattr(finfo.node, "args", None)
        if a is None:
            return set()
        pos_names = [p.arg for p in a.posonlyargs + a.args]
        axes: Set[str] = set()
        found = False
        for cinfo, cfinfo, call in project.callers_of(finfo):
            if cfinfo is not None and cfinfo.node is finfo.node:
                continue  # self-recursion never adds evidence
            arg_expr = None
            for kw in call.keywords:
                if kw.arg == param:
                    arg_expr = kw.value
            if arg_expr is None and param in pos_names:
                idx = pos_names.index(param)
                if idx < len(call.args) and not any(
                    isinstance(p, ast.Starred) for p in call.args[: idx + 1]
                ):
                    arg_expr = call.args[idx]
            if arg_expr is None:
                return set()  # splat / default binding: never guess
            cmod = cinfo.module
            cassigns = _local_assignments(
                cfinfo.node if cfinfo is not None else None, cmod.tree
            )
            got = self._attest_value(
                project, cmod, cfinfo, arg_expr, cassigns, depth - 1, seen
            )
            if not got:
                return set()  # one unattested caller poisons all
            found = True
            axes |= got
        return axes if found else set()

    def _attest_self_attr(
        self, project, module, finfo, attr: str, depth, seen
    ) -> Set[str]:
        """`self.attr` mesh: every `self.attr = ...` assignment in the
        enclosing class's __init__ must attest (union); a
        MeshConfig-typed attribute attests the full tuple outright."""
        cls = project.enclosing_class(module, finfo)
        if cls is None or depth <= 0:
            return set()
        minfo = project.info_of(module)
        if self._is_meshconfig(project.class_attr_types(minfo, cls).get(attr)):
            return set(_MESH_AXIS_KW)
        init = next(
            (
                n
                for n in cls.body
                if isinstance(n, ast.FunctionDef) and n.name == "__init__"
            ),
            None,
        )
        init_info = module.index.info_for(init) if init is not None else None
        if init_info is None:
            return set()
        init_assigns = _local_assignments(init, module.tree)
        values = []
        for stmt in init_info.body_nodes():
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            )
            for t in targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                    and t.attr == attr
                    and stmt.value is not None
                ):
                    values.append(stmt.value)
        if not values:
            return set()
        axes: Set[str] = set()
        for value in values:
            key = ("v", module.relpath, id(value))
            if key in seen:
                return set()
            seen.add(key)
            got = self._attest_value(
                project, module, init_info, value, init_assigns,
                depth - 1, seen,
            )
            if not got:
                return set()  # one opaque assignment poisons the attr
            axes |= got
        return axes

    def _attest_factory(
        self, project, module, finfo, call: ast.Call, assigns, depth, seen
    ) -> Set[str]:
        """A call whose resolvable callee takes a MeshConfig-annotated
        parameter builds its mesh FROM that config — recurse into the
        matched argument expression at this call site (the
        `make_mesh(mesh_cfg, devices)` shape). Every bound
        MeshConfig-annotated argument must attest; union."""
        if depth <= 0:
            return set()
        hit = project.resolve_call(module, finfo, call)
        if hit is None:
            return set()
        tminfo, tfinfo = hit
        a = getattr(tfinfo.node, "args", None)
        if a is None:
            return set()
        pos_names = [p.arg for p in a.posonlyargs + a.args]
        axes: Set[str] = set()
        found = False
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            if not self._is_meshconfig(
                project.annotation_type(tminfo, p.annotation)
            ):
                continue
            arg_expr = None
            for kw in call.keywords:
                if kw.arg == p.arg:
                    arg_expr = kw.value
            if arg_expr is None and p.arg in pos_names:
                idx = pos_names.index(p.arg)
                if idx < len(call.args) and not any(
                    isinstance(q, ast.Starred) for q in call.args[: idx + 1]
                ):
                    arg_expr = call.args[idx]
            if arg_expr is None:
                continue  # defaulted config: no evidence either way
            got = self._attest_value(
                project, module, finfo, arg_expr, assigns, depth - 1, seen
            )
            if not got:
                return set()  # an opaque config argument poisons the call
            found = True
            axes |= got
        return axes if found else set()

    def _reachable(self, module: SourceModule, enclosing, body) -> List:
        """The body function plus every intra-module function its call
        graph reaches (names resolved through the scope chain)."""
        start = None
        if isinstance(body, ast.Name):
            scope_info = (
                module.index.info_for(enclosing) if enclosing else None
            )
            scope = (
                scope_info.scope if scope_info else module.index.module_scope
            )
            start = scope.resolve(body.id)
        elif isinstance(body, (ast.Lambda, ast.FunctionDef)):
            start = module.index.info_for(body)
        if start is None:
            return []
        seen = {id(start.node)}
        work, out = [start], [start]
        while work:
            info = work.pop()
            for sub in info.body_nodes():
                if not isinstance(sub, ast.Call):
                    continue
                name = call_name(sub)
                if not name or "." in name:
                    continue
                callee = info.scope.resolve(name)
                if callee is not None and id(callee.node) not in seen:
                    seen.add(id(callee.node))
                    work.append(callee)
                    out.append(callee)
        return out

    def _resolve_axis(
        self, node: ast.AST, consts: Dict[str, str]
    ) -> Optional[str]:
        s = const_str(node)
        if s is not None:
            return s
        if isinstance(node, ast.Name) and node.id in consts:
            return consts[node.id]
        return None

    def _check_call(
        self,
        module: SourceModule,
        call: ast.Call,
        aliases: dict,
        consts: Dict[str, str],
        env: Set[str],
        info,
    ) -> List[Finding]:
        out: List[Finding] = []

        def flag(axis: str, what: str) -> None:
            out.append(
                Finding(
                    checker=self.name,
                    path=module.relpath,
                    line=call.lineno,
                    col=call.col_offset,
                    message=(
                        f"{what} uses axis {axis!r}, which is not in the "
                        f"enclosing shard_map's mesh axes {sorted(env)} — "
                        "this traces only at runtime, on that exact mesh"
                    ),
                    symbol=info.qualname,
                    key=f"axis-env-{axis}",
                )
            )

        coll = _collective_of(call, aliases)
        if coll is not None:
            axis_node = None
            for kw in call.keywords:
                if kw.arg == "axis_name":
                    axis_node = kw.value
            if axis_node is None:
                idx = AXIS_ARG[coll]
                if len(call.args) > idx:
                    axis_node = call.args[idx]
            axes = []
            if axis_node is not None:
                if isinstance(axis_node, (ast.Tuple, ast.List)):
                    axes = [
                        self._resolve_axis(e, consts)
                        for e in axis_node.elts
                    ]
                else:
                    axes = [self._resolve_axis(axis_node, consts)]
            for axis in axes:
                if axis is not None and axis not in env:
                    flag(axis, f"lax.{coll}")
            return out
        # Axis threaded through a local helper's *axis*-named parameter
        # (the registered-wrapper idiom: _psum_wire(x, SEQ_AXIS, k)).
        name = call_name(call)
        if not name or "." in name:
            return out
        callee = info.scope.resolve(name)
        if callee is None:
            return out
        params = callee.params
        for i, arg in enumerate(call.args):
            if i < len(params) and "axis" in params[i]:
                axis = self._resolve_axis(arg, consts)
                if axis is not None and axis not in env:
                    flag(axis, f"{name}({params[i]}=...)")
        for kw in call.keywords:
            if kw.arg and "axis" in kw.arg:
                axis = self._resolve_axis(kw.value, consts)
                if axis is not None and axis not in env:
                    flag(axis, f"{name}({kw.arg}=...)")
        return out
