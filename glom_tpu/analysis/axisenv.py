"""axis-environment: a collective's axis name must exist in the enclosing
shard_map's mesh.

The collective-coverage checker (analysis/collectives.py) validates axis
names against the GLOBAL vocabulary — every `*_AXIS` constant in the
scanned tree. That misses a subtler bug: a psum over 'model' inside a
shard_map whose mesh only declares ('data', 'seq') uses a perfectly
vocabulary-legal axis that DOES NOT EXIST in its own environment, and
fails only at runtime, only when that exact mesh shape traces. The paged
serve gathers (parallel/serve_mesh.py) are exactly where this bites: the
serve mesh is ('data', 'seq') while the training mesh also carries
'model', so a copy-pasted training collective is one axis name away from
a trace-time explosion the lint should catch on CPU.

Environment resolution (static, conservative — unresolvable skips, never
guesses). The flagging environment must be ATTESTED by a MeshConfig
construction, because PartitionSpec literals alone are a lower bound (an
axis can exist in the mesh without sharding any input):

  * a `mesh=` argument whose value (directly or via one local/module
    assignment) contains a literal `MeshConfig(data=..., seq=...)` call
    — the keyword names ARE the axis names (MeshConfig.axis_names); or,
    failing that,
  * a mesh= argument that is an OPAQUE PARAMETER of the enclosing
    function, followed back through the intra-module call graph: when
    every intra-module caller's argument (directly, via one local
    assignment, or via the caller's OWN parameter one more hop up)
    attests a MeshConfig, the UNION of those callers' axes is the
    environment — more specific than the module union, which is what
    catches a serve-shaped helper in a file that also builds a 'model'-
    carrying training mesh; one unresolvable caller skips (never
    guess); or, failing that,
  * the MODULE-WIDE union of every MeshConfig axis keyword in the file
    (a module that only ever builds (data, seq) meshes — the serve mesh
    — never legally runs a 'model' collective);
  * PartitionSpec axes from in_specs/out_specs (following one level of
    local-variable indirection, `batch_spec = P(DATA_AXIS)`) UNION into
    the environment but never attest it on their own.

A shard_map with no attested environment (an opaque mesh parameter in a
module that builds no meshes — the training shard bodies, whose mesh
shapes arrive from config) is SKIPPED — precision stance: this checker
only fires when it can prove the axis absent. Collectives are checked
through the body's intra-module call graph, both direct lax.* sites and
axis names threaded through `*axis*`-named parameters of local helpers
(the `_psum_wire(x, SEQ_AXIS, k)` idiom).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from glom_tpu.analysis.astutil import (
    call_name,
    const_str,
    enclosing_function,
    imported_collective_aliases,
)
from glom_tpu.analysis.collectives import AXIS_ARG, _collective_of
from glom_tpu.analysis.core import Checker, Context, Finding, SourceModule

# MeshConfig keyword names that declare axes (num_slices is a layout
# knob, not an axis — parallel/mesh.py).
_MESH_AXIS_KW = {"data", "seq", "model"}


def _local_assignments(fn_node: Optional[ast.AST], tree: ast.Module):
    """name -> assigned expression, function-local first then module
    level (one level of indirection is all the spec idiom uses)."""
    out: Dict[str, ast.AST] = {}
    scopes = []
    if fn_node is not None:
        scopes.append(ast.iter_child_nodes(fn_node))
    scopes.append(iter(tree.body))
    for body in scopes:
        for node in body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id not in out:
                        out[t.id] = node.value
    return out


def _spec_axes(
    node: ast.AST,
    consts: Dict[str, str],
    assigns,
    _seen: Optional[Set[str]] = None,
) -> Set[str]:
    """Axis names in a PartitionSpec expression subtree, following Name
    references (spec variables like `lv_spec = P(DATA_AXIS, SEQ_AXIS)`)
    through the assignment map (cycle-guarded)."""
    seen = _seen if _seen is not None else set()
    axes: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            name = call_name(sub)
            if name and name.split(".")[-1] in ("P", "PartitionSpec"):
                for arg in sub.args:
                    for leaf in ast.walk(arg):
                        s = const_str(leaf)
                        if s is not None:
                            axes.add(s)
                        elif (
                            isinstance(leaf, ast.Name)
                            and leaf.id in consts
                        ):
                            axes.add(consts[leaf.id])
        elif isinstance(sub, ast.Name) and sub.id not in seen:
            seen.add(sub.id)
            target = assigns.get(sub.id)
            if target is not None:
                axes |= _spec_axes(target, consts, assigns, seen)
    return axes


def _mesh_axes(node: Optional[ast.AST], assigns) -> Set[str]:
    """Axis names provable from a mesh= argument: a MeshConfig(...) call
    in the argument's (or its assignment's) subtree declares its keyword
    names as axes."""
    if node is None:
        return set()
    if isinstance(node, ast.Name):
        node = assigns.get(node.id)
        if node is None:
            return set()
    axes: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            name = call_name(sub)
            if name and name.split(".")[-1] == "MeshConfig":
                for kw in sub.keywords:
                    if kw.arg in _MESH_AXIS_KW:
                        axes.add(kw.arg)
    return axes


class AxisEnvironment(Checker):
    name = "axis-environment"
    description = (
        "collectives inside a shard_map use axis names that exist in "
        "THAT shard_map's mesh (not just the global vocabulary)"
    )

    def check(self, module: SourceModule, ctx: Context) -> List[Finding]:
        aliases = imported_collective_aliases(module.tree)
        consts: Dict[str, str] = {}
        for node in module.tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    s = const_str(node.value)
                    if isinstance(t, ast.Name) and s is not None:
                        consts[t.id] = s
        # Module-wide attestation: every MeshConfig axis keyword in the
        # file (the fallback environment when a site's mesh= argument is
        # an opaque parameter).
        module_mesh_axes: Set[str] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                name = call_name(node)
                if name and name.split(".")[-1] == "MeshConfig":
                    for kw in node.keywords:
                        if kw.arg in _MESH_AXIS_KW:
                            module_mesh_axes.add(kw.arg)
        findings: List[Finding] = []
        seen = set()
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if not name or name.split(".")[-1] != "shard_map":
                continue
            for f in self._check_shard_map(
                module, node, aliases, consts, module_mesh_axes
            ):
                # A helper reached from several shard_map sites yields
                # one finding per site — identical claims dedup.
                fp = (f.line, f.col, f.key, f.message)
                if fp not in seen:
                    seen.add(fp)
                    findings.append(f)
        return findings

    # -- one shard_map site -------------------------------------------------

    def _check_shard_map(
        self,
        module: SourceModule,
        call: ast.Call,
        aliases: dict,
        consts: Dict[str, str],
        module_mesh_axes: Set[str],
    ) -> List[Finding]:
        enclosing = enclosing_function(module.parents, call)
        assigns = _local_assignments(enclosing, module.tree)
        spec_env: Set[str] = set()
        mesh_arg = None
        for kw in call.keywords:
            if kw.arg in ("in_specs", "out_specs"):
                spec_env |= _spec_axes(kw.value, consts, assigns)
            elif kw.arg == "mesh":
                mesh_arg = kw.value
        attested = _mesh_axes(mesh_arg, assigns)
        if not attested:
            # Opaque parameter: follow the INTRA-MODULE callers' mesh
            # argument back to their MeshConfig — caller-specific axes
            # beat the module union (a file can build both a (data, seq)
            # serve mesh and a 'model'-carrying training mesh; the union
            # would attest the wrong environment for both).
            attested = self._caller_attested(module, enclosing, mesh_arg)
        if not attested:
            attested = module_mesh_axes
        if not attested:
            return []  # opaque environment: skip, never guess
        env = attested | spec_env
        body = call.args[0] if call.args else None
        funcs = self._reachable(module, enclosing, body)
        findings: List[Finding] = []
        for info in funcs:
            for sub in info.body_nodes():
                if not isinstance(sub, ast.Call):
                    continue
                findings.extend(
                    self._check_call(
                        module, sub, aliases, consts, env, info
                    )
                )
        return findings

    def _caller_attested(
        self,
        module: SourceModule,
        enclosing: Optional[ast.AST],
        mesh_arg: Optional[ast.AST],
        depth: int = 3,
    ) -> Set[str]:
        """Axes provable by following an opaque mesh PARAMETER back to
        the intra-module callers that bind it. Attests only when at
        least one caller is found AND every found caller's argument
        resolves to a MeshConfig (directly, through one local
        assignment, or through the caller's own parameter — bounded
        recursion); any unresolvable caller returns the empty set, the
        precision stance everywhere in this checker."""
        if (
            depth <= 0
            or enclosing is None
            or not isinstance(mesh_arg, ast.Name)
        ):
            return set()
        info = module.index.info_for(enclosing)
        if info is None or mesh_arg.id not in info.params:
            return set()
        param = mesh_arg.id
        a = enclosing.args
        pos_names = [p.arg for p in a.posonlyargs + a.args]
        axes: Set[str] = set()
        found = False
        for caller in module.index.functions.values():
            if caller.node is enclosing:
                continue  # self-recursion never adds evidence
            for sub in caller.body_nodes():
                if not isinstance(sub, ast.Call):
                    continue
                name = call_name(sub)
                if not name or "." in name:
                    continue
                callee = caller.scope.resolve(name)
                if callee is None or callee.node is not enclosing:
                    continue
                arg_expr = None
                for kw in sub.keywords:
                    if kw.arg == param:
                        arg_expr = kw.value
                if arg_expr is None and param in pos_names:
                    idx = pos_names.index(param)
                    if idx < len(sub.args):
                        arg_expr = sub.args[idx]
                if arg_expr is None:
                    return set()  # splat / default binding: never guess
                caller_assigns = _local_assignments(
                    caller.node, module.tree
                )
                got = _mesh_axes(arg_expr, caller_assigns)
                if not got and isinstance(arg_expr, ast.Name):
                    got = self._caller_attested(
                        module, caller.node, arg_expr, depth - 1
                    )
                if not got:
                    return set()  # one unattested caller poisons all
                found = True
                axes |= got
        return axes if found else set()

    def _reachable(self, module: SourceModule, enclosing, body) -> List:
        """The body function plus every intra-module function its call
        graph reaches (names resolved through the scope chain)."""
        start = None
        if isinstance(body, ast.Name):
            scope_info = (
                module.index.info_for(enclosing) if enclosing else None
            )
            scope = (
                scope_info.scope if scope_info else module.index.module_scope
            )
            start = scope.resolve(body.id)
        elif isinstance(body, (ast.Lambda, ast.FunctionDef)):
            start = module.index.info_for(body)
        if start is None:
            return []
        seen = {id(start.node)}
        work, out = [start], [start]
        while work:
            info = work.pop()
            for sub in info.body_nodes():
                if not isinstance(sub, ast.Call):
                    continue
                name = call_name(sub)
                if not name or "." in name:
                    continue
                callee = info.scope.resolve(name)
                if callee is not None and id(callee.node) not in seen:
                    seen.add(id(callee.node))
                    work.append(callee)
                    out.append(callee)
        return out

    def _resolve_axis(
        self, node: ast.AST, consts: Dict[str, str]
    ) -> Optional[str]:
        s = const_str(node)
        if s is not None:
            return s
        if isinstance(node, ast.Name) and node.id in consts:
            return consts[node.id]
        return None

    def _check_call(
        self,
        module: SourceModule,
        call: ast.Call,
        aliases: dict,
        consts: Dict[str, str],
        env: Set[str],
        info,
    ) -> List[Finding]:
        out: List[Finding] = []

        def flag(axis: str, what: str) -> None:
            out.append(
                Finding(
                    checker=self.name,
                    path=module.relpath,
                    line=call.lineno,
                    col=call.col_offset,
                    message=(
                        f"{what} uses axis {axis!r}, which is not in the "
                        f"enclosing shard_map's mesh axes {sorted(env)} — "
                        "this traces only at runtime, on that exact mesh"
                    ),
                    symbol=info.qualname,
                    key=f"axis-env-{axis}",
                )
            )

        coll = _collective_of(call, aliases)
        if coll is not None:
            axis_node = None
            for kw in call.keywords:
                if kw.arg == "axis_name":
                    axis_node = kw.value
            if axis_node is None:
                idx = AXIS_ARG[coll]
                if len(call.args) > idx:
                    axis_node = call.args[idx]
            axes = []
            if axis_node is not None:
                if isinstance(axis_node, (ast.Tuple, ast.List)):
                    axes = [
                        self._resolve_axis(e, consts)
                        for e in axis_node.elts
                    ]
                else:
                    axes = [self._resolve_axis(axis_node, consts)]
            for axis in axes:
                if axis is not None and axis not in env:
                    flag(axis, f"lax.{coll}")
            return out
        # Axis threaded through a local helper's *axis*-named parameter
        # (the registered-wrapper idiom: _psum_wire(x, SEQ_AXIS, k)).
        name = call_name(call)
        if not name or "." in name:
            return out
        callee = info.scope.resolve(name)
        if callee is None:
            return out
        params = callee.params
        for i, arg in enumerate(call.args):
            if i < len(params) and "axis" in params[i]:
                axis = self._resolve_axis(arg, consts)
                if axis is not None and axis not in env:
                    flag(axis, f"{name}({params[i]}=...)")
        for kw in call.keywords:
            if kw.arg and "axis" in kw.arg:
                axis = self._resolve_axis(kw.value, consts)
                if axis is not None and axis not in env:
                    flag(axis, f"{name}({kw.arg}=...)")
        return out
