"""Device prefetch for the input pipeline (SURVEY.md §5: the reference has
no data subsystem at all — its README pulls tensors synchronously).

On TPU the host->device batch transfer otherwise sits on the train step's
critical path; staging the next batches from a background thread while the
current step runs hides it entirely (the standard TPU input-pipeline
pattern; jax transfers are thread-safe and async, so the worker only
initiates DMAs — it never blocks on compute).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Optional

import jax

_END = object()


def prefetch_to_device(
    data: Iterator,
    *,
    size: int = 2,
    sharding: Optional[jax.sharding.Sharding] = None,
    metrics_writer=None,
) -> Iterator:
    """Wrap `data` so the next `size` batches are already on device (laid
    out per `sharding` if given — pass the DistributedTrainer's batch
    sharding to stage shards directly on their target devices) while the
    consumer runs.

    Validation and the worker thread start HERE, at the call — prefetching
    begins immediately, and a bad `size` fails at the call site rather
    than deep inside a training loop. Exceptions from `data` propagate to
    the consumer at the point of the failed batch. Dropping the returned
    iterator (the common case: `fit` pulls num_steps batches from an
    infinite dataset and returns) signals the worker to stop and drains
    the staged batches, so neither the thread nor the device buffers
    outlive the consumer.

    The worker's two host phases are span-covered (tracing.spans.spanned:
    host_prefetch_next = pulling from the source iterator,
    host_prefetch_stage = initiating the device transfer) into a private
    aggregator; `metrics_writer` (when given) receives the per-phase
    rollup "span" records when the stream ends — the last unattributed
    host-time sink the ROADMAP named. Without a writer the rollups feed
    the global flight recorder.
    """
    if size < 1:
        raise ValueError(f"prefetch size must be >= 1, got {size}")
    q: queue.Queue = queue.Queue(maxsize=size)
    stop = threading.Event()

    from glom_tpu.tracing.spans import SpanAggregator, spanned

    spans = SpanAggregator()

    def put(item) -> bool:
        """Blocking put that aborts when the consumer is gone."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    stage = spanned("host_prefetch_stage", aggregator=spans)(
        lambda batch: jax.device_put(batch, sharding)
        if sharding is not None
        else jax.device_put(batch)
    )
    pull_next = spanned("host_prefetch_next", aggregator=spans)(
        lambda it: next(it, _END)
    )

    def worker():
        try:
            while True:
                batch = pull_next(iter_data)
                if batch is _END:
                    break
                if not put(stage(batch)):
                    return
        except BaseException as e:  # noqa: BLE001 - relay to the consumer
            put((_END, e))
            return
        put((_END, None))

    iter_data = iter(data)

    def _drain_spans():
        from glom_tpu.tracing.flight import write_or_observe

        for rec in spans.records(extra={"source": "prefetch_to_device"}):
            write_or_observe(metrics_writer, rec)

    thread = threading.Thread(target=worker, daemon=True)
    thread.start()

    def drain():
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass

    def gen():
        try:
            while True:
                item = q.get()
                if isinstance(item, tuple) and len(item) == 2 and item[0] is _END:
                    if item[1] is not None:
                        raise item[1]
                    return
                yield item
        finally:
            # Consumer done (exhausted, closed, or GC'd): unblock the
            # worker and drop any staged device buffers promptly. A worker
            # mid-put can still enqueue ONE already-transferred batch after
            # a single drain, so alternate drain/join until it has actually
            # exited (bounded: a worker stuck inside `data` itself is a
            # daemon thread and cannot re-enqueue once stop is set and the
            # final drain has run).
            stop.set()
            deadline = 20  # x 0.1s join timeout = 2s bound
            while True:
                drain()
                thread.join(timeout=0.1)
                if not thread.is_alive() or deadline <= 0:
                    break
                deadline -= 1
            drain()
            _drain_spans()

    return gen()
