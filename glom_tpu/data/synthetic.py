"""Synthetic image datasets (this container has no dataset downloads: zero
egress, no torchvision/tfds). Procedural images with real part-whole
structure — random colored rectangles and circles on textured backgrounds —
so the denoising objective has actual signal to learn, unlike pure noise.

Deterministic given a seed; generation is numpy on the host, batches are
handed to JAX as float32 [b, c, H, W] in [-1, 1].
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np


def _draw_shapes(rng: np.random.Generator, size: int, num_shapes: int) -> np.ndarray:
    """One [3, size, size] image in [-1, 1]."""
    img = np.ones((3, size, size), np.float32) * rng.uniform(-0.4, 0.4, (3, 1, 1))
    yy, xx = np.mgrid[0:size, 0:size]
    for _ in range(num_shapes):
        color = rng.uniform(-1, 1, (3, 1, 1)).astype(np.float32)
        kind = rng.integers(0, 2)
        if kind == 0:  # rectangle
            x0, y0 = rng.integers(0, size, 2)
            w, h = rng.integers(size // 8, size // 2, 2)
            mask = (xx >= x0) & (xx < x0 + w) & (yy >= y0) & (yy < y0 + h)
        else:  # circle
            cx, cy = rng.integers(0, size, 2)
            r = rng.integers(size // 10, size // 3)
            mask = (xx - cx) ** 2 + (yy - cy) ** 2 < r ** 2
        img = np.where(mask[None], color, img)
    return np.clip(img, -1.0, 1.0)


def shapes_dataset(
    batch_size: int,
    image_size: int,
    *,
    seed: int = 0,
    num_shapes: int = 5,
    num_batches: Optional[int] = None,
) -> Iterator[np.ndarray]:
    """Infinite (or bounded) iterator of [b, 3, H, W] float32 batches."""
    rng = np.random.default_rng(seed)
    produced = 0
    while num_batches is None or produced < num_batches:
        batch = np.stack(
            [_draw_shapes(rng, image_size, num_shapes) for _ in range(batch_size)]
        )
        yield batch
        produced += 1


def gaussian_dataset(
    batch_size: int, image_size: int, *, seed: int = 0
) -> Iterator[np.ndarray]:
    """Pure-noise images — for smoke tests and benchmarks where content is
    irrelevant and generation speed matters."""
    rng = np.random.default_rng(seed)
    while True:
        yield rng.normal(size=(batch_size, 3, image_size, image_size)).astype(
            np.float32
        )
