"""Synthetic image datasets (this container has no dataset downloads: zero
egress, no torchvision/tfds). Procedural images with real part-whole
structure — random colored rectangles and circles on textured backgrounds —
so the denoising objective has actual signal to learn, unlike pure noise.

Deterministic given a seed; generation is numpy on the host, batches are
handed to JAX as float32 [b, c, H, W] in [-1, 1].
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np


def _draw_shapes(rng: np.random.Generator, size: int, num_shapes: int) -> np.ndarray:
    """One [3, size, size] image in [-1, 1]."""
    img = np.ones((3, size, size), np.float32) * rng.uniform(-0.4, 0.4, (3, 1, 1))
    yy, xx = np.mgrid[0:size, 0:size]
    for _ in range(num_shapes):
        color = rng.uniform(-1, 1, (3, 1, 1)).astype(np.float32)
        kind = rng.integers(0, 2)
        if kind == 0:  # rectangle
            x0, y0 = rng.integers(0, size, 2)
            w, h = rng.integers(size // 8, size // 2, 2)
            mask = (xx >= x0) & (xx < x0 + w) & (yy >= y0) & (yy < y0 + h)
        else:  # circle
            cx, cy = rng.integers(0, size, 2)
            r = rng.integers(size // 10, size // 3)
            mask = (xx - cx) ** 2 + (yy - cy) ** 2 < r ** 2
        img = np.where(mask[None], color, img)
    return np.clip(img, -1.0, 1.0)


def shapes_dataset(
    batch_size: int,
    image_size: int,
    *,
    seed: int = 0,
    num_shapes: int = 5,
    num_batches: Optional[int] = None,
) -> Iterator[np.ndarray]:
    """Infinite (or bounded) iterator of [b, 3, H, W] float32 batches."""
    rng = np.random.default_rng(seed)
    produced = 0
    while num_batches is None or produced < num_batches:
        batch = np.stack(
            [_draw_shapes(rng, image_size, num_shapes) for _ in range(batch_size)]
        )
        yield batch
        produced += 1


def gaussian_dataset(
    batch_size: int, image_size: int, *, seed: int = 0
) -> Iterator[np.ndarray]:
    """Pure-noise images — for smoke tests and benchmarks where content is
    irrelevant and generation speed matters."""
    rng = np.random.default_rng(seed)
    while True:
        yield rng.normal(size=(batch_size, 3, image_size, image_size)).astype(
            np.float32
        )


def write_shapes_dataset(
    out_dir: str,
    num_images: int,
    image_size: int,
    *,
    seed: int = 0,
    fmt: str = "png",
    shard_size: int = 512,
) -> list:
    """Render the seeded shapes distribution to DISK — the deterministic
    on-disk dataset that backs the file-based input-pipeline record (the
    environment has no downloadable datasets; the reference README trains
    on real images from the user's own folder, ~:30-75).

    fmt='png': one 8-bit RGB PNG per image (exercises the image-decode
    loader, image_folder_dataset). fmt='npy': [shard_size, 3, H, W]
    float32 shards (npy_dataset). Returns the list of file paths written.
    """
    import os

    os.makedirs(out_dir, exist_ok=True)
    rng = np.random.default_rng(seed)
    paths = []
    if fmt == "png":
        from PIL import Image

        for i in range(num_images):
            img = _draw_shapes(rng, image_size, 5)  # [3, H, W] in [-1, 1]
            u8 = ((np.transpose(img, (1, 2, 0)) + 1.0) * 127.5).round()
            u8 = np.clip(u8, 0, 255).astype(np.uint8)
            p = os.path.join(out_dir, f"shape_{i:06d}.png")
            Image.fromarray(u8).save(p)
            paths.append(p)
        return paths
    if fmt == "npy":
        for s in range(0, num_images, shard_size):
            count = min(shard_size, num_images - s)
            shard = np.stack(
                [_draw_shapes(rng, image_size, 5) for _ in range(count)]
            )
            p = os.path.join(out_dir, f"shard_{s // shard_size:04d}.npy")
            np.save(p, shard)
            paths.append(p)
        return paths
    raise ValueError(f"fmt={fmt!r}: one of 'png', 'npy'")
