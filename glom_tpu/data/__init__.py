from glom_tpu.data.synthetic import gaussian_dataset, shapes_dataset

__all__ = ["gaussian_dataset", "shapes_dataset"]
