from glom_tpu.data.loaders import (
    file_dataset,
    image_folder_dataset,
    npy_dataset,
)
from glom_tpu.data.prefetch import prefetch_to_device
from glom_tpu.data.synthetic import (
    gaussian_dataset,
    shapes_dataset,
    write_shapes_dataset,
)

__all__ = [
    "file_dataset",
    "gaussian_dataset",
    "image_folder_dataset",
    "npy_dataset",
    "prefetch_to_device",
    "shapes_dataset",
    "write_shapes_dataset",
]
