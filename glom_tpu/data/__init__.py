from glom_tpu.data.prefetch import prefetch_to_device
from glom_tpu.data.synthetic import gaussian_dataset, shapes_dataset

__all__ = ["gaussian_dataset", "prefetch_to_device", "shapes_dataset"]
