"""File-backed image datasets: the real-data input path.

The reference's README recipe trains on actual images pulled by the user's
own loader (SURVEY.md §2.1 #8, README ~:30-75); this module is the
framework-side equivalent: a directory of images (PIL-decodable) or .npy
shard files -> shuffled, normalized [b, 3, H, W] float32 batches, ready
for `prefetch_to_device` staging and the trainer's on-device noising
(noise stays IN-STEP — adding it on the host would burn host->device
bandwidth on data the TPU can generate during the matmuls).

Multi-host sharding is PROCESS-level (`shard_index` / `num_shards`, wired
to jax.process_index/count by the CLI): each host reads only its slice of
the file list, the per-host batch is then device-sharded by the trainer's
batch NamedSharding (data/prefetch.py handles staging). This is the
standard TPU input-pipeline split: files across hosts, batch across chips.

Normalization contract matches the synthetic datasets: images land in
[-1, 1] (uint8 -> x/127.5 - 1; float inputs are assumed pre-scaled to
[0, 1] or [-1, 1] and mapped accordingly).
"""

from __future__ import annotations

import os
from typing import Iterator, Optional, Sequence

import numpy as np

_IMG_EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp")


def _to_chw_float(arr: np.ndarray) -> np.ndarray:
    """[H, W, C] or [H, W] uint8/float -> [3, H, W] float32 in [-1, 1]."""
    if arr.ndim == 2:
        arr = arr[..., None]
    if arr.shape[-1] == 1:  # grayscale -> triple
        arr = np.repeat(arr, 3, axis=-1)
    if arr.shape[-1] == 4:  # drop alpha
        arr = arr[..., :3]
    if arr.dtype == np.uint8:
        arr = arr.astype(np.float32) / 127.5 - 1.0
    else:
        arr = arr.astype(np.float32)
        if arr.min() >= 0.0 and arr.max() > 1.5:  # 0..255 floats
            arr = arr / 127.5 - 1.0
        elif arr.min() >= 0.0:  # 0..1 floats
            arr = arr * 2.0 - 1.0
    return np.transpose(arr, (2, 0, 1))


def _list_shard(paths: Sequence[str], shard_index: int, num_shards: int):
    if not 0 <= shard_index < num_shards:
        raise ValueError(f"shard {shard_index} outside 0..{num_shards - 1}")
    shard = list(paths[shard_index::num_shards])
    if not shard:
        raise ValueError(
            f"shard {shard_index}/{num_shards} is empty ({len(paths)} files)"
        )
    return shard


def image_folder_dataset(
    data_dir: str,
    batch_size: int,
    image_size: int,
    *,
    seed: int = 0,
    shard_index: int = 0,
    num_shards: int = 1,
    num_batches: Optional[int] = None,
) -> Iterator[np.ndarray]:
    """Recursively scan `data_dir` for images; yield shuffled, resized
    [b, 3, image_size, image_size] float32 batches in [-1, 1], reshuffling
    every epoch. Requires PIL (available in this environment)."""
    from PIL import Image

    paths = sorted(
        os.path.join(root, f)
        for root, _, files in os.walk(data_dir)
        for f in files
        if f.lower().endswith(_IMG_EXTS)
    )
    if not paths:
        raise FileNotFoundError(f"no images under {data_dir!r} ({_IMG_EXTS})")
    paths = _list_shard(paths, shard_index, num_shards)
    rng = np.random.default_rng(seed + shard_index)

    def load(path):
        with Image.open(path) as im:
            im = im.convert("RGB").resize(
                (image_size, image_size), Image.BILINEAR
            )
            return _to_chw_float(np.asarray(im))

    produced = 0
    while num_batches is None or produced < num_batches:
        order = rng.permutation(len(paths))
        for start in range(0, len(order) - batch_size + 1, batch_size):
            batch = np.stack(
                [load(paths[i]) for i in order[start : start + batch_size]]
            )
            yield batch
            produced += 1
            if num_batches is not None and produced >= num_batches:
                return


def npy_dataset(
    path: str,
    batch_size: int,
    image_size: Optional[int] = None,
    *,
    seed: int = 0,
    shard_index: int = 0,
    num_shards: int = 1,
    num_batches: Optional[int] = None,
) -> Iterator[np.ndarray]:
    """Batches from .npy shard file(s): `path` is one .npy file or a
    directory of them; each holds [N, H, W, C] or [N, C, H, W] images
    (uint8 or float). Shards are memory-mapped (a CIFAR-scale file loads
    lazily; an ImageNet-scale shard set streams one file at a time),
    distributed across hosts file-wise when there are >= num_shards files,
    row-wise otherwise. Yields [b, 3, H, W] float32 in [-1, 1], shuffling
    rows within each shard pass."""
    if os.path.isdir(path):
        files = sorted(
            os.path.join(path, f)
            for f in os.listdir(path)
            if f.endswith(".npy")
        )
        if not files:
            raise FileNotFoundError(f"no .npy files under {path!r}")
    else:
        files = [path]

    row_shard = len(files) < num_shards
    if not row_shard:
        files = _list_shard(files, shard_index, num_shards)
    rng = np.random.default_rng(seed + shard_index)

    def rows(arr):
        n = arr.shape[0]
        idx = (
            np.arange(shard_index, n, num_shards) if row_shard else np.arange(n)
        )
        return idx[rng.permutation(len(idx))]

    def to_batch(arr, idx):
        x = np.asarray(arr[np.sort(idx)])  # sorted: sequential mmap reads
        if x.ndim != 4:
            raise ValueError(f"expected [N, ...] image array, got {x.shape}")
        if x.shape[-1] in (1, 3, 4) and x.shape[1] not in (1, 3):
            x = np.stack([_to_chw_float(img) for img in x])
        else:  # already [b, C, H, W]
            x = np.stack(
                [_to_chw_float(np.transpose(img, (1, 2, 0))) for img in x]
            )
        if image_size is not None and (
            x.shape[-1] != image_size or x.shape[-2] != image_size
        ):
            raise ValueError(
                f"images are {x.shape[-2]}x{x.shape[-1]}, config wants "
                f"{image_size} (resize .npy shards offline; only the image "
                "folder loader resizes)"
            )
        return x

    produced = 0
    while num_batches is None or produced < num_batches:
        for f in files:
            arr = np.load(f, mmap_mode="r")
            order = rows(arr)
            for start in range(0, len(order) - batch_size + 1, batch_size):
                yield to_batch(arr, order[start : start + batch_size])
                produced += 1
                if num_batches is not None and produced >= num_batches:
                    return


def file_dataset(
    path: str,
    batch_size: int,
    image_size: int,
    **kw,
) -> Iterator[np.ndarray]:
    """Dispatch on what `path` holds: .npy file / directory of .npy shards
    -> npy_dataset; directory of images -> image_folder_dataset."""
    if path.endswith(".npy"):
        return npy_dataset(path, batch_size, image_size, **kw)
    if os.path.isdir(path):
        has_npy = any(f.endswith(".npy") for f in os.listdir(path))
        if has_npy:
            return npy_dataset(path, batch_size, image_size, **kw)
        return image_folder_dataset(path, batch_size, image_size, **kw)
    raise FileNotFoundError(f"{path!r} is neither a .npy file nor a directory")
