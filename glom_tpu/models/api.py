"""The user-facing `Glom` class — the reference's public API, preserved.

Reference parity: `Glom(dim=512, levels=6, image_size=224, patch_size=14,
consensus_self=False, local_consensus_radius=0)` and
`forward(img, iters=None, levels=None, return_all=False)`
(glom_pytorch/glom_pytorch.py:76-83, :103). A reference user switches by
changing the import; the constructor accepts the same kwargs (plus a
`backend` flag per the project north star, and JAX-specific extras: `key`,
`param_dtype`, `compute_dtype`, `remat`).

This is a thin object-oriented shell over the functional core: it owns a
params pytree and memoizes jitted forwards per static signature. All real
logic lives in glom_tpu.models.core, which composes with jit/grad/pjit.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from glom_tpu.models.core import GlomParams, glom_forward, init_glom
from glom_tpu.utils.config import GlomConfig


class Glom:
    def __init__(
        self,
        *,
        dim: int = 512,
        levels: int = 6,
        image_size: int = 224,
        patch_size: int = 14,
        consensus_self: bool = False,
        local_consensus_radius: int = 0,
        backend: str = "tpu",
        key: Optional[jax.Array] = None,
        params: Optional[GlomParams] = None,
        param_dtype=jnp.float32,
        compute_dtype=None,
        remat: bool = False,
    ):
        if backend not in ("tpu", "cpu", "xla"):
            raise ValueError(
                f"backend={backend!r}: this framework is the native XLA backend; "
                "valid values are 'tpu', 'cpu', 'xla' (all compile via XLA to "
                "whatever jax.devices() exposes)"
            )
        self.config = GlomConfig(
            dim=dim,
            levels=levels,
            image_size=image_size,
            patch_size=patch_size,
            consensus_self=consensus_self,
            local_consensus_radius=local_consensus_radius,
        )
        self.compute_dtype = compute_dtype
        self.remat = remat
        if params is None:
            key = key if key is not None else jax.random.PRNGKey(0)
            params = init_glom(key, self.config, param_dtype)
        self.params = params
        self._jitted = {}

    def _forward(self, iters, return_all):
        # Normalize before keying so iters=None and the explicit default share
        # one compiled program; levels-presence is already distinguished by
        # jax.jit's own pytree-structure cache.
        iters = iters if iters is not None else self.config.default_iters
        sig = (iters, return_all)
        if sig not in self._jitted:
            def fn(params, img, levels):
                return glom_forward(
                    params,
                    img,
                    self.config,
                    iters=iters,
                    levels=levels,
                    return_all=return_all,
                    remat=self.remat,
                    compute_dtype=self.compute_dtype,
                )

            self._jitted[sig] = jax.jit(fn)
        return self._jitted[sig]

    def __call__(
        self,
        img: jnp.ndarray,
        iters: Optional[int] = None,
        levels: Optional[jnp.ndarray] = None,
        return_all: bool = False,
    ) -> jnp.ndarray:
        """forward(img, iters=None, levels=None, return_all=False) — the
        reference signature, jit-compiled and memoized per static config."""
        fn = self._forward(iters, return_all)
        return fn(self.params, img, levels)

    # torch-familiar alias
    forward = __call__
