"""The user-facing `Glom` class — the reference's public API, preserved.

Reference parity: `Glom(dim=512, levels=6, image_size=224, patch_size=14,
consensus_self=False, local_consensus_radius=0)` and
`forward(img, iters=None, levels=None, return_all=False)`
(glom_pytorch/glom_pytorch.py:76-83, :103). A reference user switches by
changing the import; the constructor accepts the same kwargs (plus a
`backend` flag per the project north star, and JAX-specific extras: `key`,
`param_dtype`, `compute_dtype`, `remat`).

This is a thin object-oriented shell over the functional core: it owns a
params pytree and memoizes jitted forwards per static signature. All real
logic lives in glom_tpu.models.core, which composes with jit/grad/pjit.

Fast paths through the preserved API (round-1 VERDICT weak #4: the
reference surface only reached the slow path):
  * `backend="tpu"` now actually selects the fused Pallas forward
    (level-major carry + fused grouped-MLP + fused consensus/update) when
    running on a TPU — `use_pallas` overrides explicitly.
  * `mesh=` (a MeshConfig or a ready jax Mesh) + `sp_strategy=` runs the
    forward sharded: ring/halo/ulysses consensus over the mesh's 'seq'
    axis, batch over 'data'. With `use_pallas` (the backend="tpu"
    default), sharded inference rides the MANUAL shard_map forward
    (parallel/manual.make_manual_forward) so the fused kernels survive the
    mesh — round-2 VERDICT weak #5 fixed; `use_pallas=False` keeps the
    GSPMD path (where ulysses' all-to-all decomposition lives).
"""

from __future__ import annotations

import warnings
from typing import Optional, Union

import jax
import jax.numpy as jnp

from glom_tpu.models.core import GlomParams, glom_forward, init_glom
from glom_tpu.utils.config import GlomConfig, MeshConfig


class Glom:
    def __init__(
        self,
        *,
        dim: int = 512,
        levels: int = 6,
        image_size: int = 224,
        patch_size: int = 14,
        consensus_self: bool = False,
        local_consensus_radius: int = 0,
        backend: str = "tpu",
        key: Optional[jax.Array] = None,
        params: Optional[GlomParams] = None,
        param_dtype=jnp.float32,
        compute_dtype=None,
        remat: bool = False,
        use_pallas: Optional[bool] = None,
        mesh: Optional[Union[MeshConfig, object]] = None,
        sp_strategy: str = "none",
        exit_threshold: float = 1e-3,
        auto_max_iters: Optional[int] = None,
        auto_min_iters: int = 1,
    ):
        if backend not in ("tpu", "cpu", "xla"):
            raise ValueError(
                f"backend={backend!r}: this framework is the native XLA backend; "
                "valid values are 'tpu', 'cpu', 'xla' (all compile via XLA to "
                "whatever jax.devices() exposes)"
            )
        self.config = GlomConfig(
            dim=dim,
            levels=levels,
            image_size=image_size,
            patch_size=patch_size,
            consensus_self=consensus_self,
            local_consensus_radius=local_consensus_radius,
        )
        self.compute_dtype = compute_dtype
        self.remat = remat

        if mesh is not None and isinstance(mesh, MeshConfig):
            from glom_tpu.parallel.mesh import make_mesh  # lazy: avoids cycle

            mesh = make_mesh(mesh)
        if mesh is not None:
            seq = mesh.shape.get("seq", 1)
            if self.config.num_patches % seq != 0:
                raise ValueError(
                    f"patches {self.config.num_patches} not divisible by seq "
                    f"axis {seq}"
                )
        self.mesh = mesh
        self.sp_strategy = sp_strategy
        if use_pallas is None:
            # backend="tpu" means "the fast path": fused kernels, on one
            # chip or through the manual shard_map forward under a mesh.
            use_pallas = backend == "tpu"
        if use_pallas and mesh is not None:
            axes = set(getattr(mesh, "axis_names", ()))
            if not {"data", "seq"} <= axes:
                warnings.warn(
                    "use_pallas with a mesh lacking 'data'/'seq' axes: the "
                    "manual fused forward needs the standard axis names; "
                    "falling back to the GSPMD sharded forward without "
                    "Pallas",
                    stacklevel=2,
                )
                use_pallas = False
        self.use_pallas = use_pallas
        if params is None:
            key = key if key is not None else jax.random.PRNGKey(0)
            params = init_glom(key, self.config, param_dtype)
        self.params = params
        # Consensus early-exit policy for iters="auto" (serve/early_exit):
        # exit once no level's agreement moves more than exit_threshold
        # between iterations, bounded by auto_max_iters (None -> 2L).
        self.exit_threshold = exit_threshold
        self.auto_max_iters = auto_max_iters
        self.auto_min_iters = auto_min_iters
        # Device scalar: how many iterations the last iters="auto" call
        # actually ran (read it host-side with int(...) — that syncs).
        self.last_auto_iters: Optional[jax.Array] = None
        self._jitted = {}

    def _auto_forward(self, return_all):
        """iters='auto' route: the early-exit while_loop forward
        (glom_tpu/serve/early_exit). Single-device only — the sharded
        forwards are fixed-length by construction (collectives inside a
        while_loop body would need per-iteration dispatch)."""
        if return_all:
            raise ValueError(
                "iters='auto' is incompatible with return_all=True: the "
                "early exit makes the number of stacked states data-"
                "dependent, which XLA cannot shape"
            )
        if self.mesh is not None:
            raise NotImplementedError(
                "iters='auto' is single-device (serving buckets replicate "
                "the model); drop mesh= or use a fixed iteration count"
            )
        from glom_tpu.serve.early_exit import glom_forward_auto  # lazy

        max_iters = (
            self.auto_max_iters
            if self.auto_max_iters is not None
            else self.config.default_iters
        )
        sig = ("auto", max_iters, self.exit_threshold, self.auto_min_iters)
        if sig not in self._jitted:

            def fn(params, img, levels):
                final, iters_run, _ = glom_forward_auto(
                    params, img, self.config,
                    max_iters=max_iters,
                    threshold=self.exit_threshold,
                    min_iters=self.auto_min_iters,
                    levels=levels,
                    compute_dtype=self.compute_dtype,
                    use_pallas=self.use_pallas,
                )
                return final, iters_run

            self._jitted[sig] = jax.jit(fn)
        jitted = self._jitted[sig]

        def call(params, img, levels):
            final, iters_run = jitted(params, img, levels)
            self.last_auto_iters = iters_run
            return final

        return call

    def _forward(self, iters, return_all):
        if iters == "auto":
            return self._auto_forward(return_all)
        # Normalize before keying so iters=None and the explicit default share
        # one compiled program; levels-presence is already distinguished by
        # jax.jit's own pytree-structure cache.
        iters = iters if iters is not None else self.config.default_iters
        sig = (iters, return_all)
        if self.mesh is not None and self.use_pallas:
            return self._manual_forward(iters, return_all)
        if self.mesh is not None and self.mesh.shape.get("seq", 1) > 1:
            from glom_tpu.utils.compat import HAS_PARTIAL_MANUAL

            if not HAS_PARTIAL_MANUAL:
                # Old-jax fallback (see compat.py): the GSPMD forward would
                # nest a partial-manual consensus shard_map it cannot
                # partition; the fully-manual region runs the same bodies
                # (with the plain-XLA ops when use_pallas is off).
                return self._manual_forward(iters, return_all)
        if sig not in self._jitted:
            consensus_fn = None
            if self.mesh is not None:
                from glom_tpu.parallel.runtime import make_consensus_fn  # lazy

                consensus_fn = make_consensus_fn(
                    self.mesh, self.config, self.sp_strategy
                )

            mesh = self.mesh

            def fn(params, img, levels):
                if mesh is not None:
                    # Pin the batch to the 'data' axis so the mesh kwarg
                    # delivers DP inference even with sp_strategy='none'
                    # (without this, nothing references the mesh and XLA
                    # compiles an unsharded program).
                    from jax.sharding import NamedSharding
                    from jax.sharding import PartitionSpec as P

                    img = jax.lax.with_sharding_constraint(
                        img, NamedSharding(mesh, P("data"))
                    )
                    if levels is not None:
                        levels = jax.lax.with_sharding_constraint(
                            levels, NamedSharding(mesh, P("data", "seq"))
                        )
                return glom_forward(
                    params,
                    img,
                    self.config,
                    iters=iters,
                    levels=levels,
                    return_all=return_all,
                    remat=self.remat,
                    compute_dtype=self.compute_dtype,
                    consensus_fn=consensus_fn,
                    use_pallas=self.use_pallas,
                )

            self._jitted[sig] = jax.jit(fn)
        return self._jitted[sig]

    def _manual_forward(self, iters, return_all):
        """Sharded forward through the manual fused region: the kernels
        survive the mesh (parallel/manual.make_manual_forward). Compiled
        per (iters, return_all, levels-presence)."""
        from glom_tpu.parallel.manual import make_manual_forward  # lazy

        def build(with_levels):
            sig = (iters, return_all, "manual", with_levels)
            if sig not in self._jitted:
                fwd = make_manual_forward(
                    self.mesh,
                    self.config,
                    iters=iters,
                    sp_strategy=self.sp_strategy,
                    compute_dtype=self.compute_dtype,
                    use_pallas=self.use_pallas,
                    return_all=return_all,
                    with_levels=with_levels,
                    remat=self.remat,
                )
                self._jitted[sig] = jax.jit(fwd)
            return self._jitted[sig]

        def fn(params, img, levels):
            if levels is None:
                return build(False)(params, img)
            return build(True)(params, img, levels)

        return fn

    def __call__(
        self,
        img: jnp.ndarray,
        iters: Union[int, str, None] = None,
        levels: Optional[jnp.ndarray] = None,
        return_all: bool = False,
    ) -> jnp.ndarray:
        """forward(img, iters=None, levels=None, return_all=False) — the
        reference signature, jit-compiled and memoized per static config.

        iters="auto" (beyond the reference) runs consensus early exit:
        up to auto_max_iters column updates, stopping once no level's
        agreement moves more than exit_threshold between iterations
        (docs/SERVING.md); the actual count lands on `last_auto_iters`.
        With exit_threshold=0.0 the exit never fires: exactly max_iters
        updates run, and on the reference-layout route (use_pallas=False)
        the output equals the fixed-iters forward BITWISE. With
        use_pallas=True the fixed route runs the fused level-major
        program while the auto route runs the reference-layout body with
        fused FFWs (dense consensus — the while_loop keeps one witness
        across routes), so the two agree to kernel-parity tolerance, not
        bit-for-bit."""
        fn = self._forward(iters, return_all)
        return fn(self.params, img, levels)

    # torch-familiar alias
    forward = __call__
