from glom_tpu.models.api import Glom
from glom_tpu.models.core import (
    GlomParams,
    contribution_divisor,
    glom_forward,
    init_glom,
    update_step,
)

__all__ = [
    "Glom",
    "GlomParams",
    "contribution_divisor",
    "glom_forward",
    "init_glom",
    "update_step",
]
