"""The functional GLOM core: parameter init, one column-update step, and the
scanned T-iteration forward.

Reference parity: Glom.__init__ / Glom.forward (glom_pytorch/glom_pytorch.py:
75-152); the full behavioral contract is SURVEY.md §3.2 and is locked by
tests/test_model.py against the NumPy oracle. Where the reference runs T
eager iterations (one CUDA kernel launch per op), this core is a single
`lax.scan` body compiled once by XLA — the loop is fused, weights stay
resident, and the T iterations pipeline on-chip.

Design notes (TPU-first, not a port):
  * Pure functions over a `GlomParams` pytree — jit/grad/vmap/pjit compose.
  * `iters` is a static scan length (no data-dependent control flow).
  * `consensus_fn` is injectable so the dense op can be swapped for the
    Pallas blockwise kernel or the ring/Ulysses sharded forms without
    touching the core update equation.
  * `remat=True` wraps the scan body in jax.checkpoint — BASELINE config 5's
    "ckpt over iters" — trading recompute for O(1) activation memory in T.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from einops import rearrange

from glom_tpu.ops.consensus import build_local_mask, consensus_attention
from glom_tpu.ops.ffw import GroupedFFWParams, grouped_ffw, init_grouped_ffw
from glom_tpu.ops.patch import LinearParams, image_to_tokens, init_linear
from glom_tpu.utils.config import GlomConfig
from glom_tpu.utils.helpers import default, exists

ConsensusFn = Callable[[jnp.ndarray], jnp.ndarray]
FFWFn = Callable[[GroupedFFWParams, jnp.ndarray], jnp.ndarray]


def _on_tpu() -> bool:
    """Seam for the dispatch policy's platform check (tests patch this to
    exercise the TPU-side routing on the CPU mesh)."""
    return jax.devices()[0].platform == "tpu"


class GlomParams(NamedTuple):
    """Learnable state. Mirrors the reference module tree (SURVEY.md §3.1)."""

    token_embed: LinearParams  # Linear(p*p*c -> d)        (reference :88-91)
    pos_emb: jnp.ndarray  # [n, d] learned position table   (reference :92)
    init_levels: jnp.ndarray  # [L, d] learned column init  (reference :95)
    bottom_up: GroupedFFWParams  # groups = L               (reference :98)
    top_down: GroupedFFWParams  # groups = L - 1            (reference :99)


def init_glom(key: jax.Array, cfg: GlomConfig, dtype=jnp.float32) -> GlomParams:
    k_tok, k_pos, k_lvl, k_bu, k_td = jax.random.split(key, 5)
    return GlomParams(
        token_embed=init_linear(k_tok, cfg.patch_dim, cfg.dim, dtype),
        pos_emb=jax.random.normal(k_pos, (cfg.num_patches, cfg.dim), dtype),
        init_levels=jax.random.normal(k_lvl, (cfg.levels, cfg.dim), dtype),
        bottom_up=init_grouped_ffw(k_bu, cfg.levels, cfg.dim, cfg.mult, dtype),
        top_down=init_grouped_ffw(k_td, cfg.levels - 1, cfg.dim, cfg.mult, dtype),
    )


def contribution_divisor(levels: int, dtype=jnp.float32) -> jnp.ndarray:
    """[L, 1] per-level mean divisor: 4 contributions everywhere except the
    top level, which has no top-down input and divides by 3 (reference
    :121-122 — a naive mean-of-stack is wrong at the top)."""
    div = np.full((levels, 1), 4.0, dtype=np.float64)
    div[-1] = 3.0
    return jnp.asarray(div, dtype)


def update_step(
    params: GlomParams,
    levels: jnp.ndarray,
    bottom: jnp.ndarray,
    pos: jnp.ndarray,
    divisor: jnp.ndarray,
    *,
    consensus_fn: ConsensusFn,
    ffw_fn: FFWFn = grouped_ffw,
) -> jnp.ndarray:
    """One column update: the mean of (previous value, bottom-up, top-down,
    consensus). The §3.2 loop body (reference :124-140).

    levels: [b, n, L, d]   bottom: [b, n, 1, d]   pos: [1, n, 1, d]
    """
    with_input = jnp.concatenate([bottom, levels], axis=-2)  # [b, n, L+1, d]
    # Bottom-up sees (image tokens, levels 1..L-1) -> update for levels 1..L:
    # level 1 re-reads the RAW tokens every iteration (reference :127).
    with jax.named_scope("bottom_up"):
        bottom_up_out = ffw_fn(params.bottom_up, with_input[..., :-1, :])
    # Top-down sees levels 2..L with the positional embedding injected HERE
    # and only here (reference :129); produces updates for levels 1..L-1,
    # zero-padded at the top (reference :130).
    with jax.named_scope("top_down"):
        top_down_out = ffw_fn(params.top_down, with_input[..., 2:, :] + pos)
        top_down_out = jnp.pad(top_down_out, ((0, 0), (0, 0), (0, 1), (0, 0)))
    with jax.named_scope("consensus"):
        consensus = consensus_fn(levels)
    with jax.named_scope("mean_update"):
        new_levels = (levels + bottom_up_out + top_down_out + consensus) / divisor
    return new_levels.astype(levels.dtype)


def glom_forward(
    params: GlomParams,
    img: jnp.ndarray,
    cfg: GlomConfig,
    *,
    iters: Optional[int] = None,
    levels: Optional[jnp.ndarray] = None,
    return_all: bool = False,
    remat: bool = False,
    compute_dtype=None,
    consensus_fn: Optional[ConsensusFn] = None,
    use_pallas: bool = False,
    unroll: bool = False,
) -> jnp.ndarray:
    """The T-iteration GLOM forward (reference :103-152).

    img: [b, c, H, W] -> [b, n, L, d], or [T+1, b, n, L, d] with return_all
    (T+1 includes the INITIAL state, reference :119/:140/:143).

    `levels` may be passed in to continue from a previous call (the README
    temporal/video recipe — detach between frames with lax.stop_gradient).
    `iters`/`return_all`/`remat` are static under jit.

    use_pallas=True selects the fully-fused TPU path: a LEVEL-MAJOR
    [L, b, n, d] scan carry (zero layout transposes between ops), the
    Pallas fused grouped-MLP for both FFWs, and the Pallas blockwise
    consensus+mean kernel (kernels/consensus_update.py) for the rest of
    the update. Auto-falls back to XLA ops off-TPU / unsupported shapes.
    Leave False inside GSPMD-sharded model-parallel regions — the custom
    calls have no partitioning rule for sharded weights.

    unroll=True unrolls the scan into straight-line code (identical math;
    see TrainConfig.scan_unroll for the trade-off).
    """
    T = default(iters, cfg.default_iters)

    if use_pallas and consensus_fn is None:
        if compute_dtype is not None:
            params = jax.tree_util.tree_map(
                lambda t: t.astype(compute_dtype), params
            )
            img = img.astype(compute_dtype)
            if exists(levels):
                levels = levels.astype(compute_dtype)
        return _glom_forward_fused(
            params, img, cfg, iters=T, levels_in=levels,
            return_all=return_all, remat=remat, unroll=unroll,
        )

    if use_pallas:
        # Custom consensus_fn + Pallas FFWs: reference-layout path with the
        # fused MLP swapped in (used by sharded per-shard bodies).
        from glom_tpu.kernels import fused_grouped_ffw

        ffw_fn: FFWFn = fused_grouped_ffw
    else:
        ffw_fn = grouped_ffw

    if consensus_fn is None:
        local_mask = build_local_mask(cfg.num_patches_side, cfg.local_consensus_radius)
        consensus_fn = partial(
            consensus_attention,
            attend_self=cfg.consensus_self,
            local_mask=local_mask,
        )

    # Cast params and inputs ONCE, outside the scan — casting inside the body
    # would re-run (and re-run again under remat) every iteration.
    if compute_dtype is not None:
        params = jax.tree_util.tree_map(lambda t: t.astype(compute_dtype), params)
        img = img.astype(compute_dtype)
        if exists(levels):
            levels = levels.astype(compute_dtype)

    with jax.named_scope("image_to_tokens"):
        tokens = image_to_tokens(params.token_embed, img, cfg.patch_size)  # [b,n,d]
    b, n, d = tokens.shape
    pos = rearrange(params.pos_emb, "n d -> 1 n 1 d")
    bottom = rearrange(tokens, "b n d -> b n 1 d")

    if not exists(levels):
        levels = jnp.broadcast_to(
            params.init_levels[None, None], (b, n, cfg.levels, d)
        ).astype(tokens.dtype)

    divisor = contribution_divisor(cfg.levels, jnp.float32)

    def body(carry, _):
        new = update_step(
            params, carry, bottom, pos, divisor,
            consensus_fn=consensus_fn, ffw_fn=ffw_fn,
        )
        return new, (new if return_all else None)

    if remat:
        body = jax.checkpoint(body)

    final, stacked = jax.lax.scan(body, levels, None, length=T, unroll=unroll)

    if return_all:
        return jnp.concatenate([levels[None], stacked], axis=0)  # [T+1, b, n, L, d]
    return final


def resolve_vjp_path(
    cfg: GlomConfig,
    b: int,
    iters: int,
    *,
    remat: bool = False,
    use_pallas: bool = False,
    itemsize: int = 2,
    custom_consensus: bool = False,
    return_all: bool = False,
    scan_only: bool = False,
    assume_on_tpu: bool = False,
) -> str:
    """THE single resolution source for which backward implementation a
    training forward at these static shapes will use. Both the dispatch
    (_use_fused_loop) and the trainers' metric logging call this, so a run
    can never train on a different backward than its records claim (the
    same discipline effective_sp_strategy applies to collectives).

    Returns one of:
      'fused_loop'     — the hand-rolled whole-loop VJP (kernels/fused_loop)
      'scan_blockwise' — lax.scan forward, Pallas blockwise consensus bwd
      'scan_dense'     — lax.scan forward, dense XLA/stats consensus bwd

    scan_only=True excludes the fused loop regardless of eligibility — the
    manual TP shard bodies (parallel/manual.py, mp > 1) scan the kernels
    directly and never dispatch to the whole-loop VJP.

    assume_on_tpu=True bypasses only the platform check (the CPU
    interpret-mode shard tests drive the real dispatch policy — including
    the GLOM_CONSENSUS_BWD gate — without hardware).
    """
    import os

    from glom_tpu.kernels.consensus_update import _use_blockwise_bwd
    from glom_tpu.kernels.fused_loop import loop_supported

    n, d, L = cfg.num_patches, cfg.dim, cfg.levels
    if not use_pallas or custom_consensus or not (assume_on_tpu or _on_tpu()):
        return "scan_dense"
    env_auto = os.environ.get("GLOM_CONSENSUS_BWD", "auto") == "auto"
    if (
        not scan_only
        and not return_all
        and b >= 8
        and env_auto
        and loop_supported(
            L, b, n, d, d * cfg.mult, itemsize, iters, n, remat
        )
    ):
        return "fused_loop"
    blockwise = _use_blockwise_bwd(
        (L, b, n, d), cfg.num_patches_side,
        float(cfg.local_consensus_radius), "auto", itemsize,
    )
    return "scan_blockwise" if blockwise else "scan_dense"


def _use_fused_loop(
    params: GlomParams, cfg: GlomConfig, b: int, n: int, d: int,
    iters: int, levels_in, return_all: bool, remat: bool,
) -> bool:
    """Dispatch to the hand-rolled whole-loop VJP (kernels/fused_loop.py)
    on the flagship training regime: TPU, final-state-only, the
    single-tile consensus row, tileable FFW shapes, and the measured
    batched regime where the in-VMEM backward wins (B >= 8 — see
    consensus_update._use_blockwise_bwd's crossover table). remat=True
    rides the loop too (round 5): the VJP's recompute-per-iteration mode
    keeps the glue-free structure at BASELINE config 5's
    checkpoint-over-iters regime. The GLOM_CONSENSUS_BWD=dense override
    disables it so bench A/B comparisons still reach the dense VJP.

    Thin shape-consistency gate over resolve_vjp_path (the single
    resolution source — the non-auto-env / b<8 / return_all policy lives
    THERE): this checks only what requires the actual params and tokens
    (dtype agreement, pos-emb/config coherence)."""
    if exists(levels_in) and levels_in.dtype != params.init_levels.dtype:
        return False
    if (n, d) != (cfg.num_patches, cfg.dim) or params.pos_emb.shape[0] != n:
        return False
    if params.bottom_up.w1.shape[-1] != d * cfg.mult:
        return False
    return (
        resolve_vjp_path(
            cfg, b, iters, remat=remat, use_pallas=True,
            itemsize=params.init_levels.dtype.itemsize,
            return_all=return_all,
        )
        == "fused_loop"
    )


def _glom_forward_fused(
    params: GlomParams,
    img: jnp.ndarray,
    cfg: GlomConfig,
    *,
    iters: int,
    levels_in: Optional[jnp.ndarray],
    return_all: bool,
    remat: bool,
    unroll: bool = False,
) -> jnp.ndarray:
    """The fused TPU forward: level-major carry + Pallas kernels.

    Same behavioral contract as the reference path (locked by
    tests/test_model.py::TestPallasParity); the differences are purely
    physical: the scan carry is [L, b, n, d] so the grouped-FFW batched
    matmuls and the per-(level, image) consensus tiles are layout-native,
    and the whole 4-way mean update runs inside the consensus kernel's
    epilogue instead of as separate XLA HBM sweeps.
    """
    from glom_tpu.kernels import fused_consensus_update
    from glom_tpu.kernels.grouped_mlp import fused_grouped_ffw_lm

    with jax.named_scope("image_to_tokens"):
        tokens = image_to_tokens(params.token_embed, img, cfg.patch_size)
    b, n, d = tokens.shape
    L = cfg.levels
    tokens_lm = tokens[None]  # [1, b, n, d]

    if exists(levels_in):
        # Keep the caller's carry dtype (the reference path's scan carry is
        # new.astype(levels.dtype) — the temporal recipe must see identical
        # dtype behavior under both flags).
        levels_lm = jnp.transpose(levels_in, (2, 0, 1, 3))
    else:
        levels_lm = jnp.broadcast_to(
            params.init_levels[:, None, None], (L, b, n, d)
        ).astype(tokens.dtype)

    if _use_fused_loop(params, cfg, b, n, d, iters, levels_in, return_all, remat):
        from glom_tpu.kernels.fused_loop import fused_glom_loop

        final = fused_glom_loop(
            params.bottom_up, params.top_down, params.pos_emb, tokens,
            levels_lm, iters, cfg.num_patches_side,
            float(cfg.local_consensus_radius), cfg.consensus_self, False,
            remat,
        )
        return jnp.transpose(final, (1, 2, 0, 3))  # [b, n, L, d]

    def body(carry, _):
        lv = carry
        # Bottom-up input: (image tokens, levels 1..L-1) — level 1 re-reads
        # the RAW tokens every iteration (reference :127).
        with jax.named_scope("bottom_up"):
            bu_in = jnp.concatenate([tokens_lm, lv[:-1]], axis=0)
            bu_out = fused_grouped_ffw_lm(
                params.bottom_up, bu_in.reshape(L, b * n, d)
            ).reshape(L, b, n, d)
        # Top-down input: levels 2..L with pos-emb injected HERE only
        # (reference :129); the top level's zero pad + the 4-vs-3 divisor
        # live in the consensus kernel's epilogue. The pos addend folds
        # into the kernel's tile loads (add=) — the [L-1, b, n, d] sum
        # never materializes on the fused path.
        with jax.named_scope("top_down"):
            td_out = fused_grouped_ffw_lm(
                params.top_down,
                lv[1:].reshape(L - 1, b * n, d),
                add=params.pos_emb,
            ).reshape(L - 1, b, n, d)
        with jax.named_scope("consensus_update"):
            new = fused_consensus_update(
                lv, bu_out, td_out,
                side=cfg.num_patches_side,
                radius=float(cfg.local_consensus_radius),
                attend_self=cfg.consensus_self,
            )
        return new, (new if return_all else None)

    if remat:
        body = jax.checkpoint(body)

    final, stacked = jax.lax.scan(body, levels_lm, None, length=iters, unroll=unroll)

    if return_all:
        all_lm = jnp.concatenate([levels_lm[None], stacked], axis=0)
        return jnp.transpose(all_lm, (0, 2, 3, 1, 4))  # [T+1, b, n, L, d]
    return jnp.transpose(final, (1, 2, 0, 3))  # [b, n, L, d]
